"""Runtime lock-order sanitizer — the dynamic half of the CCY plane.

The static CCY pass (``analysis/concurrency.py``) proves properties of the
lock-acquisition-order graph it can SEE in source; this module watches the
orders that actually happen.  Every lock the threaded modules create through
:func:`make_lock` / :func:`make_condition` becomes, in debug mode, an
:class:`OrderedLock` that records per-thread acquisition stacks into a
process-wide :class:`LockOrderRegistry`:

- acquiring B while holding A books the directed edge ``A -> B`` (with the
  acquiring site, first observation wins);
- an acquisition whose reverse edge ``B -> A`` has already been observed —
  by ANY thread, at any earlier time — is a **lock-order inversion**: the
  two orders can interleave into a deadlock even if this run got lucky.
  The violation is booked *before* the blocking acquire, so in strict mode
  the sanitizer trips where the deadlock would otherwise hang;
- :func:`validate_lock_order` additionally runs cycle detection over the
  accumulated graph, catching multi-lock cycles (A->B, B->C, C->A) no
  single acquisition pre-check pairs up — the cycles the AST cannot see
  (orders established through data flow, callbacks, or timing).

Every violation is booked to the
``mmlspark_lock_order_violations_total{kind}`` counter family and to the
event ring (``core.logging.log_event``), which the flight recorder dumps —
a violation under a chaos drill leaves a debuggable artifact even when the
process dies next.

Enabling: ``MMLSPARK_TPU_LOCK_SANITIZER=1`` (record + book violations),
``=strict`` (additionally raise :class:`LockOrderViolation` at the
offending acquire — how the tier-1 inversion drill proves the trip happens
before the hang), ``=0``/unset (off: :func:`make_lock` returns a plain
``threading.Lock`` — zero overhead in production).  The tier-1 conftest
exports ``=1`` by default so every threaded test doubles as a deadlock
drill.  Measured overhead of the wrapper: an uncontended acquire/release
pair goes from ~0.17 us to ~1.4 us (~8x relative, ~1.2 us absolute) —
noise against the batch-/IO-scale work the package holds these locks
around, and tier-1 wall time is unchanged within run-to-run variance
(see docs/STATIC_ANALYSIS.md for the measurement).

The env knob is read at LOCK CREATION time: modules built before the knob
flips keep the locks they were built with, so a long-lived server never
changes behaviour mid-flight.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["LockOrderRegistry", "LockOrderViolation", "OrderedLock",
           "SANITIZER_ENV", "get_lock_registry", "make_condition",
           "make_lock", "make_rlock", "sanitizer_mode",
           "validate_lock_order"]

#: env knob: "" / "0" = off, "1"/"true"/"on" = record, "strict" = raise
SANITIZER_ENV = "MMLSPARK_TPU_LOCK_SANITIZER"

#: violations kept per registry (bounded: a pathological loop must not OOM
#: the process it is diagnosing); the counter family keeps exact totals
_MAX_VIOLATIONS = 256

#: acquiring-site frames kept per edge/violation (wrapper frames skipped)
_STACK_FRAMES = 3


def sanitizer_mode() -> str:
    """-> "off" | "record" | "strict" from the env knob."""
    raw = os.environ.get(SANITIZER_ENV, "").strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return "off"
    if raw == "strict":
        return "strict"
    return "record"


class LockOrderViolation(RuntimeError):
    """Raised (strict mode only) at an acquire whose order inverts an
    already-observed order — the point where the deadlock would form."""


def _site(skip: int = 3) -> List[str]:
    """Short acquiring-site stack: ``file:line in fn`` rows, innermost
    last, wrapper/registry frames skipped."""
    rows = []
    for f in traceback.extract_stack()[:-skip][-_STACK_FRAMES:]:
        rows.append(f"{f.filename.rsplit(os.sep, 1)[-1]}:{f.lineno} "
                    f"in {f.name}")
    return rows


class _Violation:
    __slots__ = ("kind", "chain", "thread", "stack", "message")

    def __init__(self, kind: str, chain: Sequence[str], thread: str,
                 stack: Sequence[str], message: str):
        self.kind = kind          # "inversion" | "cycle"
        self.chain = list(chain)  # the locks in conflict, in order
        self.thread = thread
        self.stack = list(stack)
        self.message = message

    def as_dict(self) -> Dict[str, object]:
        return {"kind": self.kind, "chain": self.chain,
                "thread": self.thread, "stack": self.stack,
                "message": self.message}

    def __repr__(self) -> str:  # pragma: no cover - diagnostics only
        return f"<LockOrder {self.kind} {' -> '.join(self.chain)}>"


class LockOrderRegistry:
    """Process-wide observed-order graph + per-thread held-lock stacks.

    One default instance backs :func:`make_lock`; tests that deliberately
    invert orders construct their own so the global tier-1 registry stays
    clean (the suite asserts zero violations on it).
    """

    def __init__(self, strict: Optional[bool] = None,
                 book: bool = True):
        self._strict = strict
        self._book = book
        self._mu = threading.Lock()   # guards the graph; never held while
        #                               booking or raising (no I/O under it)
        #: (holder, acquired) -> first-observed acquiring site
        self._edges: Dict[Tuple[str, str], Dict[str, object]] = {}
        self._violations: List[_Violation] = []
        self._total = 0
        #: per-thread dedup: a (pair) booked once per thread, not per call
        self._tls = threading.local()

    # ------------------------------------------------------------ per-thread
    def _stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _reported(self) -> Set[frozenset]:
        rep = getattr(self._tls, "reported", None)
        if rep is None:
            rep = self._tls.reported = set()
        return rep

    def held(self) -> List[str]:
        """Lock names held by the calling thread, outermost first."""
        return list(self._stack())

    # --------------------------------------------------------------- events
    def note_acquiring(self, name: str) -> None:
        """Pre-acquire check: books (and in strict mode raises) on an
        inversion BEFORE the caller blocks on the inner lock — the drill
        trips where the deadlock would otherwise hang."""
        held = self._stack()
        if not held or name in held:   # re-entrant RLock hold: no new edge
            return
        inverted: List[Tuple[str, Dict[str, object]]] = []
        with self._mu:
            for h in held:
                rev = self._edges.get((name, h))
                if rev is not None:
                    inverted.append((h, rev))
        for h, rev in inverted:
            pair = frozenset((h, name))
            if pair in self._reported():
                continue               # once per (pair, thread)
            self._reported().add(pair)
            v = _Violation(
                kind="inversion", chain=[h, name],
                thread=threading.current_thread().name, stack=_site(),
                message=(
                    f"lock-order inversion: acquiring {name!r} while "
                    f"holding {h!r}, but the opposite order "
                    f"{name!r} -> {h!r} was observed at "
                    f"{rev.get('stack', ['?'])[-1]} "
                    f"(thread {rev.get('thread', '?')}) — the two "
                    "interleavings deadlock"))
            self._record(v)
            strict = self._strict if self._strict is not None \
                else sanitizer_mode() == "strict"
            if strict:
                raise LockOrderViolation(v.message)

    def note_acquired(self, name: str) -> None:
        """Post-acquire: push the hold and book the order edges."""
        held = self._stack()
        if held and name not in held:
            site = None
            with self._mu:
                for h in held:
                    if (h, name) not in self._edges:
                        if site is None:
                            site = {
                                "stack": _site(),
                                "thread": threading.current_thread().name,
                            }
                        self._edges[(h, name)] = site
        held.append(name)

    def note_released(self, name: str) -> None:
        """Pop the (most recent) hold of ``name`` — releases may legally
        happen out of LIFO order (Condition.wait releases mid-block)."""
        held = self._stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # ------------------------------------------------------------- booking
    def _record(self, v: _Violation) -> None:
        with self._mu:
            self._total += 1
            if len(self._violations) < _MAX_VIOLATIONS:
                self._violations.append(v)
        if not self._book:
            return
        # lazy, guarded imports: utils must stay importable without the
        # observability plane, and booking must never mask the violation
        try:
            from ..observability.metrics import get_registry
            get_registry().counter(
                "mmlspark_lock_order_violations_total",
                "lock-order sanitizer violations by kind "
                "(inversion = pre-acquire pair trip, cycle = "
                "validate_lock_order graph cycle)",
                labels=("kind",)).inc(kind=v.kind)
        except Exception:  # noqa: BLE001 — diagnostics never take the path down
            pass
        try:
            from ..core.logging import log_event
            log_event({"event": "lock_order_violation", **v.as_dict()})
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------ inspection
    def edges(self) -> Dict[Tuple[str, str], Dict[str, object]]:
        with self._mu:
            return dict(self._edges)

    def violations(self) -> List[_Violation]:
        with self._mu:
            return list(self._violations)

    @property
    def total_violations(self) -> int:
        with self._mu:
            return self._total

    def validate(self, static_edges: Optional[Sequence[Tuple[str, str]]]
                 = None) -> List[_Violation]:
        """Cycle-check the observed graph (optionally merged with the
        static CCY001 edge set) and return NEW violations found.

        A cycle here means a set of locks whose observed acquisition
        orders cannot be serialized — a deadlock waiting for the right
        interleaving.  Pair inversions are already booked at acquire time;
        this pass catches the longer cycles (and the static x dynamic
        composites neither half sees alone)."""
        with self._mu:
            graph: Dict[str, Set[str]] = {}
            for (a, b) in self._edges:
                graph.setdefault(a, set()).add(b)
        for (a, b) in static_edges or ():
            graph.setdefault(a, set()).add(b)
        new: List[_Violation] = []
        for cycle in _find_cycles(graph):
            v = _Violation(
                kind="cycle", chain=cycle,
                thread=threading.current_thread().name, stack=_site(skip=2),
                message="lock-order cycle over observed acquisitions: "
                        + " -> ".join(cycle + cycle[:1]))
            self._record(v)
            new.append(v)
        return new


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via SCC decomposition (iterative Tarjan): every
    non-trivial SCC is reported once, as its sorted member list — stable
    output for tests and dedup, without enumerating each rotation."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
    return sccs


class OrderedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper that reports every
    acquire/release to a :class:`LockOrderRegistry` under a stable NAME
    (the identity the order graph speaks — ``"Owner._attr"`` by
    convention, matching the static CCY node naming)."""

    __slots__ = ("name", "_inner", "_registry")

    def __init__(self, name: str, registry: LockOrderRegistry,
                 reentrant: bool = False):
        self.name = name
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._registry = registry

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._registry.note_acquiring(self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._registry.note_acquired(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._registry.note_released(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - diagnostics only
        return f"<OrderedLock {self.name} {self._inner!r}>"


_default_registry: Optional[LockOrderRegistry] = None
_default_registry_mu = threading.Lock()


def get_lock_registry() -> LockOrderRegistry:
    """The process-wide registry behind :func:`make_lock` (created on
    first use; strictness re-read from the env at each violation so a
    test can flip record->strict without rebuilding every lock)."""
    global _default_registry
    reg = _default_registry
    if reg is None:
        with _default_registry_mu:
            if _default_registry is None:
                _default_registry = LockOrderRegistry(strict=None)
            reg = _default_registry
    return reg


def make_lock(name: str,
              registry: Optional[LockOrderRegistry] = None):
    """A lock for ``with``/acquire/release use.  Sanitizer off: a plain
    ``threading.Lock`` (zero overhead).  On: an :class:`OrderedLock`
    reporting under ``name``."""
    if sanitizer_mode() == "off" and registry is None:
        return threading.Lock()
    return OrderedLock(name, registry or get_lock_registry())


def make_rlock(name: str,
               registry: Optional[LockOrderRegistry] = None):
    """Re-entrant variant of :func:`make_lock`."""
    if sanitizer_mode() == "off" and registry is None:
        return threading.RLock()
    return OrderedLock(name, registry or get_lock_registry(),
                       reentrant=True)


def make_condition(name: str,
                   registry: Optional[LockOrderRegistry] = None
                   ) -> threading.Condition:
    """A ``threading.Condition`` whose underlying lock is sanitized: the
    wait-time release/re-acquire cycles show up in the order graph exactly
    as they happen (a wait drops the hold; waking re-books it against
    whatever else the thread then holds)."""
    return threading.Condition(make_lock(name, registry))


def validate_lock_order(static_edges: Optional[Sequence[Tuple[str, str]]]
                        = None) -> List[_Violation]:
    """Cycle-check the default registry's observed graph (merged with an
    optional static edge set — pass the CCY001 graph to compose the two
    halves) and return newly found violations.  Call at drain/test
    teardown: an empty return means every observed order serializes."""
    return get_lock_registry().validate(static_edges)
