"""StopWatch — nested wall-time decomposition.

Reference: ``core/utils/StopWatch.scala`` as used by VW diagnostics
(``VowpalWabbitBase.scala:294-329``) to split training time into
ingest/learn/multipass percentages.
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict


class StopWatch:
    def __init__(self):
        self._totals: Dict[str, float] = {}
        self._t0 = time.perf_counter()

    @contextlib.contextmanager
    def measure(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._totals[name] = self._totals.get(name, 0.0) + (time.perf_counter() - start)

    def elapsed(self, name: str) -> float:
        return self._totals.get(name, 0.0)

    def total_elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def percentages(self) -> Dict[str, float]:
        total = self.total_elapsed()
        return {k: 100.0 * v / total for k, v in self._totals.items()} if total > 0 else {}

    def as_dict(self) -> Dict[str, float]:
        return dict(self._totals)
