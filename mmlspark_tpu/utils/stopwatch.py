"""StopWatch — nested wall-time decomposition, now a facade over spans.

Reference: ``core/utils/StopWatch.scala`` as used by VW diagnostics
(``VowpalWabbitBase.scala:294-329``) to split training time into
ingest/learn/multipass percentages.

Each ``measure(name)`` block opens a ``stopwatch.<name>`` span on the
observability layer, so the same timings that feed ``percentages()`` also
land in the metrics registry (``mmlspark_span_seconds{name=...}``) and the
logging event ring — the three telemetry fragments share one clock path.
The public API is unchanged; ``emit_spans=False`` opts out for callers that
only want the local totals.
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict


class StopWatch:
    def __init__(self, emit_spans: bool = True):
        self._totals: Dict[str, float] = {}
        self._t0 = time.perf_counter()
        self._emit_spans = emit_spans

    @contextlib.contextmanager
    def measure(self, name: str):
        start = time.perf_counter()
        try:
            if self._emit_spans:
                from ..observability.tracing import trace_span
                with trace_span(f"stopwatch.{name}"):
                    yield
            else:
                yield
        finally:
            self._totals[name] = self._totals.get(name, 0.0) + (time.perf_counter() - start)

    def elapsed(self, name: str) -> float:
        return self._totals.get(name, 0.0)

    def total_elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def percentages(self) -> Dict[str, float]:
        total = self.total_elapsed()
        return {k: 100.0 * v / total for k, v in self._totals.items()} if total > 0 else {}

    def as_dict(self) -> Dict[str, float]:
        return dict(self._totals)
