from .cluster import ClusterUtil
from .stopwatch import StopWatch
from .fault import retry_with_timeout, with_retries
from .streams import using

__all__ = ["ClusterUtil", "StopWatch", "retry_with_timeout", "with_retries", "using"]
