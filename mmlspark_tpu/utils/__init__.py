from .cluster import ClusterUtil
from .concurrency import (LockOrderRegistry, LockOrderViolation, OrderedLock,
                          make_condition, make_lock, make_rlock,
                          sanitizer_mode, validate_lock_order)
from .stopwatch import StopWatch
from .resilience import (CircuitBreaker, CircuitOpenError, Deadline,
                         DeadlineExceeded, FakeClock, current_deadline,
                         deadline_scope, retry_with_timeout, with_retries)
from .streams import using

__all__ = ["ClusterUtil", "StopWatch", "retry_with_timeout", "with_retries",
           "using", "CircuitBreaker", "CircuitOpenError", "Deadline",
           "DeadlineExceeded", "FakeClock", "current_deadline",
           "deadline_scope", "LockOrderRegistry", "LockOrderViolation",
           "OrderedLock", "make_condition", "make_lock", "make_rlock",
           "sanitizer_mode", "validate_lock_order"]
