from .cluster import ClusterUtil
from .stopwatch import StopWatch
from .resilience import (CircuitBreaker, CircuitOpenError, Deadline,
                         DeadlineExceeded, FakeClock, current_deadline,
                         deadline_scope, retry_with_timeout, with_retries)
from .streams import using

__all__ = ["ClusterUtil", "StopWatch", "retry_with_timeout", "with_retries",
           "using", "CircuitBreaker", "CircuitOpenError", "Deadline",
           "DeadlineExceeded", "FakeClock", "current_deadline",
           "deadline_scope"]
