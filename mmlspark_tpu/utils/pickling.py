"""Pickle with closure support — cloudpickle when available, stdlib otherwise.

Used for ComplexParam payloads that are functions or locally-defined modules
(the reference serializes UDFs and model graphs through Spark's closure
serializer; cloudpickle is the Python analogue).
"""
from __future__ import annotations

try:
    import cloudpickle as _impl
except ImportError:  # pragma: no cover
    import pickle as _impl


def dump(obj, fileobj) -> None:
    _impl.dump(obj, fileobj)


def dumps(obj) -> bytes:
    return _impl.dumps(obj)


def load(fileobj):
    import pickle
    return pickle.load(fileobj)  # cloudpickle output is stdlib-loadable


def loads(data: bytes):
    import pickle
    return pickle.loads(data)
