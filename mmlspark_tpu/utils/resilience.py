"""Resilience primitives — circuit breakers, deadlines, budget-aware retries.

Grown out of ``utils/fault.py`` (reference:
``core/utils/FaultToleranceUtils.scala`` ``retryWithTimeout`` guarding
native/network init, and the exponential-backoff loop in
``TrainUtils.networkInit``).  The MMLSpark papers frame serving and the
cognitive layer as production web services; this module supplies the failure
machinery those boundaries need:

- ``CircuitBreaker`` — closed/open/half-open with a rolling failure window,
  so a dead dependency is rejected fast instead of timing out per call;
- ``Deadline`` — a request budget carried via contextvar from admission
  through batch scoring, HTTP fan-out, and retries, so no retry loop ever
  overshoots what the caller is still willing to wait for;
- budget-aware ``with_retries`` / ``retry_with_timeout`` (the fault.py
  originals, now deadline-clipped);
- ``Watchdog`` — arm/heartbeat stall detection around device dispatches
  that can hang forever (a wedged TPU relay), so a *slow* failure is
  surfaced and recovered like a crash instead of wedging a worker;
- ``RetryBudget`` — token-bucket bound on retry amplification, so a full
  outage degrades to sheds instead of a fleet-wide retry storm.

Every primitive takes an injectable ``clock`` (and ``sleep`` where it
waits), so the chaos suite (``testing/chaos.py`` + ``tests/
test_resilience.py``) drives all state transitions deterministically —
no wall-clock sleeps, no flakes.
"""
from __future__ import annotations

import collections
import concurrent.futures
import signal as _signal
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Callable, Deque, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


# ---------------------------------------------------------------------------
# clocks
# ---------------------------------------------------------------------------

class FakeClock:
    """Deterministic manual clock for tests: ``now()``/``__call__`` read the
    time, ``sleep``/``advance`` move it.  Thread-safe so server threads and
    the test driver can share one instance."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)
        self._lock = threading.Lock()

    def __call__(self) -> float:
        with self._lock:
            return self._t

    now = __call__

    def sleep(self, seconds: float) -> None:
        with self._lock:
            self._t += max(0.0, float(seconds))

    advance = sleep


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

class DeadlineExceeded(TimeoutError):
    """The caller's remaining budget reached zero."""


class Deadline:
    """An absolute point (on an injectable monotonic clock) after which work
    on behalf of this request is pointless.  Carried through call stacks via
    ``deadline_scope`` so retries/timeouts anywhere below clip themselves to
    ``remaining()`` instead of their own configured maxima."""

    __slots__ = ("expires_at", "clock")

    def __init__(self, expires_at: float, clock: Callable[[], float] = time.monotonic):
        self.expires_at = float(expires_at)
        self.clock = clock

    @classmethod
    def after(cls, seconds: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + float(seconds), clock)

    def remaining(self) -> float:
        return self.expires_at - self.clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def clip(self, timeout_s: float) -> float:
        """A timeout that never overshoots the remaining budget (>= 0)."""
        return max(0.0, min(float(timeout_s), self.remaining()))

    def check(self) -> None:
        if self.expired():
            raise DeadlineExceeded(f"deadline overdue by {-self.remaining():.3f}s")

    # wire format: remaining budget in milliseconds (relative, so it survives
    # hosts with unsynchronized clocks — the receiver re-anchors on arrival)
    HEADER = "X-MMLSpark-Deadline-Ms"

    def to_header(self) -> str:
        return str(max(0, int(self.remaining() * 1000)))

    @staticmethod
    def parse_budget_s(value) -> Optional[float]:
        """Header value -> remaining budget in seconds (None if malformed).
        The single parser for the wire format — servers clipping a raw float
        budget and ``from_header`` both go through it."""
        try:
            return max(0.0, float(value)) / 1000.0
        except (TypeError, ValueError):
            return None

    @classmethod
    def from_header(cls, value: str,
                    clock: Callable[[], float] = time.monotonic) -> "Deadline":
        budget = cls.parse_budget_s(value)
        if budget is None:
            raise ValueError(f"malformed {cls.HEADER} value: {value!r}")
        return cls.after(budget, clock)

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"


_current_deadline: ContextVar[Optional[Deadline]] = \
    ContextVar("mmlspark_tpu_deadline", default=None)


def current_deadline() -> Optional[Deadline]:
    """The innermost active deadline in this context, or None."""
    return _current_deadline.get()


@contextmanager
def deadline_scope(deadline_or_seconds,
                   clock: Callable[[], float] = time.monotonic):
    """Install a deadline for the duration of the block.  Nested scopes keep
    the TIGHTER bound — a caller's budget can only shrink downstream."""
    if isinstance(deadline_or_seconds, Deadline):
        d = deadline_or_seconds
    else:
        d = Deadline.after(float(deadline_or_seconds), clock)
    outer = _current_deadline.get()
    if outer is not None and outer.expires_at < d.expires_at \
            and outer.clock is d.clock:
        d = outer
    token = _current_deadline.set(d)
    try:
        yield d
    finally:
        _current_deadline.reset(token)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class CircuitOpenError(ConnectionError):
    """Raised (or mapped to a synthetic 503) when the breaker rejects a call
    without attempting it."""

    def __init__(self, name: str, retry_after_s: float):
        self.retry_after_s = max(0.0, retry_after_s)
        super().__init__(
            f"circuit breaker {name or '<anon>'} is open; "
            f"retry after {self.retry_after_s:.1f}s")


class CircuitBreaker:
    """Classic three-state breaker over a rolling failure window.

    - ``closed``: calls flow; failures older than ``window_s`` are forgotten;
      ``failure_threshold`` failures inside the window trip it open.
    - ``open``: every call is rejected until ``cooldown_s`` has elapsed.
    - ``half_open``: up to ``half_open_max_calls`` probe calls are admitted;
      one success closes the breaker (window cleared), one failure reopens it
      (cooldown restarts).

    All transitions run on the injectable ``clock``, so tests step them
    deterministically.  Thread-safe; shared freely across client instances
    guarding the same dependency.

    Observability: ``add_listener(fn)`` registers a transition callback
    ``fn(breaker, old_state, new_state)`` (fired outside the lock —
    ``observability.instruments.instrument_breaker`` turns it into
    counters/gauges), and ``failure_rate()`` reports failures/outcomes over
    the rolling window (successes are sampled into a bounded deque so the
    hot path stays O(1); under extreme QPS the rate is approximate).
    """

    _OUTCOME_CAP = 4096  # per-deque bound on the rolling-rate samples

    def __init__(self, failure_threshold: int = 5, window_s: float = 30.0,
                 cooldown_s: float = 10.0, half_open_max_calls: int = 1,
                 clock: Callable[[], float] = time.monotonic, name: str = ""):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.half_open_max_calls = max(1, half_open_max_calls)
        self.clock = clock
        self.name = name
        self._lock = threading.Lock()
        self._failures: Deque[float] = collections.deque()
        self._state = "closed"
        self._opened_at = 0.0
        self._half_open_inflight = 0
        # observability counters (aggregated into serving /stats)
        self.rejected = 0
        self.opened_count = 0
        self.consecutive_failures = 0
        # rolling failure-rate window: tripping clears _failures (state
        # machine bookkeeping), so the rate keeps its own timestamp deques
        self._rate_failures: Deque[float] = \
            collections.deque(maxlen=self._OUTCOME_CAP)
        self._rate_successes: Deque[float] = \
            collections.deque(maxlen=self._OUTCOME_CAP)
        self._listeners: list = []
        self._pending_notifications: list = []

    # ------------------------------------------------------------- queries
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            state = self._state
        self._notify()
        return state

    def retry_after_s(self) -> float:
        """Seconds until an open breaker will admit a probe (0 if not open)."""
        with self._lock:
            if self._state != "open":
                return 0.0
            return max(0.0, self._opened_at + self.cooldown_s - self.clock())

    def failure_rate(self) -> float:
        """failures / (failures + successes) recorded inside ``window_s``
        (0.0 with no outcomes in the window)."""
        now = self.clock()
        with self._lock:
            for dq in (self._rate_failures, self._rate_successes):
                while dq and now - dq[0] > self.window_s:
                    dq.popleft()
            f, s = len(self._rate_failures), len(self._rate_successes)
        return f / (f + s) if f + s else 0.0

    def add_listener(self, fn: Callable[["CircuitBreaker", str, str], None]
                     ) -> None:
        """Register fn(breaker, old_state, new_state); fired outside the
        lock after every state transition."""
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        """Detach a listener previously registered with ``add_listener``
        (no-op if absent) — re-instrumenting a breaker must not leave the
        old listener double-counting transitions."""
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _transition(self, new_state: str) -> None:
        # caller holds the lock; notification drains after release
        if self._state != new_state:
            self._pending_notifications.append((self._state, new_state))
            self._state = new_state

    def _notify(self) -> None:
        # drain transitions recorded under the lock; listeners run unlocked
        # so they may freely query the breaker.  Each item is popped under
        # the lock — concurrent drainers must not race check-then-pop.
        while True:
            with self._lock:
                if not self._pending_notifications:
                    return
                old, new = self._pending_notifications.pop(0)
            for fn in self._listeners:
                try:
                    fn(self, old, new)
                except Exception:  # noqa: BLE001 — telemetry must not break
                    pass

    def _maybe_half_open(self) -> None:
        # caller holds the lock
        if self._state == "open" and \
                self.clock() - self._opened_at >= self.cooldown_s:
            self._transition("half_open")
            self._half_open_inflight = 0

    # ------------------------------------------------------------- protocol
    def allow(self) -> bool:
        """Admission check; half-open admits a bounded number of probes.
        Callers that take an admission MUST report the outcome via
        ``record_success``/``record_failure`` (or use ``call``)."""
        try:
            with self._lock:
                self._maybe_half_open()
                if self._state == "closed":
                    return True
                if self._state == "half_open":
                    if self._half_open_inflight < self.half_open_max_calls:
                        self._half_open_inflight += 1
                        return True
                self.rejected += 1
                return False
        finally:
            self._notify()

    def record_success(self) -> None:
        with self._lock:
            self._rate_successes.append(self.clock())
            self.consecutive_failures = 0
            if self._state == "half_open" and self._half_open_inflight > 0:
                # an allow()-admitted probe succeeded: close, start fresh.
                # The inflight check matters: a state read may have flipped
                # open->half_open lazily, and a straggler success from a
                # pre-trip call must not close the breaker then — only a
                # call that actually took a probe slot is evidence.
                self._transition("closed")
                self._failures.clear()
                self._half_open_inflight = 0
            # closed: successes do NOT clear the window — a dependency
            # failing half its calls must still trip; old failures age out
            # of the rolling window on their own.  OPEN stays open (even
            # past cooldown): a straggler success from a call admitted
            # before the trip must neither cancel the cooldown nor close
            # the breaker without an allow()-admitted half-open probe.
        self._notify()

    def record_failure(self) -> None:
        with self._lock:
            now = self.clock()
            self._rate_failures.append(now)
            self.consecutive_failures += 1
            if self._state == "half_open":
                self._trip(now)
            else:
                self._failures.append(now)
                while self._failures and now - self._failures[0] > self.window_s:
                    self._failures.popleft()
                if self._state == "closed" and \
                        len(self._failures) >= self.failure_threshold:
                    self._trip(now)
        self._notify()

    def _trip(self, now: float) -> None:
        # caller holds the lock
        self._transition("open")
        self._opened_at = now
        self._failures.clear()
        self._half_open_inflight = 0
        self.opened_count += 1

    def call(self, fn: Callable[[], T]) -> T:
        """Run fn under the breaker: rejected-fast when open, outcome
        recorded otherwise.  Exceptions from fn count as failures and
        propagate."""
        if not self.allow():
            raise CircuitOpenError(self.name, self.retry_after_s())
        try:
            result = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result

    def as_dict(self) -> dict:
        rate = self.failure_rate()  # prunes + computes outside the state lock
        with self._lock:
            return {"state": self._state,
                    "failures_in_window": len(self._failures),
                    "consecutive_failures": self.consecutive_failures,
                    "failure_rate": round(rate, 4),
                    "rejected": self.rejected, "opened_count": self.opened_count}


# ---------------------------------------------------------------------------
# transient-vs-fatal classification for data-plane I/O
# ---------------------------------------------------------------------------

#: failure shapes a retry can plausibly outwait: flaky storage/NFS, a
#: wedged device relay, a reset transfer.  ``OSError`` is deliberately in —
#: EIO/EAGAIN from a shared filesystem is the canonical transient — with
#: the *specifically hopeless* OSErrors carved out below.
TRANSIENT_IO_ERRORS: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, InterruptedError, OSError)

#: failure shapes a retry can never fix: the path/permissions are wrong,
#: not the weather.  Checked FIRST (they are OSError subclasses).
FATAL_IO_ERRORS: Tuple[Type[BaseException], ...] = (
    FileNotFoundError, PermissionError, IsADirectoryError,
    NotADirectoryError)


def is_transient_io(exc: BaseException) -> bool:
    """Transient-vs-fatal classification for load/transfer failures
    (prefetch retry, ISSUE 10): fatal subclasses win over the transient
    families; anything outside both (TypeError, ValueError, ...) is a
    bug, not weather — fatal."""
    if isinstance(exc, FATAL_IO_ERRORS):
        return False
    return isinstance(exc, TRANSIENT_IO_ERRORS)


# ---------------------------------------------------------------------------
# preemption-aware shutdown
# ---------------------------------------------------------------------------

class PreemptionToken:
    """Cooperative shutdown flag set by SIGTERM/SIGINT inside a
    :func:`preemption_scope` — or programmatically via
    :func:`request_preemption` (a fleet-membership watcher observing a
    shrink, ISSUE 14).  Training loops poll :attr:`requested` at
    iteration boundaries: a set token means "write a final checkpoint and
    return cleanly" — the preempted worker resumes instead of restarting.
    ``armed`` is False when the scope could not install handlers (not the
    main thread); signals then never fire it, but programmatic requests
    still do.  ``reason`` records what fired it (``"signal"`` or the
    string a programmatic requester passed)."""

    __slots__ = ("requested", "signum", "count", "armed", "reason")

    def __init__(self, armed: bool = False):
        self.requested = False
        self.signum: Optional[int] = None
        self.count = 0
        self.armed = armed
        self.reason: Optional[str] = None

    def fire(self, signum: int) -> None:
        self.requested = True
        self.signum = signum
        self.reason = "signal"
        self.count += 1

    def fire_event(self, reason: str) -> None:
        """Programmatic preemption (no signal): membership shrink,
        operator drain, test harness."""
        self.requested = True
        self.reason = str(reason)
        self.count += 1


#: tokens of every entered preemption_scope, innermost last — the target
#: set of request_preemption().  Guarded by _TOKEN_LOCK; scopes push on
#: entry and pop on exit even when signal installation degraded, so a
#: membership watcher can preempt a loop running off the main thread.
_TOKEN_STACK: list = []
_TOKEN_LOCK = threading.Lock()

#: observers fired once per preemption event (signal landing in a scope,
#: or a programmatic request that reached at least one token) — the
#: flight recorder (ISSUE 15) registers here so a preempted process dumps
#: its black box BEFORE the final checkpoint-and-exit.  Guarded by
#: _TOKEN_LOCK for registration; fired from a snapshot outside it.
_PREEMPTION_HOOKS: list = []


def register_preemption_hook(fn) -> None:
    """Register ``fn(reason)`` to run on every preemption event.  A
    raising hook is swallowed — observers must never break the shutdown
    path they observe.  Idempotent per callable."""
    with _TOKEN_LOCK:
        if fn not in _PREEMPTION_HOOKS:
            _PREEMPTION_HOOKS.append(fn)


def unregister_preemption_hook(fn) -> None:
    with _TOKEN_LOCK:
        try:
            _PREEMPTION_HOOKS.remove(fn)
        except ValueError:
            pass


def _fire_preemption_hooks(reason: str) -> None:
    with _TOKEN_LOCK:
        hooks = list(_PREEMPTION_HOOKS)
    for fn in hooks:
        try:
            fn(reason)
        except Exception:  # noqa: BLE001 — see register_preemption_hook
            pass


def request_preemption(reason: str = "requested") -> int:
    """Fire every active :class:`preemption_scope` token programmatically
    — the non-signal preemption path (ISSUE 14): a fleet-membership
    watcher that sees the training fleet shrink calls this so the loop
    checkpoints and exits instead of riding a dead collective.  Returns
    the number of tokens fired; books one ``preemption_requested`` ring
    event when any was."""
    with _TOKEN_LOCK:
        tokens = list(_TOKEN_STACK)
    for token in tokens:
        token.fire_event(reason)
    if tokens:
        from ..core.logging import log_event
        log_event({"event": "preemption_requested", "reason": str(reason)})
        # observers (flight recorder) AFTER the ring event so the dump's
        # ring tail includes the preemption it is recording
        _fire_preemption_hooks(str(reason))
    return len(tokens)


@contextmanager
def preemption_scope(signals: Tuple[int, ...] = None, watcher=None):
    """Install SIGTERM/SIGINT handlers for the duration of a training
    loop, yielding a :class:`PreemptionToken`.

    First signal: sets the token (and books a ``preemption_requested``
    ring event) — the loop finishes the current iteration, checkpoints,
    and exits cleanly.  A SECOND SIGINT falls through to the previous
    handler (normally ``KeyboardInterrupt``): a user hammering ctrl-C
    still gets the hard stop.  Handlers are restored on exit.  Off the
    main thread signal installation is impossible; the scope degrades to
    an inert (``armed=False``) token rather than failing the run — the
    token still fires via :func:`request_preemption`, which reaches
    every active scope (the stack makes an OUTER watcher preempt an
    inner driver loop's token).

    ``watcher`` (ISSUE 14) is an optional membership watcher — anything
    with ``start()``/``stop()`` (e.g. ``serving.distributed.
    MembershipWatcher``, whose default on-shrink action is
    ``request_preemption``): started on entry, stopped on exit, so a
    fleet shrink triggers checkpoint-and-exit instead of a collective
    that hangs on dead peers."""
    if signals is None:
        signals = (_signal.SIGTERM, _signal.SIGINT)
    token = PreemptionToken()
    previous = {}
    try:
        for signum in signals:
            def _handler(sn, frame, _token=token, _signals=signals):
                if _token.signum is not None and sn == _signal.SIGINT:
                    # second ctrl-C: the user wants a hard stop, not
                    # patience.  Gate on signum (a prior REAL signal),
                    # not requested — a programmatic fire_event (e.g. a
                    # membership-shrink request_preemption) sets
                    # requested too, and the FIRST ctrl-C after it must
                    # still take the graceful path, not interrupt the
                    # final checkpoint.  Chain to the previous handler,
                    # honouring
                    # SIG_DFL (reinstall + re-raise so the default
                    # terminate semantics apply) and SIG_IGN
                    prev = previous.get(sn)
                    if callable(prev):
                        prev(sn, frame)
                    elif prev == _signal.SIG_DFL:
                        _signal.signal(sn, prev)
                        _signal.raise_signal(sn)
                    return
                _token.fire(sn)
                from ..core.logging import log_event
                log_event({"event": "preemption_requested",
                           "signal": int(sn)})
                # flight-recorder dump while the process is still whole:
                # the handler runs on the main thread at a bytecode
                # boundary, so file I/O here is ordinary code, and hooks
                # swallow their own failures
                _fire_preemption_hooks(f"signal:{int(sn)}")
            previous[signum] = _signal.signal(signum, _handler)
        token.armed = True
    except ValueError:
        # not the main thread: nothing was actually installed (the FIRST
        # signal() call is what raises there), so there is nothing to
        # restore — degrade to an inert token
        previous = {}
    with _TOKEN_LOCK:
        _TOKEN_STACK.append(token)
    try:
        # watcher start INSIDE the try: a start() that raises must still
        # restore the handlers and pop the token, or the process keeps
        # hijacked signals and a dead stack entry forever
        if watcher is not None:
            watcher.start()
        yield token
    finally:
        if watcher is not None:
            try:
                watcher.stop()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        with _TOKEN_LOCK:
            try:
                _TOKEN_STACK.remove(token)
            except ValueError:
                pass
        for signum, prev in previous.items():
            try:
                _signal.signal(signum, prev)
            except ValueError:
                pass


# ---------------------------------------------------------------------------
# budget-aware retries (the fault.py originals, deadline-clipped)
# ---------------------------------------------------------------------------

def retry_with_timeout(fn: Callable[[], T], timeout_s: float,
                       retries: int = 3,
                       deadline: Optional[Deadline] = None) -> T:
    """Run fn with a wall-clock timeout, retrying on timeout or error.
    Honors the ambient ``deadline_scope`` (or an explicit ``deadline``):
    each attempt's timeout is clipped to the remaining budget and no attempt
    starts once the budget is gone."""
    deadline = deadline or current_deadline()
    last: Exception = RuntimeError("no attempts made")
    for _ in range(max(1, retries)):
        attempt_timeout = timeout_s
        if deadline is not None:
            if deadline.expired():
                raise DeadlineExceeded(
                    f"budget exhausted before attempt; last: {last}")
            attempt_timeout = deadline.clip(timeout_s)
        ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        fut = ex.submit(fn)
        try:
            return fut.result(timeout=attempt_timeout)
        except concurrent.futures.TimeoutError:
            last = TimeoutError(f"operation exceeded {attempt_timeout}s")
        except Exception as e:  # noqa: BLE001 — retried, re-raised at end
            last = e
        finally:
            # wait=False so a hung fn doesn't block the caller past timeout_s;
            # the worker thread is daemonic-ish leaked but control returns.
            ex.shutdown(wait=False)
    raise last


# ---------------------------------------------------------------------------
# dispatch hang watchdog (ISSUE 16)
# ---------------------------------------------------------------------------

class Watchdog:
    """Stall detector for device dispatches that can hang forever.

    The thread doing the dispatch cannot observe its own hang — it is stuck
    inside the blocked call — so detection is split: the *working* thread
    brackets each potentially-hanging section with :meth:`arm` /
    :meth:`disarm` (or the :meth:`section` context manager) and may
    :meth:`heartbeat` mid-section to restart the clock; a *monitor* (either
    the daemon thread from :meth:`start`, or a test calling :meth:`check`
    directly on a :class:`FakeClock`) observes an armed section exceeding
    ``stall_timeout_s`` and fires ``on_stall(label, elapsed_s)`` exactly
    once per armed section (re-arming resets the latch).

    ``on_stall`` runs on the monitor thread, outside the watchdog lock, and
    must therefore be safe to run concurrently with the stalled worker —
    the decode-engine integration uses it to poison-abort the engine, which
    is exactly a cross-thread teardown.  A raising callback is swallowed:
    the detector must keep detecting.
    """

    def __init__(self, stall_timeout_s: float,
                 clock: Callable[[], float] = time.monotonic,
                 on_stall: Optional[Callable[[str, float], None]] = None,
                 name: str = ""):
        if stall_timeout_s <= 0:
            raise ValueError("stall_timeout_s must be > 0")
        self.stall_timeout_s = float(stall_timeout_s)
        self.clock = clock
        self.on_stall = on_stall
        self.name = name
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._label = ""
        self._generation = 0       # bumped per arm(); the trip latch key
        self._tripped_generation = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.trips = 0             # sections that exceeded the timeout

    # ---------------------------------------------------------- worker side
    def arm(self, label: str = "dispatch") -> None:
        """Mark the start of a section that may hang.  Resets the
        once-per-section trip latch."""
        with self._lock:
            self._armed_at = self.clock()
            self._label = str(label)
            self._generation += 1

    def heartbeat(self) -> None:
        """Restart the stall clock without ending the section (a decode
        loop that made progress mid-section).  No-op when disarmed."""
        with self._lock:
            if self._armed_at is not None:
                self._armed_at = self.clock()

    def disarm(self) -> None:
        """Mark the end of the section — the dispatch returned."""
        with self._lock:
            self._armed_at = None

    @contextmanager
    def section(self, label: str = "dispatch"):
        self.arm(label)
        try:
            yield self
        finally:
            self.disarm()

    # --------------------------------------------------------- monitor side
    def stalled_for(self) -> float:
        """Seconds the current armed section has run (0.0 when disarmed)."""
        with self._lock:
            if self._armed_at is None:
                return 0.0
            return max(0.0, self.clock() - self._armed_at)

    def expired(self) -> bool:
        return self.stalled_for() > self.stall_timeout_s

    def check(self) -> bool:
        """One monitor poll: True when the armed section has overrun
        ``stall_timeout_s``.  Fires ``on_stall`` the FIRST time an armed
        section is seen overrun; later polls of the same section return
        True without re-firing."""
        with self._lock:
            if self._armed_at is None:
                return False
            elapsed = self.clock() - self._armed_at
            if elapsed <= self.stall_timeout_s:
                return False
            already = self._tripped_generation == self._generation
            if not already:
                self._tripped_generation = self._generation
                self.trips += 1
            label = self._label
        if not already and self.on_stall is not None:
            try:
                self.on_stall(label, elapsed)
            except Exception:  # noqa: BLE001 — detector must keep detecting
                pass
        return True

    def start(self, poll_interval_s: Optional[float] = None) -> "Watchdog":
        """Start the daemon monitor thread (idempotent).  Polls at
        ``poll_interval_s`` (default: a quarter of the stall timeout,
        floored at 10ms) using real ``time.sleep`` — tests on a FakeClock
        skip the thread and call :meth:`check` directly."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            interval = poll_interval_s if poll_interval_s is not None \
                else max(0.01, self.stall_timeout_s / 4.0)
            thread = threading.Thread(
                target=self._monitor, args=(float(interval),),
                name=f"mmlspark-watchdog-{self.name or 'anon'}", daemon=True)
            self._thread = thread
        thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        # an on_stall callback tearing its engine down reaches stop() ON
        # the monitor thread itself — it cannot join itself; the set event
        # ends the loop at the next poll
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)

    def _monitor(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.check()

    def as_dict(self) -> dict:
        with self._lock:
            armed = self._armed_at is not None
            label = self._label if armed else ""
        return {"armed": armed, "label": label, "trips": self.trips,
                "stall_timeout_s": self.stall_timeout_s}


# ---------------------------------------------------------------------------
# retry budget (ISSUE 16)
# ---------------------------------------------------------------------------

class RetryBudget:
    """Token bucket bounding retry amplification fleet-wide.

    Every FIRST attempt deposits ``ratio`` tokens; every retry must
    withdraw a whole token or be denied.  Under a full outage the math is
    the invariant: attempted exchanges <= (1 + ratio) * offered + initial
    — retries can never amplify offered load into a storm, no matter how
    many clients fail over at once.  ``initial`` (default: ``cap``) is the
    cold-start burst: a freshly built client can still fail over its first
    few requests before any deposits accrue; pass ``initial=0.0`` to prove
    the asymptotic bound exactly.

    Thread-safe; ``granted``/``denied`` counters are the observability
    surface (`RoutingClient` mirrors them into
    ``mmlspark_retry_budget_{granted,denied}_total``).
    """

    def __init__(self, ratio: float = 0.1, cap: float = 100.0,
                 initial: Optional[float] = None):
        if ratio < 0:
            raise ValueError("ratio must be >= 0")
        if cap <= 0:
            raise ValueError("cap must be > 0")
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._tokens = self.cap if initial is None \
            else min(self.cap, max(0.0, float(initial)))
        self._lock = threading.Lock()
        self.granted = 0
        self.denied = 0

    def deposit(self) -> None:
        """Book one first-try request: the bucket earns ``ratio`` tokens."""
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_withdraw(self) -> bool:
        """Spend one whole token for a retry; False (denied) when the
        bucket holds less than one.  The epsilon absorbs float summation
        of repeated ``ratio`` deposits (10 x 0.1 sums below 1.0), so the
        documented "1/ratio offered requests earn one retry" holds
        exactly."""
        with self._lock:
            if self._tokens >= 1.0 - 1e-9:
                self._tokens = max(0.0, self._tokens - 1.0)
                self.granted += 1
                return True
            self.denied += 1
            return False

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def as_dict(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 4), "ratio": self.ratio,
                    "cap": self.cap, "granted": self.granted,
                    "denied": self.denied}


class RestartSupervisor:
    """Supervised-restart policy for a crash/stall-prone engine.

    The owner reports each engine death via :meth:`note_failure(reason)`;
    the supervisor gates the rebuild behind capped exponential backoff
    (:meth:`retry_after_s` > 0 while backing off) and QUARANTINES after
    ``quarantine_stalls`` stall-deaths inside ``quarantine_window_s`` — a
    runner stalling over and over is wedged hardware or a dead relay, and
    the right move is to flip health unhealthy so the fleet's probes evict
    the worker, not to burn restarts forever.

    The consecutive-failure count (the backoff exponent) resets once the
    engine stays up longer than ``quarantine_window_s`` past the last
    death, or explicitly via :meth:`note_success` (a clean close).
    Quarantine never lifts on its own — the worker is replaced, not
    healed.  Injectable clock; thread-safe.
    """

    def __init__(self, initial_backoff_s: float = 0.5,
                 backoff_cap_s: float = 30.0, quarantine_stalls: int = 3,
                 quarantine_window_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic):
        self.initial_backoff_s = float(initial_backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.quarantine_stalls = max(1, int(quarantine_stalls))
        self.quarantine_window_s = float(quarantine_window_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._stalls: Deque[float] = collections.deque()
        self._consecutive = 0
        self._last_failure_at: Optional[float] = None
        self._not_before: Optional[float] = None
        self.quarantined = False
        self.failures = 0
        self.restarts = 0

    def note_failure(self, reason: str = "error") -> float:
        """Record one engine death; returns the backoff applied to the
        next rebuild.  ``reason == "stall"`` feeds the quarantine window."""
        with self._lock:
            now = self.clock()
            if self._last_failure_at is not None and \
                    now - self._last_failure_at > self.quarantine_window_s:
                self._consecutive = 0
            self._last_failure_at = now
            self.failures += 1
            self._consecutive += 1
            backoff = min(self.backoff_cap_s,
                          self.initial_backoff_s
                          * (2.0 ** (self._consecutive - 1)))
            self._not_before = now + backoff
            if reason == "stall":
                self._stalls.append(now)
                while self._stalls and \
                        now - self._stalls[0] > self.quarantine_window_s:
                    self._stalls.popleft()
                if len(self._stalls) >= self.quarantine_stalls:
                    self.quarantined = True
            return backoff

    def retry_after_s(self) -> float:
        """Seconds until a rebuild is admissible: 0.0 = go now;
        ``backoff_cap_s`` forever while quarantined (the header-friendly
        stand-in for never — the worker is being evicted)."""
        with self._lock:
            if self.quarantined:
                return self.backoff_cap_s
            if self._not_before is None:
                return 0.0
            return max(0.0, self._not_before - self.clock())

    def note_restart(self) -> None:
        """A supervised rebuild actually happened (observability)."""
        with self._lock:
            self.restarts += 1

    def note_success(self) -> None:
        """The engine proved healthy (clean close, sustained uptime): the
        backoff exponent resets.  Quarantine does NOT lift — see class
        docstring."""
        with self._lock:
            self._consecutive = 0
            self._not_before = None

    def as_dict(self) -> dict:
        with self._lock:
            return {"quarantined": self.quarantined,
                    "failures": self.failures, "restarts": self.restarts,
                    "consecutive": self._consecutive,
                    "stalls_in_window": len(self._stalls)}


def with_retries(fn: Callable[[], T], retries: int = 3,
                 initial_delay_s: float = 0.1, backoff: float = 2.0,
                 exceptions: Tuple[Type[BaseException], ...] = (Exception,),
                 deadline: Optional[Deadline] = None,
                 sleep: Callable[[float], None] = time.sleep) -> T:
    """Exponential-backoff retry (reference networkInit retry pattern),
    clipped to the ambient/explicit deadline: backoff sleeps never overshoot
    the remaining budget, and once the budget is spent the last error is
    raised instead of burning further attempts."""
    retries = max(1, retries)
    deadline = deadline or current_deadline()
    delay = initial_delay_s
    for attempt in range(retries):
        if deadline is not None and deadline.expired():
            raise DeadlineExceeded("budget exhausted before attempt")
        try:
            return fn()
        except exceptions:
            if attempt == retries - 1:
                raise
            if deadline is not None:
                remaining = deadline.remaining()
                if remaining <= 0:
                    raise
                sleep(min(delay, remaining))
            else:
                sleep(delay)
            delay *= backoff
