"""Resource management helpers (reference ``core/env/StreamUtilities.scala``)."""
from __future__ import annotations

import contextlib
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def using(resource, fn: Callable[..., R]) -> R:
    """StreamUtilities.using: apply fn to resource, always closing it."""
    with contextlib.closing(resource) as r:
        return fn(r)


def using_many(resources: Iterable, fn: Callable[..., R]) -> R:
    resources = list(resources)
    try:
        return fn(resources)
    finally:
        for r in resources:
            with contextlib.suppress(Exception):
                r.close()
