"""NativeLoader — build/load the C++ data plane, with pure-python fallback.

Reference: ``core/env/NativeLoader.java:28`` extracts packaged ``.so`` files
and ``System.load``s them in manifest order.  Here the library is built from
``native/mmlspark_native.cpp`` on first use (g++ is part of the toolchain)
and loaded via ctypes; every consumer has a numpy fallback so the framework
stays functional without a compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")


def load_native() -> Optional[ctypes.CDLL]:
    """Returns the loaded library or None (fallback mode)."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        d = _native_dir()
        so = os.path.join(d, "libmmlspark_native.so")
        src = os.path.join(d, "mmlspark_native.cpp")
        stale = (os.path.exists(so) and os.path.exists(src)
                 and os.path.getmtime(src) > os.path.getmtime(so))
        if not os.path.exists(so) or stale:
            if not os.path.exists(src):
                return None
            try:
                # rebuild BEFORE the first dlopen — reloading the same path
                # after a rebuild would serve the cached stale handle
                subprocess.run(["make", "-C", d, "-B"] if stale else
                               ["make", "-C", d], check=True,
                               capture_output=True, timeout=120)
            except Exception:  # noqa: BLE001 — no compiler: numpy fallback
                if not os.path.exists(so):
                    return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        lib.mm_murmur3_32.restype = ctypes.c_uint32
        lib.mm_murmur3_32.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.c_uint32]
        lib.mm_murmur3_batch.restype = None
        lib.mm_csv_parse_f32.restype = ctypes.c_int64
        lib.mm_csv_shape.restype = None
        lib.mm_chunked_new.restype = ctypes.c_void_p
        lib.mm_chunked_size.restype = ctypes.c_int64
        lib.mm_chunked_add.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_int64]
        lib.mm_chunked_coalesce.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.mm_chunked_free.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "mm_bin_edges"):
            lib.mm_bin_edges.restype = None
            lib.mm_bin_apply.restype = None
        _LIB = lib
        return _LIB


def murmur3_batch_native(strings, seed: int = 0):
    """Hash a list of str/bytes via the native batch kernel; None if no lib."""
    import numpy as np
    lib = load_native()
    if lib is None:
        return None
    blobs = [s.encode("utf-8") if isinstance(s, str) else bytes(s) for s in strings]
    offsets = np.zeros(len(blobs) + 1, np.int64)
    for i, b in enumerate(blobs):
        offsets[i + 1] = offsets[i] + len(b)
    data = b"".join(blobs)
    out = np.zeros(len(blobs), np.uint32)
    lib.mm_murmur3_batch(data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                         len(blobs), ctypes.c_uint32(seed),
                         out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return out


def csv_to_matrix_native(text: bytes, skip_header: bool = True):
    """CSV bytes -> (n, F) float32 matrix via the native parser; None if no lib."""
    import numpy as np
    lib = load_native()
    if lib is None:
        return None
    nrows = ctypes.c_int64()
    ncols = ctypes.c_int64()
    lib.mm_csv_shape(text, len(text), ctypes.byref(nrows), ctypes.byref(ncols))
    cap = nrows.value
    out = np.empty((max(cap, 1), ncols.value), np.float32)
    got = lib.mm_csv_parse_f32(text, len(text), ncols.value,
                               out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                               cap, 1 if skip_header else 0)
    if got < 0:
        return None
    return out[:got]


def bin_edges_native(X, max_bin: int, n_threads: int = 0):
    """(n, F) float32 -> (F, max_bin-1) quantile edges via the threaded C++
    kernel (BinMapper.fit hot path); None if no lib."""
    import numpy as np
    lib = load_native()
    if lib is None or not hasattr(lib, "mm_bin_edges"):
        return None
    X = np.ascontiguousarray(X, np.float32)
    n, F = X.shape
    edges = np.empty((F, max_bin - 1), np.float32)
    lib.mm_bin_edges(X.ctypes.data_as(ctypes.c_void_p),
                     ctypes.c_int64(n), ctypes.c_int64(F),
                     ctypes.c_int(max_bin),
                     edges.ctypes.data_as(ctypes.c_void_p),
                     ctypes.c_int(n_threads))
    return edges


def bin_apply_native(X, edges, max_bin: int, n_threads: int = 0):
    """(n, F) raw -> (n, F) uint8 bins via the threaded C++ binary search;
    None if no lib."""
    import numpy as np
    lib = load_native()
    if lib is None or not hasattr(lib, "mm_bin_apply"):
        return None
    X = np.ascontiguousarray(X, np.float32)
    edges = np.ascontiguousarray(edges, np.float32)
    n, F = X.shape
    out = np.empty((n, F), np.uint8)
    lib.mm_bin_apply(X.ctypes.data_as(ctypes.c_void_p),
                     ctypes.c_int64(n), ctypes.c_int64(F),
                     edges.ctypes.data_as(ctypes.c_void_p),
                     ctypes.c_int(max_bin),
                     out.ctypes.data_as(ctypes.c_void_p),
                     ctypes.c_int(n_threads))
    return out


class ChunkedArray:
    """Growable native float32 buffer (reference SWIG ChunkedArray analogue,
    ``swig/SwigUtils.scala:23-100``)."""

    def __init__(self, initial_cap: int = 1 << 16):
        self._lib = load_native()
        self._chunks = []  # fallback storage
        self._handle = None
        if self._lib is not None:
            self._handle = ctypes.c_void_p(self._lib.mm_chunked_new(initial_cap))

    def add(self, values) -> None:
        import numpy as np
        arr = np.ascontiguousarray(values, np.float32)
        if self._handle is not None:
            self._lib.mm_chunked_add(self._handle,
                                     arr.ctypes.data_as(ctypes.c_void_p),
                                     arr.size)
        else:
            self._chunks.append(arr.copy())

    @property
    def size(self) -> int:
        if self._handle is not None:
            return int(self._lib.mm_chunked_size(self._handle))
        return int(sum(a.size for a in self._chunks))

    def coalesce(self):
        import numpy as np
        if self._handle is not None:
            out = np.empty(self.size, np.float32)
            self._lib.mm_chunked_coalesce(self._handle,
                                          out.ctypes.data_as(ctypes.c_void_p))
            return out
        return np.concatenate(self._chunks) if self._chunks else np.empty(0, np.float32)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.mm_chunked_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
