"""Fault tolerance — compatibility facade over ``utils/resilience.py``.

The original 49-line retry/timeout wrappers (reference:
``core/utils/FaultToleranceUtils.scala`` ``retryWithTimeout`` at
``TrainUtils.scala:339`` / ``VowpalWabbitBase.scala:347``, and the
exponential-backoff loop in ``TrainUtils.networkInit``,
``TrainUtils.scala:279-295``) grew into the full resilience subsystem —
circuit breakers, deadline propagation, budget-aware retries.  Existing
imports of ``utils.fault`` keep working; new code should import from
``mmlspark_tpu.utils.resilience`` directly.
"""
from .resilience import (CircuitBreaker, CircuitOpenError, Deadline,
                         DeadlineExceeded, FakeClock, current_deadline,
                         deadline_scope, retry_with_timeout, with_retries)

__all__ = ["retry_with_timeout", "with_retries", "CircuitBreaker",
           "CircuitOpenError", "Deadline", "DeadlineExceeded", "FakeClock",
           "current_deadline", "deadline_scope"]
