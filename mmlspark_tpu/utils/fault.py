"""Fault tolerance — retry/timeout wrappers around flaky init paths.

Reference: ``core/utils/FaultToleranceUtils.scala`` (``retryWithTimeout``
guarding native/network init at ``TrainUtils.scala:339``,
``VowpalWabbitBase.scala:347``) and the exponential-backoff retry loop in
``TrainUtils.networkInit`` (``TrainUtils.scala:279-295``).
"""
from __future__ import annotations

import concurrent.futures
import time
from typing import Callable, Tuple, Type, TypeVar

T = TypeVar("T")


def retry_with_timeout(fn: Callable[[], T], timeout_s: float, retries: int = 3) -> T:
    """Run fn with a wall-clock timeout, retrying on timeout or error."""
    last: Exception = RuntimeError("no attempts made")
    for _ in range(max(1, retries)):
        ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        fut = ex.submit(fn)
        try:
            return fut.result(timeout=timeout_s)
        except concurrent.futures.TimeoutError:
            last = TimeoutError(f"operation exceeded {timeout_s}s")
        except Exception as e:  # noqa: BLE001 — retried, re-raised at end
            last = e
        finally:
            # wait=False so a hung fn doesn't block the caller past timeout_s;
            # the worker thread is daemonic-ish leaked but control returns.
            ex.shutdown(wait=False)
    raise last


def with_retries(fn: Callable[[], T], retries: int = 3, initial_delay_s: float = 0.1,
                 backoff: float = 2.0,
                 exceptions: Tuple[Type[BaseException], ...] = (Exception,)) -> T:
    """Exponential-backoff retry (reference networkInit retry pattern)."""
    retries = max(1, retries)
    delay = initial_delay_s
    for attempt in range(retries):
        try:
            return fn()
        except exceptions:
            if attempt == retries - 1:
                raise
            time.sleep(delay)
            delay *= backoff
