"""ClusterUtil — device/cluster topology oracle.

Reference: ``core/utils/ClusterUtil.scala:20-145`` derives executor count,
tasks-per-executor and driver host so LightGBM/VW can size their allreduce
rings.  TPU-native, the topology is the JAX device set: one process per host,
N local chips, global mesh over ICI/DCN.  This module answers the same
questions (how many workers, who is the coordinator) in device terms and is
consumed by the trainers and the mesh bootstrap (``parallel.mesh``).
"""
from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import List, Optional


@dataclass(frozen=True)
class Topology:
    num_devices: int          # global chip count (ring size equivalent)
    num_local_devices: int    # chips on this host (tasks-per-executor analogue)
    num_hosts: int            # executor count analogue
    host_index: int           # this executor's index
    platform: str             # 'tpu' | 'cpu' | ...
    coordinator: str          # driver host:port analogue


class ClusterUtil:
    """Static topology queries (mirrors reference ClusterUtil's static API)."""

    _override: Optional[Topology] = None

    @staticmethod
    def get_topology() -> Topology:
        if ClusterUtil._override is not None:
            return ClusterUtil._override
        import jax
        devices = jax.devices()
        return Topology(
            num_devices=len(devices),
            num_local_devices=len(jax.local_devices()),
            num_hosts=jax.process_count(),
            host_index=jax.process_index(),
            platform=devices[0].platform if devices else "cpu",
            coordinator=os.environ.get("MMLSPARK_TPU_COORDINATOR",
                                       f"{socket.gethostname()}:0"),
        )

    @staticmethod
    def set_topology_override(topo: Optional[Topology]) -> None:
        """Tests inject synthetic topologies (reference tests spoof executor
        counts through local[*] task settings)."""
        ClusterUtil._override = topo

    @staticmethod
    def get_num_devices() -> int:
        return ClusterUtil.get_topology().num_devices

    @staticmethod
    def get_num_hosts() -> int:
        return ClusterUtil.get_topology().num_hosts

    @staticmethod
    def get_num_tasks_per_executor() -> int:
        return ClusterUtil.get_topology().num_local_devices

    @staticmethod
    def get_driver_host() -> str:
        return ClusterUtil.get_topology().coordinator.split(":")[0]

    @staticmethod
    def default_parallelism(df_partitions: int, requested: Optional[int] = None) -> int:
        """How many data shards to train over: min(partitions, devices) unless
        the caller pins a count (reference prepareDataframe repartition logic,
        ``LightGBMBase.scala:110-145``)."""
        n = requested or ClusterUtil.get_num_devices()
        return max(1, min(n, df_partitions if df_partitions > 0 else n))
