"""Gradient-histogram builds — the GBDT hot kernel.

Reference: LightGBM's native histogram construction + socket allreduce
(`LGBM_NetworkInit` ring; reference ``TrainUtils.scala:279-295``, C-API calls
in ``LightGBMBooster.scala``).  TPU-native: one fused scatter-add over a
flattened (node, feature, bin) index space, expressed as ``segment_sum`` so
XLA lowers it to a single sorted-scatter per iteration; across data shards the
histograms are combined by ``psum`` over ICI — either inserted automatically
by GSPMD (jit + shardings) or explicitly in ``shard_map`` (see
``lightgbm.core``).

Layout note: the histogram tensor is (nodes, features, bins, 3) holding
(sum_grad, sum_hess, count).  bins=const 256 max keeps the last dim a
multiple of 128 lanes after flattening; counts ride along as a third channel
instead of a separate pass.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def build_histograms(binned: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                     node_ids: jnp.ndarray, num_nodes: int, num_bins: int,
                     sample_weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Histograms for every (node, feature, bin) cell in one pass.

    Args:
      binned: (n, F) uint8/int32 feature bins.
      grad, hess: (n,) per-row gradient/hessian.
      node_ids: (n,) int32 current node of each row at this depth, in
        [0, num_nodes); rows with node_id < 0 (masked out by bagging/GOSS)
        are dropped.
      num_nodes, num_bins: static sizes.
      sample_weight: optional (n,) multiplier folded into grad/hess/count.

    Returns:
      (num_nodes, F, num_bins, 3) float32: sums of grad, hess, count.
    """
    n, F = binned.shape
    b = binned.astype(jnp.int32)
    valid = node_ids >= 0
    node = jnp.where(valid, node_ids, 0).astype(jnp.int32)

    w = jnp.where(valid, 1.0, 0.0)
    if sample_weight is not None:
        w = w * sample_weight
    g = (grad * w)[:, None]
    h = (hess * w)[:, None]
    c = w[:, None]

    # flattened segment id per (row, feature): ((node * F) + f) * B + bin
    f_idx = jnp.arange(F, dtype=jnp.int32)[None, :]
    seg = (node[:, None] * F + f_idx) * num_bins + b  # (n, F)
    data = jnp.stack([jnp.broadcast_to(g, (n, F)),
                      jnp.broadcast_to(h, (n, F)),
                      jnp.broadcast_to(c, (n, F))], axis=-1)  # (n, F, 3)
    flat = jax.ops.segment_sum(data.reshape(n * F, 3), seg.reshape(n * F),
                               num_segments=num_nodes * F * num_bins)
    return flat.reshape(num_nodes, F, num_bins, 3)


def histogram_subtraction(parent_hist: jnp.ndarray, child_hist: jnp.ndarray) -> jnp.ndarray:
    """Sibling trick: sibling = parent - child (LightGBM's halving of
    histogram work).  parent/child: (nodes_d, F, B, 3) with children of node
    i at 2i, 2i+1 — returns the sibling histograms for the next level."""
    return parent_hist - child_hist


@partial(jax.jit, static_argnames=("num_bins",))
def bin_matrix(x: jnp.ndarray, edges: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """Digitize raw features on device: bin = #edges < x (vectorized
    searchsorted).  edges: (F, num_bins-1) ascending with +inf padding."""
    # (n, F, 1) > (1, F, B-1) -> sum over last axis
    return jnp.sum(x[:, :, None] > edges[None, :, :], axis=-1).astype(jnp.uint8)
