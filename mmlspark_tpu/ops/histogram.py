"""Gradient-histogram builds — the GBDT hot kernel.

Reference: LightGBM's native histogram construction + socket allreduce
(`LGBM_NetworkInit` ring; reference ``TrainUtils.scala:279-295``, C-API calls
in ``LightGBMBooster.scala``).  TPU-native: one fused scatter-add over a
flattened (node, feature, bin) index space, expressed as ``segment_sum`` so
XLA lowers it to a single sorted-scatter per iteration; across data shards the
histograms are combined by ``psum`` over ICI — either inserted automatically
by GSPMD (jit + shardings) or explicitly in ``shard_map`` (see
``lightgbm.core``).

Layout note: the histogram tensor is (nodes, features, bins, 3) holding
(sum_grad, sum_hess, count).  bins=const 256 max keeps the last dim a
multiple of 128 lanes after flattening; counts ride along as a third channel
instead of a separate pass.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..observability.compute import instrumented_jit


def build_histograms(binned: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                     node_ids: jnp.ndarray, num_nodes: int, num_bins: int,
                     sample_weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Histograms for every (node, feature, bin) cell in one pass.

    Args:
      binned: (n, F) uint8/int32 feature bins.
      grad, hess: (n,) per-row gradient/hessian.
      node_ids: (n,) int32 current node of each row at this depth, in
        [0, num_nodes); rows with node_id < 0 (masked out by bagging/GOSS)
        are dropped.
      num_nodes, num_bins: static sizes.
      sample_weight: optional (n,) multiplier folded into grad/hess/count.

    Returns:
      (num_nodes, F, num_bins, 3) float32: sums of grad, hess, count.
    """
    n, F = binned.shape
    B = num_bins
    S = num_nodes * F * B
    node = node_ids.astype(jnp.int32)
    g = grad.astype(jnp.float32)
    h = hess.astype(jnp.float32)
    c = jnp.ones_like(g)  # counts stay unweighted (min_data_in_leaf semantics)
    if sample_weight is not None:
        g, h = g * sample_weight, h * sample_weight

    # Row-chunked accumulation keeps the (chunk, F) broadcast small instead of
    # materialising n*F floats (0.8 GB at 1M x 200).  Rows with node < 0
    # (bagging/GOSS-masked or padding) get negative segment ids, which the
    # scatter drops natively.  Three separate f32 scatters measured faster on
    # TPU than channel-windowed or complex-packed variants.
    chunk = max(1024, min(n, (1 << 23) // max(F, 1)))
    n_pad = -n % chunk
    if n_pad:
        node = jnp.concatenate([node, jnp.full((n_pad,), -1, jnp.int32)])
        b_mat = jnp.concatenate([binned, jnp.zeros((n_pad, F), binned.dtype)])
        g = jnp.concatenate([g, jnp.zeros((n_pad,), g.dtype)])
        h = jnp.concatenate([h, jnp.zeros((n_pad,), h.dtype)])
        c = jnp.concatenate([c, jnp.zeros((n_pad,), c.dtype)])
    else:
        b_mat = binned
    R = (n + n_pad) // chunk
    f_idx = jnp.arange(F, dtype=jnp.int32)[None, :]

    def body(acc, args):
        b_c, g_c, h_c, c_c, node_c = args
        seg = ((node_c[:, None] * F + f_idx) * B + b_c.astype(jnp.int32)).reshape(-1)
        sums = [jax.ops.segment_sum(
            jnp.broadcast_to(x[:, None], (chunk, F)).reshape(-1), seg,
            num_segments=S) for x in (g_c, h_c, c_c)]
        return (acc[0] + sums[0], acc[1] + sums[1], acc[2] + sums[2]), None

    init = (jnp.zeros((S,), jnp.float32),) * 3
    (gs, hs, cs), _ = jax.lax.scan(
        body, init,
        (b_mat.reshape(R, chunk, F), g.reshape(R, chunk), h.reshape(R, chunk),
         c.reshape(R, chunk), node.reshape(R, chunk)))
    return jnp.stack([gs, hs, cs], axis=-1).reshape(num_nodes, F, B, 3)


def histogram_subtraction(parent_hist: jnp.ndarray, child_hist: jnp.ndarray) -> jnp.ndarray:
    """Sibling trick: sibling = parent - child (LightGBM's halving of
    histogram work).  parent/child: (nodes_d, F, B, 3) with children of node
    i at 2i, 2i+1 — returns the sibling histograms for the next level."""
    return parent_hist - child_hist


@instrumented_jit(name="ops.bin_matrix", static_argnames=("num_bins",))
def bin_matrix(x: jnp.ndarray, edges: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """Digitize raw features on device: bin = #edges < x.  edges:
    (F, num_bins-1) ascending with +inf padding.

    Per-feature binary search (vmapped ``searchsorted``), O(n*F*log B) time
    and O(n*F) memory — the old broadcast compare materialized an
    (n, F, B-1) boolean (~50GB logical at 1M x 200 x 255; round-1 weak
    item 10).  NaNs bin to 0, matching the comparison semantics.
    """
    def per_feature(e, xf):
        return jnp.searchsorted(e, xf, side="left")

    bins = jax.vmap(per_feature, in_axes=(0, 1), out_axes=1)(edges, x)
    return jnp.where(jnp.isnan(x), 0, bins).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# MXU histogram backend
# ---------------------------------------------------------------------------

def _node_pure_layout(binned, grad, hess, node_ids, num_nodes, R,
                      sample_weight=None, residuals=True, max_rows=None,
                      quantized=False):
    """Shared host/device prep for the MXU histogram backend:
    sort rows by node and pad so every R-row block is node-pure, then build
    the bf16x2-decomposed weight channels (``residuals=False`` keeps just
    bf16-rounded grad/hess + count — 3 channels instead of 5).

    With ``quantized=True``, ``grad``/``hess`` are the pre-quantized int
    gradients and the weight channels come back as **int8**
    (qg, qh, valid) — the packed-histogram operand layout.

    Returns (bb_all (N_pad, F) u8, w_ch (5 or 3, N_pad) f32, node_blk (NB,)
    i32, NB).  Masked rows (node < 0) land in dummy node P whose buffer is
    dropped by the caller.

    ``max_rows`` is a STATIC caller GUARANTEE that at most that many rows
    are unmasked (node >= 0).  It truncates the padded layout — and with it
    the block scan — to ``ceil(max_rows/R) + P + 1`` blocks instead of
    covering all n rows; surplus masked rows fall off the end of the
    (smaller) scatter and are dropped.  The level-wise grower uses this with
    LightGBM's smaller-child rule: levels below the root only ever scatter
    the smaller sibling of each parent (<= n/2 rows total), halving the
    one-hot operand traffic of every build after the root.  If the caller's
    guarantee is violated, UNMASKED rows are silently dropped — callers must
    pass a true bound.
    """
    import jax
    import jax.numpy as jnp

    n, F = binned.shape
    P = num_nodes
    if quantized:
        g = grad.astype(jnp.int32)
        h = hess.astype(jnp.int32)
    else:
        g = grad.astype(jnp.float32)
        h = hess.astype(jnp.float32)
        if sample_weight is not None:
            g, h = g * sample_weight, h * sample_weight
    c = jnp.ones_like(g)  # counts stay unweighted (min_data_in_leaf semantics)

    import os as _os
    node_s = jnp.where(node_ids < 0, P, node_ids).astype(jnp.int32)
    # the one-hot cumsum materializes (n, P+1) transients — a candidate win
    # only while P is small (depth-5 level-wise peaks at P=16); wide-node
    # builds (deep trees, leaf-wise num_leaves buffers) always use the
    # stable sort.  Default stays "sort" (the r4-measured baseline) until
    # the on-chip A/B in bench_attempts/tune_r5.log proves cumsum faster —
    # select it via MMLSPARK_TPU_HIST_LAYOUT=cumsum
    use_cumsum = (_os.environ.get("MMLSPARK_TPU_HIST_LAYOUT", "sort")
                  == "cumsum") and P + 1 <= 33
    if use_cumsum:
        # rank-by-cumulative-count: rows keep their original order within
        # each node, exactly like the stable argsort below, but the slot
        # comes from an exclusive prefix count over a (n, P+1) one-hot —
        # P <= num_nodes is tiny, so 17 parallel prefix sums beat a full
        # 1M-key sort on both CPU and TPU (tools/profile_gbdt.py)
        onehot_n = (node_s[:, None] == jnp.arange(P + 1)).astype(jnp.int32)
        inc = jnp.cumsum(onehot_n, axis=0)
        counts = inc[-1]
        rank_all = jnp.take_along_axis(inc - onehot_n, node_s[:, None],
                                       axis=1)[:, 0]
    else:
        order = jnp.argsort(node_s)                 # stable
        ns = node_s[order]
        counts = jax.ops.segment_sum(jnp.ones((n,), jnp.int32), node_s,
                                     num_segments=P + 1)
        start = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                 jnp.cumsum(counts)[:-1]])
    # empty nodes get ZERO blocks (their buffer stays at acc0's zeros);
    # node_blk's searchsorted('right')-1 naturally skips past zero-width
    # offsets to the node that actually owns the rows
    padded_counts = ((counts + R - 1) // R) * R
    padded_off = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                  jnp.cumsum(padded_counts)[:-1]])
    n_cap = n if max_rows is None else min(n, int(max_rows))
    N_pad = ((n_cap + R - 1) // R + P + 1) * R       # static upper bound, R-aligned
    if use_cumsum:
        pos = padded_off[node_s] + rank_all
        padded_idx = jnp.full((N_pad,), -1, jnp.int32).at[pos].set(
            jnp.arange(n, dtype=jnp.int32))
    else:
        rank = jnp.arange(n, dtype=jnp.int32) - start[ns]
        pos = padded_off[ns] + rank
        padded_idx = jnp.full((N_pad,), -1, jnp.int32).at[pos].set(order)

    NB = N_pad // R
    block_starts = jnp.arange(NB, dtype=jnp.int32) * R
    node_blk = jnp.searchsorted(padded_off, block_starts, side="right").astype(jnp.int32) - 1
    node_blk = jnp.clip(node_blk, 0, P)
    # blocks past a node's real (padded) rows are all -1 ids -> zero weights

    valid = (padded_idx >= 0)
    safe_idx = jnp.maximum(padded_idx, 0)
    bb_all = binned[safe_idx]                        # (N_pad, F) uint8
    if quantized:
        # int8 operand lanes: |qg| <= 64 and qh <= 127 by the quant_bins
        # cap, so the per-row values are exact; accumulation is int32
        vi = valid.astype(jnp.int32)
        w_ch = jnp.stack([g[safe_idx] * vi, h[safe_idx] * vi, vi],
                         axis=0).astype(jnp.int8)               # (3, N_pad)
        return bb_all, w_ch, node_blk, NB
    # bf16x2 decomposition for the MXU inputs: grad/hess are signed and
    # cancellation-sensitive, so each carries a bf16 residual channel; counts
    # (small ints) are exact in bf16.  Accumulation itself is f32 on the MXU.
    gp = g[safe_idx] * valid
    hp = h[safe_idx] * valid
    cp = c[safe_idx] * valid
    g_hi = gp.astype(jnp.bfloat16).astype(jnp.float32)
    h_hi = hp.astype(jnp.bfloat16).astype(jnp.float32)
    if not residuals:
        w_ch = jnp.stack([g_hi, h_hi, cp], axis=0)                  # (3, N_pad)
        return bb_all, w_ch, node_blk, NB
    w5 = jnp.stack([g_hi, gp - g_hi, h_hi, hp - h_hi, cp], axis=0)  # (5, N_pad)
    return bb_all, w5, node_blk, NB


def build_histograms_matmul(binned: jnp.ndarray, grad: jnp.ndarray,
                            hess: jnp.ndarray, node_ids: jnp.ndarray,
                            num_nodes: int, num_bins: int,
                            sample_weight: Optional[jnp.ndarray] = None,
                            block_rows: int = 4096,
                            lo_width: int = 0,
                            residuals: bool = True,
                            max_rows: Optional[int] = None) -> jnp.ndarray:
    """Histogram build as batched one-hot matmuls on the MXU.

    TPU scatter runs ~100M updates/s — far below what the n*F histogram pass
    needs.  This backend reformulates the build so the inner loop is matrix
    multiplication:

    1. rows are sorted by node and padded so every `block_rows` block is
       node-pure (one bounded-size scatter of int32 row ids, not n*F floats);
    2. each 8-bit bin splits into hi/lo parts (``lo_width`` lanes wide); a
       block's histogram is the pair of one-hot indicators contracted over
       rows — ``einsum('rfm,rfl->fml', onehot_hi * weight, onehot_lo)`` —
       which XLA lowers to F-batched matmuls on the systolic array;
    3. block results accumulate into per-node buffers in a `lax.scan`.

    Masked rows (node < 0) land in a dummy node whose buffer is dropped.
    Exact: every (row, feature) contributes to exactly one (hi, lo) cell.

    The pass is HBM-bound, not MXU-bound (measured r4): traffic per
    (row, feature) is ``2*(C*HI + LO)`` bytes of materialized bf16 one-hot
    operands plus the per-block f32 accumulator round-trip.  Hence the
    knobs: larger ``block_rows`` cuts accumulator traffic ~linearly;
    ``lo_width=64`` (hi=4) shrinks the weighted operand from 5*16 to 5*4
    channels (the MXU time is invariant to the split — M*N stays C*B);
    ``residuals=False`` drops the two bf16-residual channels (inputs round
    to bf16, accumulation stays exact f32 — LightGBM's own histograms are
    f32) for another ~40% operand-traffic cut; ``max_rows`` (a static caller
    guarantee on the unmasked row count — see ``_node_pure_layout``)
    truncates the scan itself, LightGBM's smaller-child halving.
    """
    import jax
    import jax.numpy as jnp

    n, F = binned.shape
    B = num_bins
    if B > 256:
        raise ValueError("matmul backend supports max_bin <= 256")
    LO = lo_width or 16
    if LO not in (16, 32, 64, 128):
        raise ValueError("lo_width must be one of 16/32/64/128")
    HI = (B + LO - 1) // LO
    shift = LO.bit_length() - 1
    P = num_nodes
    # small inputs: shrink the block so padding (one block minimum per node)
    # stays proportionate
    R = min(block_rows, max(256, 1 << max(0, (n - 1)).bit_length()))

    bb_all, w_ch, node_blk, NB = _node_pure_layout(
        binned, grad, hess, node_ids, num_nodes, R, sample_weight,
        residuals=residuals, max_rows=max_rows)
    C = w_ch.shape[0]                                # 5 or 3 channels

    hi_iota = jnp.arange(HI, dtype=jnp.int32)
    lo_iota = jnp.arange(LO, dtype=jnp.int32)

    def body(acc, args):
        bb, w, nb = args                             # (R,F) u8, (C,R), ()
        b32 = bb.astype(jnp.int32)
        hi = b32 >> shift
        lo = b32 & (LO - 1)
        onehot_lo = (lo[:, :, None] == lo_iota).astype(jnp.bfloat16)   # (R,F,LO)
        onehot_hi = (hi[:, :, None] == hi_iota).astype(jnp.bfloat16)   # (R,F,HI)
        # channels merged into the matmul M axis: M = C*HI instead of
        # batched M=LO matmuls -> C x less systolic-array padding waste
        a = (onehot_hi[:, :, None, :] *
             w.T[:, None, :, None].astype(jnp.bfloat16))               # (R,F,C,HI)
        a = a.reshape(R, F, C * HI)
        blk = jnp.einsum("rfm,rfl->fml", a, onehot_lo,
                         preferred_element_type=jnp.float32)           # (F,C*HI,LO)
        return acc.at[nb].add(blk), None

    acc0 = jnp.zeros((P + 1, F, C * HI, LO), jnp.float32)
    acc, _ = jax.lax.scan(
        body, acc0,
        (bb_all.reshape(NB, R, F), jnp.moveaxis(w_ch.reshape(C, NB, R), 1, 0),
         node_blk))
    acc = acc[:P].reshape(P, F, C, HI, LO)                             # split channels
    if residuals:
        acc3 = jnp.stack([acc[:, :, 0] + acc[:, :, 1],
                          acc[:, :, 2] + acc[:, :, 3], acc[:, :, 4]], axis=0)
    else:
        acc3 = jnp.moveaxis(acc, 2, 0)
    hist = acc3.reshape(3, P, F, HI * LO)[..., :B]                     # (3,P,F,B)
    return jnp.moveaxis(hist, 0, -1)                                    # (P,F,B,3)


# ---------------------------------------------------------------------------
# quantized-gradient packed histograms (LightGBM 4.x quantized training)
# ---------------------------------------------------------------------------
#
# "Quantized Training of Gradient Boosting Decision Trees": per-row grad/hess
# quantize ONCE PER ITERATION to low-bit integers with stochastic rounding and
# per-iteration scale factors; the histogram build then accumulates packed
# integers instead of three f32 channels, and split gains are computed from
# the rescaled integer sums.  Because every level of a tree reuses the SAME
# per-row integers, sibling subtraction (right = parent - left) is EXACT in
# integer space — no f32 cancellation drift between levels.

def global_row_ids(axis_name: Optional[str], n: int):
    """Global ids of this shard's ``n`` contiguous rows, or None when
    unsharded (local ids are already global).  THE formula the elastic
    bit-identity contract rides (ISSUE 14): with contiguous block
    sharding, real rows keep identical ids at ANY shard count, so
    rounding noise keyed on them is width-independent — both growers
    must use this one helper, never a local copy."""
    if axis_name is None:
        return None
    return jax.lax.axis_index(axis_name) * n + jnp.arange(n)


def quantize_gradients(grad, hess, quant_bins: int, seed: int = 0,
                       axis_name: Optional[str] = None,
                       g_scale=None, h_scale=None,
                       row_ids=None, mix=None):
    """Stochastically round per-row grad/hess to small signed/unsigned ints.

    Returns ``(qg, qh, g_scale, h_scale)`` with ``qg`` in
    ``[-quant_bins//2, quant_bins//2]`` (int32), ``qh`` in
    ``[0, quant_bins - 1]`` (int32), and ``E[qg * g_scale] == grad`` /
    ``E[qh * h_scale] == hess`` (stochastic rounding is unbiased:
    ``floor(x + u)``, ``u ~ U[0, 1)``).  Scales are per-call (one boosting
    iteration); with ``axis_name`` they are ``pmax``'d over the mesh so
    every shard quantizes in the SAME units and the psum'd integer
    histograms stay meaningful.

    Passing ``g_scale``/``h_scale`` (both or neither) skips the max pass
    and quantizes in the CALLER's units — the out-of-core tile stream
    computes global maxima in a first pass over every tile, then hands
    each tile the same scales so per-tile integer partial histograms
    accumulate exactly (the tile-level twin of the ``pmax`` contract).
    The values are clipped to the integer caps either way, so a stale
    (too-small) scale degrades resolution, never correctness.

    The rounding noise needs no host RNG plumbing: the PRNG key folds in a
    bitcast of the gradient sum, which changes every iteration (the scores
    moved), decorrelating rounding patterns across iterations while staying
    deterministic and tracer-safe.

    Topology independence (elastic resume, ISSUE 14): with ``row_ids``
    given (the GLOBAL row index of each local row), the per-row noise is
    counter-based — ``u(row) = uniform(fold_in(key, row_id))`` — so a row
    rounds identically no matter which shard or tile holds it.  The key
    itself must then also be topology-free: inside ``shard_map``
    (``axis_name`` set) it folds an exact INTEGER psum of the bitcast
    |grad|/hess magnitudes (integer adds are associative, so 4 shards and
    8 shards fold the same value; |g| zeroes the sign bit so ``-0.0`` pad
    rows cannot skew the count); single-shard callers that stream tiles
    pass ``mix`` (an int32 computed once over the whole row space) for the
    same guarantee.  Without ``row_ids`` the original shape-keyed draw is
    preserved bit-for-bit.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jrandom

    g = grad.astype(jnp.float32)
    h = hess.astype(jnp.float32)
    qg_cap = max(1, quant_bins // 2)
    qh_cap = max(1, quant_bins - 1)
    if (g_scale is None) != (h_scale is None):
        raise ValueError("pass both g_scale and h_scale or neither")
    if g_scale is None:
        gmax = jnp.max(jnp.abs(g))
        hmax = jnp.max(h)
        if axis_name is not None:
            gmax = jax.lax.pmax(gmax, axis_name)
            hmax = jax.lax.pmax(hmax, axis_name)
        g_scale = jnp.maximum(gmax, 1e-12) / qg_cap
        h_scale = jnp.maximum(hmax, 1e-12) / qh_cap
    else:
        g_scale = jnp.maximum(jnp.asarray(g_scale, jnp.float32), 1e-30)
        h_scale = jnp.maximum(jnp.asarray(h_scale, jnp.float32), 1e-30)
    if mix is None:
        if row_ids is not None and axis_name is not None:
            # exact integer fold: associative across any shard layout
            mix = jax.lax.psum(
                jnp.sum(jax.lax.bitcast_convert_type(jnp.abs(g), jnp.int32))
                + 3 * jnp.sum(jax.lax.bitcast_convert_type(h, jnp.int32)),
                axis_name)
        else:
            mix = jax.lax.bitcast_convert_type(
                jnp.sum(g) + 3.0 * jnp.sum(h), jnp.int32)
    key = jrandom.fold_in(jrandom.PRNGKey(seed),
                          jnp.asarray(mix, jnp.int32))
    if row_ids is not None:
        if g.ndim != 1:
            raise ValueError("row_ids quantization expects 1-d grad/hess "
                             f"(got shape {g.shape})")
        row_keys = jax.vmap(lambda i: jrandom.fold_in(key, i))(
            jnp.asarray(row_ids, jnp.int32))
        u = jnp.moveaxis(
            jax.vmap(lambda k: jrandom.uniform(k, (2,)))(row_keys), -1, 0)
    else:
        u = jrandom.uniform(key, (2,) + g.shape)
    qg = jnp.clip(jnp.floor(g / g_scale + u[0]),
                  -qg_cap, qg_cap).astype(jnp.int32)
    qh = jnp.clip(jnp.floor(h / h_scale + u[1]),
                  0, qh_cap).astype(jnp.int32)
    return qg, qh, g_scale, h_scale


def dequantize_histogram(hist_i32, g_scale, h_scale):
    """(..., 3) int32 [sum_qg, sum_qh, count] -> (..., 3) f32
    [sum_grad, sum_hess, count] — the rescale applied at split-gain time."""
    import jax.numpy as jnp
    f = hist_i32.astype(jnp.float32)
    return jnp.stack([f[..., 0] * g_scale, f[..., 1] * h_scale, f[..., 2]],
                     axis=-1)


def _packed_layout(bound: int, quant_bins: int):
    """Static lane plan for the scatter backend's int32 accumulation.

    ``bound`` is the max rows any single (node, feature, bin) cell can
    receive (== max rows per node).  The widest layout that still fits 31
    bits wins — bit-width WIDENING as node row counts grow:

    - ``all3``: grad, hess AND count share ONE int32 channel
      (1 segment-sum instead of 3 — the deep-level / many-node regime);
    - ``2ch``: grad alone + (hess, count) packed in the hessian lane's
      spare bits (2 segment-sums);
    - ``wide``: three separate int32 channels (root-scale nodes; exact for
      any n with ``n * (quant_bins - 1) < 2**31``).
    """
    qg_cap = max(1, quant_bins // 2)
    qh_cap = max(1, quant_bins - 1)
    cbits = bound.bit_length()
    hbits = (bound * qh_cap).bit_length()
    gbits = (bound * qg_cap).bit_length()
    if cbits + hbits + gbits <= 31:
        return "all3", cbits, hbits
    if cbits + hbits <= 31:
        return "2ch", cbits, hbits
    return "wide", cbits, hbits


def _pack_lanes(qg, qh, mode: str, cbits: int, hbits: int):
    """Per-row packed int32 weight channels for a ``_packed_layout`` plan.
    ONE definition shared by the XLA scatter builder and the Pallas kernel
    — the cross-backend bit-exactness contract depends on both sides
    packing (and ``_unpack_lanes`` decoding) identically."""
    import jax.numpy as jnp
    KC, KH = 1 << cbits, 1 << hbits
    qg = qg.astype(jnp.int32)
    qh = qh.astype(jnp.int32)
    if mode == "all3":
        return [((qg * KH) + qh) * KC + 1]
    if mode == "2ch":
        return [qg, qh * KC + 1]
    return [qg, qh, jnp.ones_like(qg)]


def _unpack_lanes(acc, mode: str, cbits: int, hbits: int):
    """Decode accumulated packed-lane sums -> ``(qg_sum, qh_sum, count)``.
    Elementwise, so it serves any channel shape.  The lane terms are
    multiples of KC/KH, so floor mod/div decode exactly — negative sums
    included."""
    KC, KH = 1 << cbits, 1 << hbits
    if mode == "all3":
        s = acc[0]
        count = s % KC
        s2 = (s - count) // KC
        qh_s = s2 % KH
        qg_s = (s2 - qh_s) // KH
    elif mode == "2ch":
        qg_s = acc[0]
        count = acc[1] % KC
        qh_s = (acc[1] - count) // KC
    else:
        qg_s, qh_s, count = acc[0], acc[1], acc[2]
    return qg_s, qh_s, count


def build_histograms_quantized(binned: jnp.ndarray, qg: jnp.ndarray,
                               qh: jnp.ndarray, node_ids: jnp.ndarray,
                               num_nodes: int, num_bins: int,
                               quant_bins: int = 16,
                               node_rows_bound: Optional[int] = None,
                               max_rows: Optional[int] = None) -> jnp.ndarray:
    """Packed-integer scatter build: one int32 segment-sum pass instead of
    three f32 ones whenever the static ``node_rows_bound`` lets the lanes
    coexist (see ``_packed_layout``).

    Args mirror ``build_histograms`` except grad/hess arrive pre-quantized
    (``quantize_gradients``).  ``node_rows_bound`` is a STATIC caller
    guarantee on the max rows any node receives; like ``max_rows`` it is a
    trace-time contract — a violated bound silently corrupts lanes, so
    callers must pass a true bound (or None for the safe n default).

    Returns (num_nodes, F, B, 3) **int32**: [sum_qg, sum_qh, count].
    """
    import jax
    import jax.numpy as jnp

    n, F = binned.shape
    B = num_bins
    S = num_nodes * F * B
    node = node_ids.astype(jnp.int32)
    qg = qg.astype(jnp.int32)
    qh = qh.astype(jnp.int32)
    bound = max(1, min(n, int(node_rows_bound or n), int(max_rows or n)))
    qh_cap = max(1, quant_bins - 1)
    if n * qh_cap >= (1 << 31):
        raise ValueError("quantized histograms overflow int32 above "
                         f"{(1 << 31) // qh_cap} rows at {quant_bins} bins")
    mode, cbits, hbits = _packed_layout(bound, quant_bins)
    chans = _pack_lanes(qg, qh, mode, cbits, hbits)

    chunk = max(1024, min(n, (1 << 23) // max(F, 1)))
    n_pad = -n % chunk
    if n_pad:
        node = jnp.concatenate([node, jnp.full((n_pad,), -1, jnp.int32)])
        b_mat = jnp.concatenate([binned, jnp.zeros((n_pad, F), binned.dtype)])
        chans = [jnp.concatenate([c, jnp.zeros((n_pad,), jnp.int32)])
                 for c in chans]
    else:
        b_mat = binned
    R = (n + n_pad) // chunk
    f_idx = jnp.arange(F, dtype=jnp.int32)[None, :]
    nc = len(chans)

    def body(acc, args):
        b_c, node_c = args[0], args[-1]
        seg = ((node_c[:, None] * F + f_idx) * B + b_c.astype(jnp.int32)).reshape(-1)
        sums = [jax.ops.segment_sum(
            jnp.broadcast_to(x[:, None], (chunk, F)).reshape(-1), seg,
            num_segments=S) for x in args[1:-1]]
        return tuple(a + s for a, s in zip(acc, sums)), None

    init = (jnp.zeros((S,), jnp.int32),) * nc
    acc, _ = jax.lax.scan(
        body, init,
        (b_mat.reshape(R, chunk, F),
         *[c.reshape(R, chunk) for c in chans],
         node.reshape(R, chunk)))
    qg_s, qh_s, count = _unpack_lanes(acc, mode, cbits, hbits)
    return jnp.stack([qg_s, qh_s, count], axis=-1).reshape(
        num_nodes, F, B, 3)


def build_histograms_matmul_quantized(binned: jnp.ndarray, qg: jnp.ndarray,
                                      qh: jnp.ndarray, node_ids: jnp.ndarray,
                                      num_nodes: int, num_bins: int,
                                      quant_bins: int = 16,
                                      block_rows: int = 4096,
                                      lo_width: int = 0,
                                      max_rows: Optional[int] = None
                                      ) -> jnp.ndarray:
    """Packed-integer MXU build: the bandwidth lever on TPU.

    Same node-pure block layout as ``build_histograms_matmul``, but the
    weighted one-hot operands are **int8** (quantized values fit int8 up to
    128 quantization levels) and the einsum accumulates **int32** on the
    MXU's integer path.  Operand traffic per (row, feature) drops from
    ``2*(5*HI + LO)`` bytes (bf16, residual channels) to ``3*HI + LO``
    bytes — the ~3x hot-kernel bandwidth cut — and per-block integer sums
    are exact, so cross-level sibling subtraction is too.

    Returns (num_nodes, F, B, 3) **int32**: [sum_qg, sum_qh, count].
    """
    import jax
    import jax.numpy as jnp

    n, F = binned.shape
    B = num_bins
    if B > 256:
        raise ValueError("matmul backend supports max_bin <= 256")
    if quant_bins > 128:
        raise ValueError("int8 operand lanes cap num_grad_quant_bins at 128")
    qh_cap = max(1, quant_bins - 1)
    if n * qh_cap >= (1 << 31):
        raise ValueError("quantized histograms overflow int32 above "
                         f"{(1 << 31) // qh_cap} rows at {quant_bins} bins")
    LO = lo_width or 16
    if LO not in (16, 32, 64, 128):
        raise ValueError("lo_width must be one of 16/32/64/128")
    HI = (B + LO - 1) // LO
    shift = LO.bit_length() - 1
    P = num_nodes
    R = min(block_rows, max(256, 1 << max(0, (n - 1)).bit_length()))

    bb_all, w_ch, node_blk, NB = _node_pure_layout(
        binned, qg, qh, node_ids, num_nodes, R, quantized=True,
        max_rows=max_rows)
    C = 3                                            # qg, qh, count

    hi_iota = jnp.arange(HI, dtype=jnp.int32)
    lo_iota = jnp.arange(LO, dtype=jnp.int32)

    def body(acc, args):
        bb, w, nb = args                             # (R,F) u8, (C,R) i8, ()
        b32 = bb.astype(jnp.int32)
        hi = b32 >> shift
        lo = b32 & (LO - 1)
        onehot_lo = (lo[:, :, None] == lo_iota).astype(jnp.int8)       # (R,F,LO)
        onehot_hi = (hi[:, :, None] == hi_iota).astype(jnp.int8)       # (R,F,HI)
        a = onehot_hi[:, :, None, :] * w.T[:, None, :, None]           # (R,F,C,HI)
        a = a.reshape(R, F, C * HI)
        blk = jnp.einsum("rfm,rfl->fml", a, onehot_lo,
                         preferred_element_type=jnp.int32)             # (F,C*HI,LO)
        return acc.at[nb].add(blk), None

    acc0 = jnp.zeros((P + 1, F, C * HI, LO), jnp.int32)
    acc, _ = jax.lax.scan(
        body, acc0,
        (bb_all.reshape(NB, R, F),
         jnp.moveaxis(w_ch.reshape(C, NB, R), 1, 0), node_blk))
    acc = acc[:P].reshape(P, F, C, HI, LO)
    hist = jnp.moveaxis(acc, 2, 0).reshape(3, P, F, HI * LO)[..., :B]
    return jnp.moveaxis(hist, 0, -1)                                   # (P,F,B,3)


def _pallas_pref():
    """``MMLSPARK_TPU_HIST_PALLAS`` hatch: 1/true forces the fused Pallas
    backend into the auto choice on ANY platform (interpret mode off-TPU),
    0/false keeps auto off it, unset = auto-select on TPU only.  Explicit
    ``backend=``/``MMLSPARK_TPU_HIST_BACKEND`` settings always win."""
    import os
    raw = os.environ.get("MMLSPARK_TPU_HIST_PALLAS", "").strip().lower()
    if raw in ("0", "false", "off", "no"):
        return False
    if raw in ("1", "true", "on", "yes"):
        return True
    return None


def resolve_quantized_backend(backend: str = "auto") -> str:
    """Resolve the quantized-build backend the way ``build_quantized``
    will: explicit caller choice > ``MMLSPARK_TPU_HIST_BACKEND`` env >
    platform auto (TPU -> the fused Pallas kernel unless the
    ``MMLSPARK_TPU_HIST_PALLAS=0`` hatch says otherwise; CPU -> scatter;
    other accelerators -> matmul).  The growers call this at trace time to
    decide whether the fused frontier path engages — the env knobs are part
    of every jit cache key (``lightgbm.core._resolve_hist_backend``)."""
    import os
    if backend == "auto":
        backend = os.environ.get("MMLSPARK_TPU_HIST_BACKEND", "auto")
    if backend != "auto":
        return backend
    pref = _pallas_pref()
    if pref is True:
        return "pallas"
    plat = jax.default_backend()
    if plat == "cpu":
        return "scatter"
    if plat == "tpu" and pref is not False:
        return "pallas"
    return "matmul"


def build_quantized(binned, qg, qh, node_ids, num_nodes, num_bins,
                    quant_bins: int = 16, backend: str = "auto",
                    max_rows=None, node_rows_bound=None):
    """Quantized-path backend dispatcher, mirroring ``build``: 'auto' picks
    the fused Pallas kernel on TPU (``MMLSPARK_TPU_HIST_PALLAS=0/1``
    hatch; interpret mode everywhere else), the int8 MXU build on other
    accelerators and the packed int32 scatter on CPU;
    ``MMLSPARK_TPU_HIST_BACKEND`` overrides only when the caller did not
    request a specific backend.  Returns int32 (nodes, F, B, 3)
    [sum_qg, sum_qh, count] — rescale with ``dequantize_histogram``."""
    import os
    backend = resolve_quantized_backend(backend)
    if backend == "pallas":
        from . import pallas_histogram as _plh
        if _plh.pallas_supported(num_bins, quant_bins, num_nodes=num_nodes):
            return _plh.build_histograms_pallas(
                binned, qg, qh, node_ids, num_nodes, num_bins,
                quant_bins=quant_bins, node_rows_bound=node_rows_bound,
                max_rows=max_rows)
        # clean fallback: unsupported shape (bins/quant range, or a node
        # frontier wider than the kernel's VMEM node cap — deep-level/
        # sharded/streamed builds) -> the XLA builders
        backend = "scatter" if jax.default_backend() == "cpu" else "matmul"
    if backend == "matmul":
        kw = {}
        block_rows = int(os.environ.get("MMLSPARK_TPU_HIST_BLOCK_ROWS", "0"))
        if block_rows:
            kw["block_rows"] = block_rows
        lo = int(os.environ.get("MMLSPARK_TPU_HIST_LO", "0"))
        if lo:
            kw["lo_width"] = lo
        return build_histograms_matmul_quantized(
            binned, qg, qh, node_ids, num_nodes, num_bins,
            quant_bins=quant_bins, max_rows=max_rows, **kw)
    return build_histograms_quantized(
        binned, qg, qh, node_ids, num_nodes, num_bins,
        quant_bins=quant_bins, node_rows_bound=node_rows_bound,
        max_rows=max_rows)


def build(binned, grad, hess, node_ids, num_nodes, num_bins,
          sample_weight=None, backend: str = "auto", max_rows=None):
    """Backend dispatcher.  'auto' picks the MXU matmul build on accelerator
    platforms (13x faster than scatter on v5e, measured) and the scatter
    build on CPU (where one-hot matmuls lose).  The round-3/4 FLOAT Pallas
    kernel was retired in round 5 (lost the shootout 3.5x, Mosaic
    grad-channel drift — PARITY.md); its ISSUE-8 successor
    (``ops.pallas_histogram``) is integer-only and lives on the QUANTIZED
    path (``build_quantized``), so a 'pallas' request here falls back
    cleanly to the surviving float builders.  Override via
    MMLSPARK_TPU_HIST_BACKEND=matmul|scatter."""
    import os
    if backend == "auto":  # env override only applies when the caller did
        backend = os.environ.get("MMLSPARK_TPU_HIST_BACKEND", backend)
        # not request a specific backend (ADVICE r2)
    if backend in ("auto", "pallas"):
        backend = "scatter" if jax.default_backend() == "cpu" else "matmul"
    # MXU tuning knobs (read at trace time; train() keys its jit caches on
    # them): block size, lo one-hot width, residual channels on/off
    block_rows = int(os.environ.get("MMLSPARK_TPU_HIST_BLOCK_ROWS", "0")) or None
    if backend == "matmul":
        kw = {}
        if block_rows:
            kw["block_rows"] = block_rows
        lo = int(os.environ.get("MMLSPARK_TPU_HIST_LO", "0"))
        if lo:
            kw["lo_width"] = lo
        if os.environ.get("MMLSPARK_TPU_HIST_RESID", "1") == "0":
            kw["residuals"] = False
        return build_histograms_matmul(binned, grad, hess, node_ids,
                                       num_nodes, num_bins, sample_weight,
                                       max_rows=max_rows, **kw)
    # scatter drops masked rows natively; the max_rows bound is a no-op there
    return build_histograms(binned, grad, hess, node_ids, num_nodes, num_bins,
                            sample_weight)
