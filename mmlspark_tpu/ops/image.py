"""Device-side image ops — the OpenCV replacement for the compute path.

Reference: ``opencv/.../ImageTransformer.scala:42-220`` applies per-row JNI
``Mat`` ops (resize/crop/flip/blur/threshold/color).  TPU-first these are
batched jitted array ops: NHWC uint8/float batches in, XLA fuses the chain.
Decode (png/jpg bytes -> array) stays host-side in ``io.image``.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def resize(images: jnp.ndarray, height: int, width: int,
           method: str = "linear") -> jnp.ndarray:
    """Batched resize, NHWC."""
    n, _, _, c = images.shape
    return jax.image.resize(images.astype(jnp.float32),
                            (n, height, width, c), method=method)


def center_crop(images: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    _, h, w, _ = images.shape
    top = max(0, (h - height) // 2)
    left = max(0, (w - width) // 2)
    return images[:, top:top + height, left:left + width, :]


def crop(images: jnp.ndarray, x: int, y: int, height: int, width: int) -> jnp.ndarray:
    return images[:, y:y + height, x:x + width, :]


def flip(images: jnp.ndarray, horizontal: bool = True) -> jnp.ndarray:
    axis = 2 if horizontal else 1
    return jnp.flip(images, axis=axis)


def normalize(images: jnp.ndarray,
              mean: Sequence[float] = (0.485, 0.456, 0.406),
              std: Sequence[float] = (0.229, 0.224, 0.225),
              scale: float = 1.0 / 255.0) -> jnp.ndarray:
    x = images.astype(jnp.float32) * scale
    return (x - jnp.asarray(mean)) / jnp.asarray(std)


def gaussian_kernel(size: int, sigma: float) -> jnp.ndarray:
    ax = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    g = jnp.exp(-(ax ** 2) / (2.0 * sigma ** 2))
    k = jnp.outer(g, g)
    return k / jnp.sum(k)


def blur(images: jnp.ndarray, kernel_size: int = 5, sigma: float = 1.0) -> jnp.ndarray:
    """Depthwise gaussian blur via conv (VPU/MXU friendly)."""
    k = gaussian_kernel(kernel_size, sigma)
    c = images.shape[-1]
    kern = jnp.tile(k[:, :, None, None], (1, 1, 1, c))  # HWIO depthwise
    x = images.astype(jnp.float32)
    return jax.lax.conv_general_dilated(
        x, kern, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)


def threshold(images: jnp.ndarray, thresh: float, max_val: float = 255.0,
              kind: str = "binary") -> jnp.ndarray:
    x = images.astype(jnp.float32)
    if kind == "binary":
        return jnp.where(x > thresh, max_val, 0.0)
    if kind == "binary_inv":
        return jnp.where(x > thresh, 0.0, max_val)
    if kind == "trunc":
        return jnp.minimum(x, thresh)
    if kind == "tozero":
        return jnp.where(x > thresh, x, 0.0)
    if kind == "tozero_inv":
        return jnp.where(x > thresh, 0.0, x)
    raise ValueError(f"unknown threshold kind {kind!r}")


def to_grayscale(images: jnp.ndarray) -> jnp.ndarray:
    """RGB -> single-channel luminance (color-format op equivalent)."""
    w = jnp.asarray([0.299, 0.587, 0.114])
    return jnp.sum(images.astype(jnp.float32) * w, axis=-1, keepdims=True)


def unroll(images: jnp.ndarray) -> jnp.ndarray:
    """(N,H,W,C) -> (N, H*W*C): reference ``UnrollImage`` (image/)."""
    n = images.shape[0]
    return images.reshape(n, -1)
