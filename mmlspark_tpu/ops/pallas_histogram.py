"""Fused Pallas GBDT frontier kernel — bin-slot lookup + packed-int
accumulation + integer sibling subtraction + an in-kernel split-gain scan,
one VMEM-resident pass per node-frontier step (ISSUE 8).

Why: PR 5's quantized packed histograms cut hot-kernel operand traffic ~3x,
but the frontier step still runs as separate XLA ops — bin one-hot
materialization, histogram accumulation, sibling subtraction, and the
split-gain cumsum+argmax — with HBM round trips between every stage.  Snap
ML (arXiv:1803.06333) shows hierarchical GBDT training is bandwidth-bound
at exactly this seam.  This kernel streams row tiles through VMEM (the
Pallas grid pipeline double-buffers the HBM->VMEM block fetches, so tile
k+1's DMA rides under tile k's compute) and keeps every intermediate — the
bin-slot lookups, the packed per-tile partials, the assembled children
histograms, the dequantized gain tables — on chip.  Only two tensors ever
reach HBM per step: the ``(nodes, F, B, 3)`` int32 histogram (the next
level's parent / the psum / stored-carry operand, which the growers need
regardless) and a 9-float best-split record per (feature block, node).
The full one-hot operands and gain tables never materialize off-chip.

Layout support matrix (``_packed_layout`` from ``ops.histogram`` decides,
exactly as the scatter builder does):

    layout  in-kernel channels  operand dtype (onehot accum)
    all3    1  (grad+hess+count share one int32 lane)   int32
    2ch     2  (count rides the hessian lane)           int32 / int8*
    wide    3  (separate lanes)                         int8

    * int8 whenever the static lane magnitudes fit; the int8 path is the
      MXU operand contract inherited from ``build_histograms_matmul_quantized``.

Accumulation modes (static, chosen per backend):

- ``scatter`` — per-tile packed-lane scatter-add into the VMEM-resident
  accumulator.  The interpret-mode default: Pallas interpret lowers the
  grid to one compiled ``while_loop`` and the scatter to XLA's native
  scatter-add, which is the fastest CPU formulation (and the one the
  tier-1 bit-exactness gate runs).
- ``onehot`` — the hi/lo one-hot matmul formulation (the in-kernel twin of
  the XLA MXU builder): per feature, ``(N*C*HI, R) @ (R, LO)`` integer
  contractions.  The compiled-TPU default; Mosaic has no vector scatter.

Both modes accumulate exact integers, so outputs are bit-identical to
``build_histograms_quantized`` (tested across layouts, ragged tiles and
streamed per-tile accumulation).  Interpret mode is the correctness
contract this container can gate; the on-chip (Mosaic-compiled) number is
recorded at the next TPU bench round (``bench.py phase_hist_ab`` fused arm
runs the real kernel there; the round-5 retirement of the *float* Pallas
histogram — Mosaic grad-channel drift, see PARITY.md — does not apply to
this integer kernel, whose sums carry no rounding to drift).

VMEM tile-sizing rule (docs/lightgbm.md): with row tile R, feature block
FB, N frontier nodes and C lane channels, the resident set is the binned
tile (R*FB bytes), the one-hot operands (R*FB*(LO + N*C*HI) operand
bytes), and the accumulator (C*N*FB*B*4 bytes); the compiled default
R=1024, FB=8 keeps the sum (double-buffered) well under the 16 MB VMEM
budget up to N=16 frontier nodes at B=256.  Interpret mode uses large
tiles (R = (1<<23)/F — the XLA scatter builder's chunk rule, FB=F): the
grid is a while_loop, so fewer/fatter steps win, while the rule keeps
the per-step scatter intermediate at ~32 MB.

Split-gain contract: the in-kernel scan mirrors the growers' gain math
(dequantize -> f32 bin cumsum -> leaf_score with l1/l2 ->
min_data/min_hess/feat-mask/edge-mask validity -> first-max argmax) with
one deliberate difference: node totals come from the EXACT integer bin
sums (scaled once) instead of the f32 cumsum's last element, so totals are
consistent across feature blocks (the XLA path's totals carry cumsum
rounding).  Split decisions agree except at sub-ulp gain ties; the e2e
accuracy gates hold either way (tests/test_pallas_histogram.py).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram import _pack_lanes, _packed_layout, _unpack_lanes

_CHANNELS = {"all3": 1, "2ch": 2, "wide": 3}
_LO = 16  # lo one-hot width of the onehot accumulation mode

#: max frontier nodes (the kernel's N) the VMEM tile-sizing rule holds
#: for at the compiled defaults (R=1024, FB=8, B<=256): the per-block
#: resident set — (2N, FB, B, 3) hist out, (N, FB, B, 3) parent,
#: (C, N, FB, B) scratch — scales linearly with N and clears the 16 MB
#: budget up to here.  The level-wise grower statically falls back to
#: the XLA scan for deeper levels (interpret mode enforces the same cap
#: so tier-1 exercises exactly what the compiled path runs).
FUSED_MAX_NODES = 16


def builder_node_cap(num_bins: int) -> int:
    """Max ``num_nodes`` the BUILDER path clears the VMEM budget for at the
    compiled defaults (FB=8): per feature block the resident set is the
    double-buffered ``(N, FB, B, 3)`` int32 output plus the ``(C<=3, N,
    FB, B)`` int32 scratch accumulator — 36·FB·B bytes per node — and a
    12 MiB slice of the 16 MiB budget leaves headroom for the input
    blocks.  ``FUSED_MAX_NODES`` gates the growers' fused-frontier calls;
    this cap gates everything else reaching ``build_histograms_pallas``
    through the dispatcher (deep-level, sharded and streamed builds pass
    frontier widths up to 2^(D-1) nodes), which falls back to the XLA
    builders above it.  Static, platform-independent: interpret mode
    enforces the same cap so tier-1 exercises the exact dispatch the
    compiled path takes."""
    return max(1, (12 << 20) // (36 * 8 * num_bins))


def pallas_supported(num_bins: int, quant_bins: int = 16,
                     num_nodes: Optional[int] = None) -> bool:
    """Static support check for the fused kernel: callers fall back to the
    XLA builders (scatter/matmul) when this is False.  Pass ``num_nodes``
    on the builder path — the per-block VMEM resident set scales linearly
    with it (``builder_node_cap``)."""
    if not (2 <= num_bins <= 256 and 2 <= quant_bins <= 128):
        return False
    return num_nodes is None or num_nodes <= builder_node_cap(num_bins)


def _interpret_default() -> bool:
    # the compiled (Mosaic) path is TPU-only; everything else runs the
    # kernel under the Pallas interpreter, which lowers to plain XLA
    return jax.default_backend() != "tpu"


def _plan(n: int, F: int, interpret: bool,
          tile_rows: Optional[int], feat_block: Optional[int]) -> Tuple[int, int]:
    """(row tile R, feature block FB) — the VMEM tile-sizing rule."""
    if tile_rows is None:
        if interpret:
            # interpret = one while_loop over the grid: few fat tiles win.
            # Same chunk rule as the XLA scatter builder — the per-step
            # (R*FB,) scatter intermediate stays ~32 MB while the grid
            # degenerates to a single step whenever n fits
            tile_rows = max(1024, (1 << 23) // max(F, 1))
        else:
            tile_rows = 1024
    if feat_block is None:
        feat_block = F if interpret else min(F, 8)
    return max(1, min(int(tile_rows), n)), max(1, min(int(feat_block), F))


def _lane_cap(mode: str, cbits: int, hbits: int, quant_bins: int) -> int:
    """Static max |channel value| — decides the onehot operand dtype."""
    qg_cap = max(1, quant_bins // 2)
    qh_cap = max(1, quant_bins - 1)
    KC, KH = 1 << cbits, 1 << hbits
    if mode == "all3":
        return (qg_cap * KH + qh_cap) * KC + 1
    if mode == "2ch":
        return max(qg_cap, qh_cap * KC + 1)
    return max(qg_cap, qh_cap, 1)


def _make_kernel(*, n, F, B, N, C, mode, cbits, hbits, R, FB, NR, accum,
                 subtract, gains, leaf_gate, l1, l2, min_data, min_hess,
                 op_dtype, HI, shift):
    """Build the kernel body for one static configuration.  Grid is
    (feature blocks, row tiles) with row tiles innermost; the packed
    accumulator lives in VMEM scratch and persists across the row-tile
    sweep of each feature block."""
    S = N * FB * B
    n_out = 2 * N if subtract else N

    def thresh(G):
        return jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)

    def score(G, H):
        return thresh(G) ** 2 / (H + l2)

    def decode(acc):  # (C, N, FB, B) packed lanes -> (N, FB, B, 3) int32
        return jnp.stack(_unpack_lanes(acc, mode, cbits, hbits), axis=-1)

    def kernel(*refs):
        it = iter(refs)
        b_ref = next(it)
        lanes_ref = next(it)
        node_ref = next(it)
        parent_ref = next(it) if subtract else None
        sleft_ref = next(it) if subtract else None
        if gains:
            gsc_ref = next(it)
            hsc_ref = next(it)
            fmask_ref = next(it)
            edge_ref = next(it)
            dok_ref = next(it) if leaf_gate else None
        hist_ref = next(it)
        best_ref = next(it) if gains else None
        acc_ref = next(it)

        j = pl.program_id(0)
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        b32 = b_ref[...].astype(jnp.int32)                       # (R, FB)
        node = node_ref[0, :]                                    # (R,)
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (R, FB), 0)
        f_ids = jax.lax.broadcasted_iota(jnp.int32, (R, FB), 1)
        # ragged edges are masked in-kernel, never padded on the host:
        # rows past n (last row tile) and features past F (last feature
        # block) read block-padding garbage, which must not contribute
        valid = (node[:, None] >= 0) & (row_ids < n - i * R) \
            & (f_ids + j * FB < F)

        if accum == "scatter":
            seg = (node[:, None] * FB + f_ids) * B + b32
            seg = jnp.where(valid, seg, S).reshape(-1)           # OOB drops
            for c in range(C):
                vals = jnp.broadcast_to(lanes_ref[c, :][:, None],
                                        (R, FB)).reshape(-1)
                part = jnp.zeros((S,), jnp.int32).at[seg].add(vals,
                                                              mode="drop")
                acc_ref[c] += part.reshape(N, FB, B)
        else:
            hi = b32 >> shift
            lo = b32 & (_LO - 1)
            node_oh = (node[:, None] ==
                       jax.lax.broadcasted_iota(jnp.int32, (R, N), 1))
            w = jnp.stack([lanes_ref[c, :] for c in range(C)], axis=-1)
            wn = (node_oh[:, :, None] * w[:, None, :]).reshape(R, N * C)
            lo_oh = ((lo[:, :, None] ==
                      jax.lax.broadcasted_iota(jnp.int32, (R, FB, _LO), 2))
                     & valid[..., None]).astype(op_dtype)        # (R,FB,LO)
            hi_oh = (hi[:, :, None] ==
                     jax.lax.broadcasted_iota(jnp.int32, (R, FB, HI), 2))
            a = (hi_oh[:, :, None, :] *
                 wn[:, None, :, None].astype(op_dtype)) \
                .reshape(R, FB, N * C * HI)                      # (R,FB,NCH)
            for f in range(FB):
                m = jax.lax.dot_general(
                    a[:, f, :], lo_oh[:, f, :], (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)            # (NCH, LO)
                m = m.reshape(N, C, HI * _LO)[..., :B]
                acc_ref[:, :, f, :] += jnp.moveaxis(m, 1, 0)

        @pl.when(i == NR - 1)
        def _finish():
            hist_small = decode(acc_ref[...])                    # (N,FB,B,3)
            if subtract:
                parent = parent_ref[...]
                sib = parent - hist_small                        # exact ints
                sl = (sleft_ref[0, :] != 0)[:, None, None, None]
                hist_out = jnp.stack(
                    [jnp.where(sl, hist_small, sib),
                     jnp.where(sl, sib, hist_small)],
                    axis=1).reshape(n_out, FB, B, 3)
            else:
                hist_out = hist_small
            hist_ref[...] = hist_out
            if gains:
                gsc = gsc_ref[0, 0]
                hsc = hsc_ref[0, 0]
                # dequantize then f32 cumsum — the growers' exact op order,
                # so left-side stats match the XLA path bit for bit
                GL = jnp.cumsum(hist_out[..., 0].astype(jnp.float32) * gsc,
                                axis=-1)
                HL = jnp.cumsum(hist_out[..., 1].astype(jnp.float32) * hsc,
                                axis=-1)
                CL = jnp.cumsum(hist_out[..., 2].astype(jnp.float32),
                                axis=-1)
                # node totals from the EXACT integer sums (any one in-range
                # feature column carries every row once) — consistent
                # across feature blocks, unlike an f32 cumsum tail
                tg = jnp.sum(hist_out[:, 0, :, 0],
                             axis=-1).astype(jnp.float32) * gsc
                th = jnp.sum(hist_out[:, 0, :, 1],
                             axis=-1).astype(jnp.float32) * hsc
                tc = jnp.sum(hist_out[:, 0, :, 2],
                             axis=-1).astype(jnp.float32)
                GR = tg[:, None, None] - GL
                HR = th[:, None, None] - HL
                CR = tc[:, None, None] - CL
                gain = (score(GL, HL) + score(GR, HR)
                        - score(tg, th)[:, None, None])
                fcol = jax.lax.broadcasted_iota(jnp.int32, (1, FB, 1), 1) \
                    + j * FB
                ok = ((CL >= min_data) & (CR >= min_data)
                      & (HL >= min_hess) & (HR >= min_hess)
                      & (fmask_ref[0, :] != 0)[None, :, None]
                      & (edge_ref[...] != 0)[None]
                      & (fcol < F))
                if leaf_gate:
                    ok &= dok_ref[0, 0] != 0
                gain = jnp.where(ok, gain, -jnp.inf)
                flat = gain.reshape(n_out, FB * B)
                am = jnp.argmax(flat, axis=1)                    # first max

                def take(X):
                    return jnp.take_along_axis(X.reshape(n_out, FB * B),
                                               am[:, None], axis=1)[:, 0]

                best_ref[0] = jnp.stack(
                    [take(gain),
                     (am // B + j * FB).astype(jnp.float32),
                     (am % B).astype(jnp.float32),
                     take(GL), take(HL), take(CL), tg, th, tc], axis=-1)

    return kernel


def _frontier(binned, qg, qh, node_ids, num_nodes, num_bins, *, quant_bins,
              bound, gains, parent_hist=None, small_left=None, g_scale=None,
              h_scale=None, feat_mask=None, edge_ok=None, depth_ok=None,
              l1=0.0, l2=0.0, min_data=0.0, min_hess=0.0, interpret=None,
              accum=None, tile_rows=None, feat_block=None):
    n, F = binned.shape
    B, N = int(num_bins), int(num_nodes)
    if not pallas_supported(B, quant_bins):
        raise ValueError(f"pallas histogram kernel supports 2 <= num_bins "
                         f"<= 256 and quant_bins <= 128, got ({B}, "
                         f"{quant_bins})")
    if gains and N > FUSED_MAX_NODES:
        # the builder path has its own cap (builder_node_cap); the fused
        # path's VMEM rule is only sized up to FUSED_MAX_NODES — past it
        # the compiled kernel would surface an opaque Mosaic OOM instead
        raise ValueError(
            f"fused_frontier VMEM node cap exceeded: {N} frontier nodes > "
            f"FUSED_MAX_NODES={FUSED_MAX_NODES} — callers must fall back "
            "to the XLA gain scan (the growers gate per level)")
    qh_cap = max(1, quant_bins - 1)
    if n * qh_cap >= (1 << 31):
        raise ValueError("quantized histograms overflow int32 above "
                         f"{(1 << 31) // qh_cap} rows at {quant_bins} bins")
    interpret = _interpret_default() if interpret is None else bool(interpret)
    accum = accum or ("scatter" if interpret else "onehot")
    if accum not in ("scatter", "onehot"):
        raise ValueError("accum must be scatter|onehot")
    if accum == "scatter" and not interpret:
        # fail at dispatch with a name, not deep inside kernel compilation:
        # Mosaic has no vector scatter, the compiled path must use onehot
        raise ValueError("accum='scatter' is interpret-only (Mosaic has no "
                         "vector scatter) — use accum='onehot' on TPU")
    R, FB = _plan(n, F, interpret, tile_rows, feat_block)
    NR, NFB = pl.cdiv(n, R), pl.cdiv(F, FB)
    mode, cbits, hbits = _packed_layout(bound, quant_bins)
    C = _CHANNELS[mode]
    cap = _lane_cap(mode, cbits, hbits, quant_bins)
    op_dtype = jnp.int8 if (accum == "onehot" and cap <= 127) else jnp.int32
    HI = pl.cdiv(B, _LO)
    shift = _LO.bit_length() - 1

    subtract = parent_hist is not None
    leaf_gate = depth_ok is not None
    n_out = 2 * N if subtract else N

    lanes = jnp.stack(_pack_lanes(qg, qh, mode, cbits, hbits))     # (C, n)
    node2 = node_ids.astype(jnp.int32)[None, :]                    # (1, n)

    inputs = [binned, lanes, node2]
    in_specs = [
        pl.BlockSpec((R, FB), lambda jj, ii: (ii, jj)),
        pl.BlockSpec((C, R), lambda jj, ii: (0, ii)),
        pl.BlockSpec((1, R), lambda jj, ii: (0, ii)),
    ]
    if subtract:
        if small_left is None:
            raise ValueError("subtract mode needs small_left")
        inputs += [parent_hist.astype(jnp.int32),
                   small_left.astype(jnp.int32)[None, :]]
        in_specs += [
            pl.BlockSpec((N, FB, B, 3), lambda jj, ii: (0, jj, 0, 0)),
            pl.BlockSpec((1, N), lambda jj, ii: (0, 0)),
        ]
    if gains:
        if g_scale is None or h_scale is None or feat_mask is None \
                or edge_ok is None:
            raise ValueError("gain scan needs g_scale/h_scale/feat_mask/"
                             "edge_ok")
        inputs += [jnp.asarray(g_scale, jnp.float32).reshape(1, 1),
                   jnp.asarray(h_scale, jnp.float32).reshape(1, 1),
                   feat_mask.astype(jnp.int32)[None, :],
                   edge_ok.astype(jnp.int32)]
        in_specs += [
            pl.BlockSpec((1, 1), lambda jj, ii: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda jj, ii: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, FB), lambda jj, ii: (0, jj)),
            pl.BlockSpec((FB, B), lambda jj, ii: (jj, 0)),
        ]
        if leaf_gate:
            inputs.append(jnp.asarray(depth_ok, jnp.int32).reshape(1, 1))
            in_specs.append(pl.BlockSpec((1, 1), lambda jj, ii: (0, 0),
                                         memory_space=pltpu.SMEM))

    out_shape = [jax.ShapeDtypeStruct((n_out, F, B, 3), jnp.int32)]
    out_specs = [pl.BlockSpec((n_out, FB, B, 3),
                              lambda jj, ii: (0, jj, 0, 0))]
    if gains:
        out_shape.append(jax.ShapeDtypeStruct((NFB, n_out, 9), jnp.float32))
        out_specs.append(pl.BlockSpec((1, n_out, 9),
                                      lambda jj, ii: (jj, 0, 0)))

    kernel = _make_kernel(
        n=n, F=F, B=B, N=N, C=C, mode=mode, cbits=cbits, hbits=hbits, R=R,
        FB=FB, NR=NR, accum=accum, subtract=subtract, gains=gains,
        leaf_gate=leaf_gate, l1=float(l1), l2=float(l2),
        min_data=float(min_data), min_hess=float(min_hess),
        op_dtype=op_dtype, HI=HI, shift=shift)

    kw = {}
    if not interpret:
        kw["compiler_params"] = pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"))
    # pallas-site: compiled inside the growers'/bench's instrumented_jit
    # programs — compile booking rides lightgbm.grower/iter/multi_iter
    outs = pl.pallas_call(
        kernel,
        grid=(NFB, NR),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((C, N, FB, B), jnp.int32)],
        interpret=interpret,
        **kw,
    )(*inputs)
    if not gains:
        return outs[0]
    hist, best = outs
    # cross-block reduction: first-max-wins over feature blocks replicates
    # the XLA path's flat argmax ordering (lower feature index wins ties)
    jb = jnp.argmax(best[:, :, 0], axis=0)
    win = jnp.take_along_axis(best, jb[None, :, None], axis=0)[0]
    return hist, (win[:, 0], win[:, 1].astype(jnp.int32),
                  win[:, 2].astype(jnp.int32), win[:, 3:6], win[:, 6:9])


def build_histograms_pallas(binned, qg, qh, node_ids, num_nodes, num_bins,
                            quant_bins: int = 16,
                            node_rows_bound: Optional[int] = None,
                            max_rows: Optional[int] = None,
                            interpret: Optional[bool] = None,
                            accum: Optional[str] = None,
                            tile_rows: Optional[int] = None,
                            feat_block: Optional[int] = None):
    """Drop-in quantized histogram builder on the fused Pallas kernel.

    Same contract as ``ops.histogram.build_histograms_quantized`` — returns
    ``(num_nodes, F, B, 3)`` **int32** ``[sum_qg, sum_qh, count]``, bit-exact
    (integer sums) with the scatter/matmul builders, so it composes with
    the growers' integer sibling subtraction, ``train_streamed``'s per-tile
    partial accumulation, and ``collectives.histogram_psum`` unchanged.
    ``max_rows`` is accepted for signature parity and ignored (masked rows
    drop in-kernel; like the scatter builder, no scan is truncated)."""
    n = binned.shape[0]
    cap = builder_node_cap(num_bins)
    if num_nodes > cap:
        raise ValueError(
            f"pallas builder VMEM node cap exceeded: {num_nodes} nodes > "
            f"{cap} at {num_bins} bins — use the XLA builders "
            "(build_quantized falls back automatically)")
    bound = max(1, min(n, int(node_rows_bound or n), int(max_rows or n)))
    return _frontier(binned, qg, qh, node_ids, num_nodes, num_bins,
                     quant_bins=quant_bins, bound=bound, gains=False,
                     interpret=interpret, accum=accum, tile_rows=tile_rows,
                     feat_block=feat_block)


def fused_frontier(binned, qg, qh, node_ids, num_nodes, num_bins,
                   g_scale, h_scale, feat_mask, edge_ok, *,
                   quant_bins: int = 16, l1: float = 0.0, l2: float = 0.0,
                   min_data: float = 0.0, min_hess: float = 0.0,
                   parent_hist=None, small_left=None, depth_ok=None,
                   node_rows_bound: Optional[int] = None,
                   interpret: Optional[bool] = None,
                   accum: Optional[str] = None,
                   tile_rows: Optional[int] = None,
                   feat_block: Optional[int] = None):
    """One fused frontier step: histogram build (+ optional integer sibling
    subtraction against ``parent_hist``) feeding the in-kernel split-gain
    scan.

    Modes:

    - **direct** (``parent_hist=None``): builds ``num_nodes`` frontier
      histograms and scans their best splits — the root step of both
      growers.
    - **subtract** (``parent_hist`` = ``(num_nodes, F, B, 3)`` int32 parent
      histograms, ``small_left`` = ``(num_nodes,)`` bool): ``node_ids``
      address each parent's SMALLER child; the sibling comes from exact
      integer subtraction in VMEM and both children are emitted interleaved
      ``(2*num_nodes, F, B, 3)`` exactly as the level-wise grower assembles
      them (child ``2k`` is the small child iff ``small_left[k]``).

    ``depth_ok`` (optional traced bool) gates every candidate — the
    leaf-wise grower's depth cap.  Returns ``(hist, (best_gain, best_feat,
    best_bin, left_stats, node_totals))`` with per-node f32 stats; callers
    needing LightGBM's full bookkeeping read left/total (G, H, C) straight
    from the tuple instead of re-scanning the histogram."""
    n = binned.shape[0]
    bound = max(1, min(n, int(node_rows_bound or n)))
    return _frontier(binned, qg, qh, node_ids, num_nodes, num_bins,
                     quant_bins=quant_bins, bound=bound, gains=True,
                     parent_hist=parent_hist, small_left=small_left,
                     g_scale=g_scale, h_scale=h_scale, feat_mask=feat_mask,
                     edge_ok=edge_ok, depth_ok=depth_ok, l1=l1, l2=l2,
                     min_data=min_data, min_hess=min_hess,
                     interpret=interpret, accum=accum, tile_rows=tile_rows,
                     feat_block=feat_block)
