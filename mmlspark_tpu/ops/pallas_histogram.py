"""Pallas TPU kernel for the GBDT histogram build — the make-or-break op.

Reference hot loop: LightGBM's C++ ``ConstructHistograms`` inside
``updateOneIteration`` (``booster/LightGBMBooster.scala:351`` dispatches into
the native engine).  SURVEY §7 names the histogram build as the framework's
hardest kernel; ``build_histograms_matmul`` (histogram.py) already reformulates
it as MXU one-hot contractions, but each scan step round-trips its block
one-hots and the (P+1, F, 5*HI, 16) accumulator through HBM.

This kernel fuses the whole pipeline per block — nibble split, one-hot
construction, weight channel broadcast, MXU contraction, and accumulation —
in VMEM.  Layout mirrors the matmul backend (shared ``_node_pure_layout``):

- rows sorted by node, padded so each R-row block is node-pure;
- grid = one step per block, sequential on TPU;
- the OUTPUT BlockSpec's index map routes each step to its node's histogram
  buffer via a scalar-prefetched ``node_blk`` array; consecutive blocks of
  the same node hit the same VMEM-resident buffer (Pallas only writes back
  on index change), and ``pl.when(first-visit)`` zero-initialises it;
- inside, a ``fori_loop`` over features issues (5*HI, R) x (R, 16) MXU dots
  in bf16 with f32 accumulation (the bf16x2 residual channels keep grad/hess
  exact to ~f32).

Numerics are identical to the matmul backend by construction.  On CPU the
kernel runs under ``interpret=True`` (pure-jax semantics) for tests; real
Mosaic lowering is exercised on the TPU platform.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .histogram import _node_pure_layout


@partial(jax.jit, static_argnames=("num_nodes", "num_bins", "block_rows",
                                   "interpret"))
def build_histograms_pallas(binned: jnp.ndarray, grad: jnp.ndarray,
                            hess: jnp.ndarray, node_ids: jnp.ndarray,
                            num_nodes: int, num_bins: int,
                            sample_weight: Optional[jnp.ndarray] = None,
                            block_rows: int = 512,
                            interpret: bool = False) -> jnp.ndarray:
    """(num_nodes, F, num_bins, 3) histogram of (grad, hess, count)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, F = binned.shape
    B = num_bins
    if B > 256:
        raise ValueError("pallas backend supports max_bin <= 256")
    HI = (B + 15) // 16
    LO = 16
    P = num_nodes
    R = block_rows

    bb_all, w5, node_blk, NB = _node_pure_layout(binned, grad, hess, node_ids,
                                                 P, R, sample_weight)
    bb_blocks = bb_all.reshape(NB, R, F)
    w_blocks = jnp.moveaxis(w5.reshape(5, NB, R), 1, 0)   # (NB, 5, R)

    def kernel(nb_ref, bb_ref, w_ref, out_ref):
        i = pl.program_id(0)
        prev = nb_ref[jnp.maximum(i - 1, 0)]
        first = (i == 0) | (nb_ref[i] != prev)

        @pl.when(first)
        def _init():
            out_ref[...] = jnp.zeros(out_ref.shape, out_ref.dtype)

        b32 = bb_ref[0].astype(jnp.int32)             # (R, F)
        w = w_ref[0].astype(jnp.bfloat16)             # (5, R)
        hi = b32 >> 4
        lo = b32 & 15
        lo_iota = jnp.arange(LO, dtype=jnp.int32)
        hi_iota = jnp.arange(HI, dtype=jnp.int32)

        def per_feature(f, carry):
            onehot_lo = (lo[:, f][:, None] == lo_iota).astype(jnp.bfloat16)
            onehot_hi = (hi[:, f][:, None] == hi_iota).astype(jnp.bfloat16)
            # channel-weighted hi one-hots on the MXU M axis, (5, HI) order
            # matching the matmul backend's channel flattening;
            # (5*HI, R) x (R, 16) -> (5*HI, 16) f32
            a = jnp.transpose(w[:, :, None] * onehot_hi[None, :, :],
                              (0, 2, 1)).reshape(5 * HI, R)
            blk = jax.lax.dot(a, onehot_lo,
                              preferred_element_type=jnp.float32)
            out_ref[0, f] = out_ref[0, f] + blk
            return carry

        jax.lax.fori_loop(0, F, per_feature, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,                         # node_blk
        grid=(NB,),
        in_specs=[
            pl.BlockSpec((1, R, F), lambda i, nb: (i, 0, 0)),
            pl.BlockSpec((1, 5, R), lambda i, nb: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, F, 5 * HI, LO),
                               lambda i, nb: (nb[i], 0, 0, 0)),
    )

    acc = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P + 1, F, 5 * HI, LO), jnp.float32),
        interpret=interpret,
    )(node_blk, bb_blocks, w_blocks)

    acc = acc[:P].reshape(P, F, 5, HI, LO)
    acc3 = jnp.stack([acc[:, :, 0] + acc[:, :, 1],
                      acc[:, :, 2] + acc[:, :, 3], acc[:, :, 4]], axis=0)
    hist = acc3.reshape(3, P, F, HI * LO)[..., :B]      # (3, P, F, B)
    return jnp.moveaxis(hist, 0, -1)                    # (P, F, B, 3)
