"""Pallas TPU kernel for the GBDT histogram build — the make-or-break op.

Reference hot loop: LightGBM's C++ ``ConstructHistograms`` inside
``updateOneIteration`` (``booster/LightGBMBooster.scala:351`` dispatches into
the native engine).  SURVEY §7 names the histogram build as the framework's
hardest kernel; ``build_histograms_matmul`` (histogram.py) already reformulates
it as MXU one-hot contractions, but each scan step round-trips its block
one-hots and the (P+1, F, 5*HI, 16) accumulator through HBM.

This kernel fuses the whole pipeline per block — nibble split, one-hot
construction, weight channel broadcast, MXU contraction, and accumulation —
in VMEM.  Layout mirrors the matmul backend (shared ``_node_pure_layout``):

- rows sorted by node, padded so each R-row block is node-pure;
- grid = (feature-block OUTER, row-block INNER), sequential on TPU; every
  index the kernel body touches is STATIC — Mosaic TC lowering has no
  dynamic_slice, so the feature dimension lives in the grid (BlockSpec
  index maps) and the FB features inside a block unroll as a python loop
  (first Mosaic attempt used a ``fori_loop`` + ``lo[:, f]`` and failed to
  lower on exactly that);
- the OUTPUT BlockSpec's index map routes each step to its node's histogram
  buffer via a scalar-prefetched ``node_blk`` array, and
  ``pl.when(first-block-of-node)`` zero-initialises each buffer.  The grid
  order keeps every output buffer's visits CONSECUTIVE (all of a node's row
  blocks inside one feature sweep) — Mosaic's reload of a non-consecutively
  revisited output block is undefined (observed: duplicated accumulation);
- per feature, a (5*HI, R) x (R, 16) MXU dot in bf16 with f32 accumulation
  (the bf16x2 residual channels keep grad/hess exact to ~f32).

Numerics: exact count/hess channels on-chip; the grad channel lands within
~1%% of the f32 scatter truth under real Mosaic lowering (interpret mode is
exact to ~1e-4 — the residual deviation is a Mosaic-side rounding of the
channel pipeline, measured in bench_attempts/, and does not move split
decisions: the pallas-trained booster passes the same held-out accuracy
gates).  On CPU the kernel runs under ``interpret=True`` (pure-jax
semantics) for tests; real Mosaic lowering is exercised on the TPU platform
(tools/hist_backend_probe).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .histogram import _node_pure_layout


@partial(jax.jit, static_argnames=("num_nodes", "num_bins", "block_rows",
                                   "interpret"))
def build_histograms_pallas(binned: jnp.ndarray, grad: jnp.ndarray,
                            hess: jnp.ndarray, node_ids: jnp.ndarray,
                            num_nodes: int, num_bins: int,
                            sample_weight: Optional[jnp.ndarray] = None,
                            block_rows: int = 512,
                            interpret: bool = False) -> jnp.ndarray:
    """(num_nodes, F, num_bins, 3) histogram of (grad, hess, count)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, F = binned.shape
    B = num_bins
    if B > 256:
        raise ValueError("pallas backend supports max_bin <= 256")
    HI = (B + 15) // 16
    LO = 16
    P = num_nodes
    R = block_rows

    bb_all, w5, node_blk, NB = _node_pure_layout(binned, grad, hess, node_ids,
                                                 P, R, sample_weight)
    FB = 8                                            # features per grid step
    F_pad = ((F + FB - 1) // FB) * FB
    FM = F_pad // FB
    # (NB, F_pad, R): BlockSpec slices FB whole feature COLUMNS per step, so
    # in-kernel feature indexing is a static python unroll
    bb_fmajor = jnp.transpose(bb_all.reshape(NB, R, F), (0, 2, 1))
    if F_pad != F:
        bb_fmajor = jnp.pad(bb_fmajor, ((0, 0), (0, F_pad - F), (0, 0)))
    w_blocks = jnp.moveaxis(w5.reshape(5, NB, R), 1, 0)   # (NB, 5, R)

    def kernel(nb_ref, bb_ref, w_ref, out_ref):
        # grid = (feature-block j OUTER, row-block i INNER): within one
        # j-sweep a node's output buffer is visited by CONSECUTIVE steps
        # only — Mosaic revisit semantics for non-consecutive output blocks
        # are undefined (observed: duplicated accumulation at 1M rows)
        i = pl.program_id(1)
        prev = nb_ref[jnp.maximum(i - 1, 0)]
        first = (i == 0) | (nb_ref[i] != prev)

        @pl.when(first)
        def _init():
            out_ref[...] = jnp.zeros(out_ref.shape, out_ref.dtype)

        w32 = w_ref[0]                                # (5, R) f32
        # 2-D iotas: Mosaic rejects 1-D iota
        lo_iota = jax.lax.broadcasted_iota(jnp.int32, (R, LO), 1)
        hi_iota = jax.lax.broadcasted_iota(jnp.int32, (R, HI), 1)
        for fl in range(FB):                          # static unroll
            b32 = bb_ref[0, fl].astype(jnp.int32)     # (R,)
            onehot_lo = ((b32 & 15)[:, None] == lo_iota).astype(jnp.bfloat16)
            onehot_hi = ((b32 >> 4)[:, None] == hi_iota).astype(jnp.float32)
            # channel-weighted hi one-hots on the MXU M axis, (5, HI) order
            # matching the matmul backend's channel flattening; the
            # broadcast-multiply runs in f32 (Mosaic only lowers minor-dim
            # insertion for 32-bit types), the MXU dot takes bf16:
            # (5*HI, R) x (R, 16) -> (5*HI, 16) f32
            a = jnp.transpose(w32[:, :, None] * onehot_hi[None, :, :],
                              (0, 2, 1)).reshape(5 * HI, R) \
                .astype(jnp.bfloat16)
            blk = jax.lax.dot(a, onehot_lo,
                              preferred_element_type=jnp.float32)
            out_ref[0, fl] = out_ref[0, fl] + blk

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,                         # node_blk
        grid=(FM, NB),                                 # j outer, i inner
        in_specs=[
            pl.BlockSpec((1, FB, R), lambda j, i, nb: (i, j, 0)),
            pl.BlockSpec((1, 5, R), lambda j, i, nb: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, FB, 5 * HI, LO),
                               lambda j, i, nb: (nb[i], j, 0, 0)),
    )

    acc = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P + 1, F_pad, 5 * HI, LO),
                                       jnp.float32),
        interpret=interpret,
    )(node_blk, bb_fmajor, w_blocks)

    acc = acc[:P, :F].reshape(P, F, 5, HI, LO)
    acc3 = jnp.stack([acc[:, :, 0] + acc[:, :, 1],
                      acc[:, :, 2] + acc[:, :, 3], acc[:, :, 4]], axis=0)
    hist = acc3.reshape(3, P, F, HI * LO)[..., :B]      # (3, P, F, B)
    return jnp.moveaxis(hist, 0, -1)                    # (P, F, B, 3)
