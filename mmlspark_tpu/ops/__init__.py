from . import image

__all__ = ["image"]
