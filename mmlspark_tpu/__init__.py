"""mmlspark_tpu — a TPU-native rebuild of MMLSpark (Azure/mmlspark).

MMLSpark is an ecosystem of SparkML-compatible estimators/transformers wrapping
native ML engines (LightGBM, VowpalWabbit, CNTK, OpenCV), web services, and
serving infrastructure.  This package re-creates that capability surface
TPU-first:

- compute is JAX/XLA (jit, shard_map over a `jax.sharding.Mesh`); the hot
  ops are formulated MXU-first (histograms as one-hot matmul contractions,
  blockwise ring attention) and left to XLA to fuse — a hand-written Pallas
  histogram kernel was raced and retired (PARITY.md);
- cross-device communication is XLA collectives over ICI/DCN (`psum`,
  `all_gather`, `ppermute`) instead of the reference's socket allreduce rings
  (LightGBM ring, VW spanning tree — see reference `TrainUtils.scala:236-343`,
  `VowpalWabbitBase.scala:434-462`);
- the pipeline contract (Estimator/Transformer/Params, reference
  `core/contracts/Params.scala`) is preserved over a partitioned columnar
  DataFrame instead of Spark rows.

Layout mirrors the reference's module map (SURVEY.md §1-2):

- ``core``      — DataFrame, Params, Pipeline, serialization (ref L1)
- ``utils``     — cluster topology, stopwatch, fault tolerance (ref L1)
- ``parallel``  — device-mesh bootstrap, shardings, collectives, ring attention
- ``ops``       — XLA kernels (histogram, segment ops, image, hashing)
- ``models``    — flax model zoo (ResNet, BiLSTM, transformer) + GBDT booster
- ``lightgbm``  — LightGBMClassifier/Regressor/Ranker (ref ``lightgbm/``)
- ``vw``        — VowpalWabbit learners + featurizer (ref ``vw/``)
- ``dl``        — JaxModel + ImageFeaturizer (ref ``deep-learning/``)
- ``io``        — HTTP-on-frame, binary/image IO, PowerBI (ref ``core/.../io``)
- ``serving``   — low-latency web serving (ref Spark Serving)
- ``cognitive`` — cognitive-service transformers (ref ``cognitive/``)
- ``stages``    — generic plumbing transformers (ref ``stages/``)
- ``featurize`` — automatic featurization (ref ``featurize/``)
- ``train``     — TrainClassifier/Regressor, ComputeModelStatistics
- ``explainers``— LIME/KernelSHAP (ref ``explainers/``, ``lime/``)
- ``nn``        — BallTree KNN (ref ``nn/``)
- ``recommendation`` — SAR + ranking eval (ref ``recommendation/``)
- ``automl``    — TuneHyperparameters / FindBestModel (ref ``automl/``)
- ``isolationforest`` — IsolationForest (ref ``isolationforest/``)
- ``cyber``     — access-anomaly detection (ref ``core/src/main/python/mmlspark/cyber``)
- ``codegen``   — stage reflection, stub/doc generation (ref ``codegen/``)
- ``observability`` — metrics registry (+/metrics exposition), tracing spans,
  breaker instrumentation (ref BasicLogging telemetry, unified)
"""

__version__ = "0.2.0"

# jax < 0.5 compat: the codebase targets the top-level `jax.shard_map`
# (with its `check_vma` kwarg); older jax only ships
# `jax.experimental.shard_map.shard_map` (whose equivalent kwarg is
# `check_rep`).  Install a translating alias so every call site works on
# both — without it the whole parallel/ layer fails at call time.
# Tolerate a missing jax entirely: the pure-source tools (graft-lint,
# `python -m mmlspark_tpu.analysis`) must run on lint-only environments;
# compute modules fail at their own import time as before.
try:
    import jax as _jax
except ImportError:
    _jax = None

if _jax is not None and not hasattr(_jax, "shard_map"):
    import functools as _functools
    from jax.experimental.shard_map import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def _shard_map_compat(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    _jax.shard_map = _shard_map_compat

del _jax
