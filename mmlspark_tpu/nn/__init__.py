from .balltree import BallTree, ConditionalBallTree
from .knn import KNN, KNNModel, ConditionalKNN, ConditionalKNNModel

__all__ = ["BallTree", "ConditionalBallTree", "KNN", "KNNModel",
           "ConditionalKNN", "ConditionalKNNModel"]
