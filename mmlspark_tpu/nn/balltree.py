"""Ball trees for exact maximum-inner-product search.

Reference: ``nn/BallTree.scala:109`` (balltree over mean-split hyperplanes
with inner-product bounds) and ``ConditionalBallTree`` (:202, label-aware
pruning via per-node label sets + ``ReverseIndex`` :181).

On TPU the production query path is brute-force matmul top-k (``knn.py``) —
the MXU outruns tree traversal by orders of magnitude for the sizes the
reference handles — but the trees are kept for host-side/serving queries and
API parity, including their ``save``/``load`` used by ComplexParams.
"""
from __future__ import annotations

import heapq
import os
import pickle
from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.serialize import Saveable


class _Node:
    __slots__ = ("idx", "mu", "radius", "left", "right", "labels")

    def __init__(self, idx, mu, radius, left=None, right=None, labels=None):
        self.idx = idx          # leaf: indices into data
        self.mu = mu
        self.radius = radius
        self.left = left
        self.right = right
        self.labels = labels    # ConditionalBallTree: label set under node


class BallTree(Saveable):
    """Exact MIPS ball tree (mean-split, inner-product upper bounds)."""

    def __init__(self, data: np.ndarray, values: Optional[Sequence] = None,
                 leaf_size: int = 50):
        self.data = np.asarray(data, np.float64)
        self.values = list(values) if values is not None else list(range(len(self.data)))
        self.leaf_size = leaf_size
        self.norms = np.linalg.norm(self.data, axis=1)
        self.root = self._build(np.arange(len(self.data)), None)

    def _make_node(self, idx, labels) -> _Node:
        pts = self.data[idx]
        mu = pts.mean(axis=0)
        radius = float(np.max(np.linalg.norm(pts - mu, axis=1))) if len(idx) else 0.0
        return _Node(idx, mu, radius,
                     labels=None if labels is None else set(labels[i] for i in idx))

    def _build(self, idx: np.ndarray, labels) -> _Node:
        node = self._make_node(idx, labels)
        if len(idx) <= self.leaf_size:
            return node
        pts = self.data[idx]
        # split along direction of max spread (reference uses furthest-point pivots)
        a = pts[np.argmax(np.linalg.norm(pts - node.mu, axis=1))]
        b = pts[np.argmax(np.linalg.norm(pts - a, axis=1))]
        proj = pts @ (a - b)
        median = np.median(proj)
        left_mask = proj <= median
        if left_mask.all() or not left_mask.any():
            return node
        node.left = self._build(idx[left_mask], labels)
        node.right = self._build(idx[~left_mask], labels)
        node.idx = None
        return node

    @staticmethod
    def _bound(q: np.ndarray, node: _Node) -> float:
        # max over ball of q.x <= q.mu + ||q|| * radius
        return float(q @ node.mu) + float(np.linalg.norm(q)) * node.radius

    def find_maximum_inner_products(self, query: np.ndarray, k: int = 1,
                                    allowed: Optional[Set] = None) -> List[Tuple[int, float]]:
        """Top-k (index, inner product), optionally restricted to rows whose
        value is in `allowed` (ConditionalBallTree query)."""
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []   # min-heap of (ip, idx)

        def visit(node: _Node):
            if node is None:
                return
            if allowed is not None and node.labels is not None and \
                    not (node.labels & allowed):
                return
            if len(heap) == k and self._bound(q, node) <= heap[0][0]:
                return
            if node.idx is not None:  # leaf
                for i in node.idx:
                    if allowed is not None and self.values[i] not in allowed:
                        continue
                    ip = float(q @ self.data[i])
                    if len(heap) < k:
                        heapq.heappush(heap, (ip, int(i)))
                    elif ip > heap[0][0]:
                        heapq.heapreplace(heap, (ip, int(i)))
                return
            # visit more promising child first
            bl = self._bound(q, node.left) if node.left else -np.inf
            br = self._bound(q, node.right) if node.right else -np.inf
            first, second = (node.left, node.right) if bl >= br else (node.right, node.left)
            visit(first)
            visit(second)

        visit(self.root)
        return [(i, ip) for ip, i in sorted(heap, reverse=True)]

    # ------------------------------------------------------------------ serde
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "tree.pkl"), "wb") as f:
            pickle.dump(self, f)

    @classmethod
    def load(cls, path: str) -> "BallTree":
        with open(os.path.join(path, "tree.pkl"), "rb") as f:
            return pickle.load(f)


class ConditionalBallTree(BallTree):
    """Label-conditioned ball tree (reference ``ConditionalBallTree:202``):
    each node stores the label set beneath it so conditional queries prune
    whole subtrees whose labels don't intersect the allowed set."""

    def __init__(self, data: np.ndarray, values: Sequence, labels: Sequence,
                 leaf_size: int = 50):
        self.labels_arr = list(labels)
        self.data = np.asarray(data, np.float64)
        self.values = list(values)
        self.leaf_size = leaf_size
        self.norms = np.linalg.norm(self.data, axis=1)
        self.root = self._build(np.arange(len(self.data)), self.labels_arr)

    def find_maximum_inner_products(self, query, k=1, conditioner: Optional[Set] = None):
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []

        def visit(node: _Node):
            if node is None:
                return
            if conditioner is not None and node.labels is not None and \
                    not (node.labels & conditioner):
                return
            if len(heap) == k and self._bound(q, node) <= heap[0][0]:
                return
            if node.idx is not None:
                for i in node.idx:
                    if conditioner is not None and self.labels_arr[i] not in conditioner:
                        continue
                    ip = float(q @ self.data[i])
                    if len(heap) < k:
                        heapq.heappush(heap, (ip, int(i)))
                    elif ip > heap[0][0]:
                        heapq.heapreplace(heap, (ip, int(i)))
                return
            bl = self._bound(q, node.left) if node.left else -np.inf
            br = self._bound(q, node.right) if node.right else -np.inf
            first, second = (node.left, node.right) if bl >= br else (node.right, node.left)
            visit(first)
            visit(second)

        visit(self.root)
        return [(i, ip) for ip, i in sorted(heap, reverse=True)]
