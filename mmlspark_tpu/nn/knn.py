"""KNN / ConditionalKNN estimators.

Reference: ``nn/ConditionalKNN.scala:31`` — fit broadcasts a (Conditional)
BallTree; transform queries it per row (``KNNFuncHolder.queryFunc:64``).

TPU-first: the default query path is brute-force MIPS on the MXU —
``scores = Q @ X^T`` then ``lax.top_k`` — batched over query rows.  For the
reference's dataset sizes this saturates the systolic array and beats tree
traversal outright; the ball tree remains available (``use_ball_tree``) for
host-only/serving queries and is what gets serialized either way.
"""
from __future__ import annotations

from typing import List, Optional, Set

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, HasFeaturesCol,
                    HasOutputCol, Model, Param)
from ..core.schema import ColumnType, stack_vector_column
from .balltree import BallTree, ConditionalBallTree


def _device_topk(data: np.ndarray, queries: np.ndarray, k: int,
                 batch: int = 1024):
    """(scores, indices) per query via jitted matmul + top_k."""
    import jax
    import jax.numpy as jnp

    X = jnp.asarray(data, jnp.float32)

    @jax.jit
    def search(Q):
        scores = Q @ X.T                       # (bq, n) on the MXU
        return jax.lax.top_k(scores, k)

    out_scores, out_idx = [], []
    n = len(queries)
    for s in range(0, n, batch):
        chunk = np.asarray(queries[s:s + batch], np.float32)
        m = len(chunk)
        if m < batch and n > batch:
            chunk = np.concatenate([chunk, np.repeat(chunk[-1:], batch - m, 0)])
        sc, ix = search(jnp.asarray(chunk))
        out_scores.append(np.asarray(sc)[:m])
        out_idx.append(np.asarray(ix)[:m])
    return np.concatenate(out_scores), np.concatenate(out_idx)


class KNN(Estimator, HasFeaturesCol, HasOutputCol):
    values_col = Param("values_col", "payload column returned with matches", "string",
                       default="values")
    k = Param("k", "neighbours per query", "int", default=5)
    leaf_size = Param("leaf_size", "ball tree leaf size", "int", default=50)

    def _fit(self, df: DataFrame) -> "KNNModel":
        data = df.collect()
        X = stack_vector_column(data[self.get_or_fail("features_col")])
        vc = self.get("values_col")
        values = list(data[vc]) if vc in data else list(range(len(X)))
        tree = BallTree(X, values, self.get("leaf_size"))
        m = KNNModel()
        m.set("ball_tree", tree)
        m.set("k", self.get("k"))
        m.set("features_col", self.get("features_col"))
        m.set("output_col", self.get("output_col"))
        return m


class KNNModel(Model, HasFeaturesCol, HasOutputCol):
    ball_tree = ComplexParam("ball_tree", "fitted BallTree")
    k = Param("k", "neighbours per query", "int", default=5)
    use_ball_tree = Param("use_ball_tree", "query via tree instead of device "
                                           "matmul", "bool", default=False)

    def _transform(self, df: DataFrame) -> DataFrame:
        tree: BallTree = self.get_or_fail("ball_tree")
        k = self.get("k")
        fc, oc = self.get_or_fail("features_col"), self.get_or_fail("output_col")

        def per_part(p):
            Q = stack_vector_column(p[fc])
            out = np.empty(len(Q), dtype=object)
            if self.get("use_ball_tree") or len(tree.data) < 32:
                for i in range(len(Q)):
                    matches = tree.find_maximum_inner_products(Q[i], k)
                    out[i] = [{"value": tree.values[j], "distance": ip}
                              for j, ip in matches]
            else:
                scores, idx = _device_topk(tree.data, Q, min(k, len(tree.data)))
                for i in range(len(Q)):
                    out[i] = [{"value": tree.values[j], "distance": float(s)}
                              for j, s in zip(idx[i], scores[i])]
            return {**p, oc: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("features_col"))
        return schema.add(self.get_or_fail("output_col"), ColumnType.ARRAY)


class ConditionalKNN(Estimator, HasFeaturesCol, HasOutputCol):
    values_col = Param("values_col", "payload column", "string", default="values")
    label_col = Param("label_col", "conditioning label column", "string", default="labels")
    k = Param("k", "neighbours per query", "int", default=5)
    leaf_size = Param("leaf_size", "ball tree leaf size", "int", default=50)

    def _fit(self, df: DataFrame) -> "ConditionalKNNModel":
        data = df.collect()
        X = stack_vector_column(data[self.get_or_fail("features_col")])
        values = list(data[self.get("values_col")]) if self.get("values_col") in data \
            else list(range(len(X)))
        labels = list(data[self.get_or_fail("label_col")])
        tree = ConditionalBallTree(X, values, labels, self.get("leaf_size"))
        m = ConditionalKNNModel()
        m.set("ball_tree", tree)
        m.set("k", self.get("k"))
        m.set("features_col", self.get("features_col"))
        m.set("output_col", self.get("output_col"))
        return m


class ConditionalKNNModel(Model, HasFeaturesCol, HasOutputCol):
    ball_tree = ComplexParam("ball_tree", "fitted ConditionalBallTree")
    k = Param("k", "neighbours per query", "int", default=5)
    conditioner_col = Param("conditioner_col", "column holding allowed label sets",
                            "string", default="conditioner")

    def _transform(self, df: DataFrame) -> DataFrame:
        tree: ConditionalBallTree = self.get_or_fail("ball_tree")
        k = self.get("k")
        fc, oc = self.get_or_fail("features_col"), self.get_or_fail("output_col")
        cc = self.get("conditioner_col")

        def per_part(p):
            Q = stack_vector_column(p[fc])
            out = np.empty(len(Q), dtype=object)
            for i in range(len(Q)):
                cond = set(p[cc][i]) if cc in p else None
                matches = tree.find_maximum_inner_products(Q[i], k, cond)
                out[i] = [{"value": tree.values[j], "label": tree.labels_arr[j],
                           "distance": ip} for j, ip in matches]
            return {**p, oc: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("features_col"))
        return schema.add(self.get_or_fail("output_col"), ColumnType.ARRAY)
