"""Text featurization.

Reference: ``core/.../featurize/text/``: ``TextFeaturizer`` (tokenize ->
n-grams -> hashing-TF -> IDF pipeline), ``MultiNGram`` (several n-gram widths
concatenated), ``PageSplitter`` (split long strings into page-sized chunks).
"""
from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from ..core import (DataFrame, Estimator, HasInputCol, HasOutputCol, Model,
                    Param, Transformer)
from ..core.schema import vector_column
from ..vw.murmur import StringHashCache


def _tokenize(s: str, pattern: str, gaps: bool, min_len: int, lower: bool) -> List[str]:
    if lower:
        s = s.lower()
    toks = re.split(pattern, s) if gaps else re.findall(pattern, s)
    return [t for t in toks if len(t) >= min_len]


def _ngrams(tokens: List[str], n: int) -> List[str]:
    if n <= 1:
        return list(tokens)
    return [" ".join(tokens[i:i + n]) for i in range(len(tokens) - n + 1)]


class _TextFeaturizerParams(HasInputCol, HasOutputCol):
    use_tokenizer = Param("use_tokenizer", "tokenize input", "bool", default=True)
    tokenizer_pattern = Param("tokenizer_pattern", "regex", "string", default=r"\s+")
    tokenizer_gaps = Param("tokenizer_gaps", "pattern matches gaps", "bool", default=True)
    min_token_length = Param("min_token_length", "min token chars", "int", default=0)
    to_lower_case = Param("to_lower_case", "lowercase", "bool", default=True)
    use_stop_words_remover = Param("use_stop_words_remover", "drop stopwords", "bool", default=False)
    stop_words = Param("stop_words", "stopword list", "list", default=None)
    use_ngram = Param("use_ngram", "emit n-grams", "bool", default=False)
    n = Param("n", "n-gram width", "int", default=2)
    num_features = Param("num_features", "hash dims", "int", default=1 << 18)
    binary = Param("binary", "binary TF", "bool", default=False)
    use_idf = Param("use_idf", "apply IDF weighting", "bool", default=True)
    min_doc_freq = Param("min_doc_freq", "min docs for IDF", "int", default=1)

    _DEFAULT_STOPS = {"a", "an", "the", "and", "or", "of", "to", "in", "is",
                      "it", "this", "that", "for", "on", "with", "as", "at"}


class TextFeaturizer(Estimator, _TextFeaturizerParams):
    """tokenize -> stopwords -> n-grams -> hashing TF -> IDF
    (reference ``TextFeaturizer.scala`` pipeline assembly)."""

    def _terms(self, s: str) -> List[str]:
        toks = _tokenize(str(s), self.get("tokenizer_pattern"),
                         self.get("tokenizer_gaps"), self.get("min_token_length"),
                         self.get("to_lower_case")) if self.get("use_tokenizer") else [str(s)]
        if self.get("use_stop_words_remover"):
            stops = set(self.get("stop_words") or self._DEFAULT_STOPS)
            toks = [t for t in toks if t not in stops]
        if self.get("use_ngram"):
            toks = _ngrams(toks, self.get("n"))
        return toks

    def _fit(self, df):
        dims = self.get("num_features")
        hasher = StringHashCache()
        col = df.collect()[self.get_or_fail("input_col")]
        n_docs = len(col)
        df_counts = np.zeros(dims, np.float64)
        for s in col:
            idxs = {hasher(t) % dims for t in self._terms(s)}
            for j in idxs:
                df_counts[j] += 1
        idf = np.log((n_docs + 1.0) / (df_counts + 1.0)) + 1.0 if self.get("use_idf") else None
        if idf is not None and self.get("min_doc_freq") > 1:
            idf = np.where(df_counts >= self.get("min_doc_freq"), idf, 0.0)
        m = TextFeaturizerModel()
        m._paramMap.update(self._paramMap)
        m.set("idf", idf.tolist() if idf is not None else None)
        return m


class TextFeaturizerModel(Model, _TextFeaturizerParams):
    idf = Param("idf", "IDF weights", "object")

    _terms = TextFeaturizer._terms

    def _transform(self, df):
        dims = self.get("num_features")
        binary = self.get("binary")
        idf = self.get("idf")
        idf_arr = np.asarray(idf) if idf is not None else None
        hasher = StringHashCache()
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, s in enumerate(p[in_col]):
                vec = {}
                for t in self._terms(s):
                    j = hasher(t) % dims
                    vec[j] = 1.0 if binary else vec.get(j, 0.0) + 1.0
                idxs = np.asarray(sorted(vec), np.int64)
                vals = np.asarray([vec[j] for j in idxs], np.float64)
                if idf_arr is not None and len(idxs):
                    vals = vals * idf_arr[idxs]
                out[i] = {"indices": idxs.astype(np.int32),
                          "values": vals.astype(np.float32), "size": dims}
            return {**p, out_col: out}

        return df.map_partitions(per_part)


class MultiNGram(Transformer, HasInputCol, HasOutputCol):
    """Concatenate n-grams of several widths (reference ``MultiNGram.scala``)."""
    lengths = Param("lengths", "n-gram widths", "list", default=[1, 2, 3])

    def _transform(self, df):
        lengths = self.get("lengths")
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, toks in enumerate(p[in_col]):
                toks = list(toks)
                grams: List[str] = []
                for n in lengths:
                    grams.extend(_ngrams(toks, int(n)))
                out[i] = grams
            return {**p, out_col: out}

        return df.map_partitions(per_part)


class PageSplitter(Transformer, HasInputCol, HasOutputCol):
    """Split strings into page-sized chunks on whitespace boundaries
    (reference ``PageSplitter.scala``)."""
    maximum_page_length = Param("maximum_page_length", "max chars per page", "int", default=5000)
    minimum_page_length = Param("minimum_page_length", "min chars before a "
                                "whitespace split is taken", "int", default=4500)

    def _transform(self, df):
        max_len = self.get("maximum_page_length")
        min_len = self.get("minimum_page_length")
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def split_one(s: str) -> List[str]:
            pages = []
            s = str(s)
            while len(s) > max_len:
                cut = max_len
                ws = [m.start() for m in re.finditer(r"\s", s[min_len:max_len])]
                if ws:
                    cut = min_len + ws[-1]
                pages.append(s[:cut])
                s = s[cut:]
            pages.append(s)
            return pages

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, s in enumerate(p[in_col]):
                out[i] = split_one(s)
            return {**p, out_col: out}

        return df.map_partitions(per_part)
