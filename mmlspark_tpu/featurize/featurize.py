"""Automatic featurization pipeline.

Reference: ``core/.../featurize/`` (~1.6k LoC): ``Featurize`` assembles an
impute -> index -> one-hot/hash -> assemble pipeline from column types
(column-state machine ``Featurize.scala:82-110``); ``CleanMissingData``;
``ValueIndexer``/``ValueIndexerModel``/``IndexToValue``; ``CountSelector``;
``DataConversion``.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import (DataFrame, Estimator, HasInputCol, HasInputCols,
                    HasOutputCol, Model, Param, Transformer)
from ..core.dataframe import _as_column
from ..core.schema import ColumnType, vector_column


def assemble_vector_column(parts: List[np.ndarray]) -> np.ndarray:
    """FastVectorAssembler equivalent: concat numeric/vector columns row-wise."""
    n = len(parts[0])
    out = np.empty(n, dtype=object)
    for i in range(n):
        pieces = []
        for col in parts:
            v = col[i]
            if isinstance(v, (list, tuple, np.ndarray)):
                pieces.append(np.asarray(v, np.float64).ravel())
            else:
                pieces.append(np.asarray([0.0 if v is None else float(v)]))
        out[i] = np.concatenate(pieces)
    return out


class CleanMissingData(Estimator, HasInputCols):
    """Impute missing numerics (reference ``CleanMissingData.scala``)."""
    cleaning_mode = Param("cleaning_mode", "Mean|Median|Custom", "string", default="Mean")
    custom_value = Param("custom_value", "fill value for Custom mode", "float")
    output_cols = Param("output_cols", "output columns (default in-place)", "list")

    def _fit(self, df):
        cols = self.get_or_fail("input_cols")
        mode = self.get("cleaning_mode")
        whole = df.collect()
        fills: Dict[str, float] = {}
        for c in cols:
            v = whole[c].astype(float)
            if mode == "Mean":
                fills[c] = float(np.nanmean(v)) if np.isfinite(np.nanmean(v)) else 0.0
            elif mode == "Median":
                fills[c] = float(np.nanmedian(v))
            else:
                fills[c] = float(self.get_or_fail("custom_value"))
        m = CleanMissingDataModel()
        m.set("input_cols", cols)
        m.set("output_cols", self.get("output_cols") or cols)
        m.set("fill_values", fills)
        return m


class CleanMissingDataModel(Model, HasInputCols):
    output_cols = Param("output_cols", "output columns", "list")
    fill_values = Param("fill_values", "column -> fill value", "object")

    def _transform(self, df):
        fills = self.get_or_fail("fill_values")
        out = df
        for c, o in zip(self.get_or_fail("input_cols"), self.get_or_fail("output_cols")):
            fill = fills[c]
            out = out.with_column(o, lambda p, c=c, fill=fill:
                                  np.nan_to_num(p[c].astype(float), nan=fill))
        return out


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Index categorical values with deterministic ordering
    (reference ``ValueIndexer.scala``)."""

    def _fit(self, df):
        col = df.collect()[self.get_or_fail("input_col")]
        non_null = [v for v in col if v is not None]
        levels = sorted(set(str(v) for v in non_null))
        m = ValueIndexerModel()
        m.set("input_col", self.get("input_col"))
        m.set("output_col", self.get("output_col"))
        m.set("levels", levels)
        return m


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = Param("levels", "ordered category values", "list")

    def _transform(self, df):
        levels = {v: i for i, v in enumerate(self.get_or_fail("levels"))}
        in_col = self.get_or_fail("input_col")
        unknown = len(levels)
        return df.with_column(
            self.get_or_fail("output_col"),
            lambda p: np.asarray([levels.get(str(v), unknown) if v is not None else unknown
                                  for v in p[in_col]], np.float64))


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Inverse of ValueIndexerModel (reference ``IndexToValue.scala``)."""
    levels = Param("levels", "ordered category values", "list")

    def _transform(self, df):
        levels = self.get_or_fail("levels")
        in_col = self.get_or_fail("input_col")

        def decode(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                iv = int(v)
                out[i] = levels[iv] if 0 <= iv < len(levels) else None
            return out

        return df.with_column(self.get_or_fail("output_col"), decode)


class CountSelector(Estimator, HasInputCol, HasOutputCol):
    """Drop all-zero vector slots (reference ``CountSelector.scala``)."""

    def _fit(self, df):
        col = df.collect()[self.get_or_fail("input_col")]
        mat = np.stack([np.asarray(v, float) for v in col]) if len(col) else np.zeros((0, 0))
        keep = np.nonzero((mat != 0).any(axis=0))[0] if mat.size else np.empty(0, int)
        m = CountSelectorModel()
        m.set("input_col", self.get("input_col"))
        m.set("output_col", self.get("output_col"))
        m.set("indices", keep.tolist())
        return m


class CountSelectorModel(Model, HasInputCol, HasOutputCol):
    indices = Param("indices", "kept slot indices", "list")

    def _transform(self, df):
        keep = np.asarray(self.get_or_fail("indices"), int)
        in_col = self.get_or_fail("input_col")

        def select(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                out[i] = np.asarray(v, float)[keep]
            return out

        return df.with_column(self.get_or_fail("output_col"), select)


class DataConversion(Transformer):
    """Column dtype conversion (reference ``DataConversion.scala``),
    including ``toCategorical`` (string/value column -> stable integer
    codes, sorted-distinct order — the Spark categorical-metadata
    analogue) and ``clearCategorical`` (codes stay plain doubles)."""
    cols = Param("cols", "columns to convert", "list")
    convert_to = Param("convert_to", "boolean|byte|short|integer|long|float|"
                                     "double|string|date|toCategorical|"
                                     "clearCategorical", "string",
                       default="double")

    _CASTS = {"boolean": bool, "byte": np.int8, "short": np.int16,
              "integer": np.int32, "long": np.int64, "float": np.float32,
              "double": np.float64}

    def _transform(self, df):
        to = self.get("convert_to")
        out = df
        for c in self.get_or_fail("cols"):
            if to == "string":
                out = out.with_column(c, lambda p, c=c: _as_column([str(v) for v in p[c]]))
            elif to == "date":
                import datetime
                out = out.with_column(c, lambda p, c=c: _as_column(
                    [datetime.datetime.fromisoformat(str(v)) for v in p[c]]))
            elif to == "toCategorical":
                # frame-global code table (sorted distinct values) so every
                # partition recodes identically
                levels = sorted({str(v) for v in out.collect()[c]})
                table = {v: float(i) for i, v in enumerate(levels)}
                out = out.with_column(c, lambda p, c=c, t=table: np.asarray(
                    [t[str(v)] for v in p[c]], np.float64))
            elif to == "clearCategorical":
                out = out.with_column(
                    c, lambda p, c=c: _cast_coerce(np.asarray(p[c]),
                                                   np.float64))
            else:
                cast = self._CASTS[to]
                out = out.with_column(
                    c, lambda p, c=c, cast=cast: _cast_coerce(p[c], cast))
        return out


def _cast_coerce(col: np.ndarray, cast) -> np.ndarray:
    """Spark cast semantics (reference DataConversion.scala): values that
    cannot be parsed become null (NaN here), they do not fail the job —
    '?'-style missing markers in imported CSVs rely on this."""
    try:
        return col.astype(cast)
    except (ValueError, TypeError):
        if not np.issubdtype(np.dtype(cast), np.floating):
            raise  # int/bool have no NaN; surface the bad value
        out = np.empty(len(col), np.dtype(cast))
        for i, v in enumerate(col):
            try:
                out[i] = cast(v)
            except (ValueError, TypeError):
                out[i] = np.nan
        return out


class Featurize(Estimator, HasOutputCol):
    """Auto-assemble a feature vector from mixed-type columns
    (reference ``Featurize.scala:36``: impute -> index/one-hot or hash ->
    assemble; ``one_hot_encode_categoricals`` and ``num_features`` mirror the
    reference params)."""

    input_cols = Param("input_cols", "columns to featurize", "list")
    one_hot_encode_categoricals = Param("one_hot_encode_categoricals",
                                        "one-hot instead of index", "bool", default=True)
    num_features = Param("num_features", "hash dims for text columns", "int", default=2 ** 8)
    impute_missing = Param("impute_missing", "mean-impute numerics", "bool", default=True)

    def _fit(self, df):
        cols = self.get("input_cols") or [c for c in df.columns]
        whole = df.collect()
        plan: List[Dict[str, Any]] = []
        for c in cols:
            col = whole[c]
            kind = ColumnType.of(col)
            if kind in (ColumnType.DOUBLE, ColumnType.LONG, ColumnType.BOOL):
                fill = float(np.nanmean(col.astype(float))) if self.get("impute_missing") else 0.0
                plan.append({"col": c, "kind": "numeric", "width": 1,
                             "fill": 0.0 if not np.isfinite(fill) else fill})
            elif kind == ColumnType.VECTOR:
                plan.append({"col": c, "kind": "vector",
                             "width": int(np.asarray(col[0]).size) if len(col) else 0})
            else:
                values = [str(v) for v in col if v is not None]
                levels = sorted(set(values))
                if len(levels) > 64:  # high-cardinality: feature hashing
                    plan.append({"col": c, "kind": "hash",
                                 "dims": self.get("num_features")})
                elif self.get("one_hot_encode_categoricals"):
                    plan.append({"col": c, "kind": "onehot", "width": len(levels),
                                 "levels": levels})
                else:
                    plan.append({"col": c, "kind": "index", "width": 1,
                                 "levels": levels})
        m = FeaturizeModel()
        m.set("plan", plan)
        m.set("output_col", self.get("output_col") or "features")
        return m


class FeaturizeModel(Model, HasOutputCol):
    plan = Param("plan", "per-column featurization plan", "list")

    def categorical_slots(self):
        """Assembled-vector slot indices holding CATEGORY CODES (the
        ``index``-kind plan entries) — the schema metadata the reference's
        ``getCategoricalIndexes`` (LightGBMBase.scala:168) reads off the
        assembled vector, used to auto-wire LightGBM categorical splits."""
        slots, pos = [], 0
        for spec in self.get_or_fail("plan"):
            if spec["kind"] == "index":
                slots.append(pos)
            pos += spec.get("width", spec.get("dims", 1))
        return slots

    def _transform(self, df):
        plan = self.get_or_fail("plan")
        out_col = self.get_or_fail("output_col")
        from ..vw.murmur import StringHashCache
        hasher = StringHashCache()

        def per_part(p):
            pieces: List[np.ndarray] = []
            n = len(next(iter(p.values()))) if p else 0
            for spec in plan:
                col = p[spec["col"]]
                kind = spec["kind"]
                if kind == "numeric":
                    v = np.nan_to_num(col.astype(float), nan=spec["fill"])
                    pieces.append(v[:, None])
                elif kind == "vector":
                    pieces.append(np.stack([np.asarray(x, float) for x in col]))
                elif kind == "onehot":
                    levels = {v: i for i, v in enumerate(spec["levels"])}
                    mat = np.zeros((n, len(levels)), float)
                    for i, v in enumerate(col):
                        j = levels.get(str(v))
                        if j is not None:
                            mat[i, j] = 1.0
                    pieces.append(mat)
                elif kind == "index":
                    levels = {v: i for i, v in enumerate(spec["levels"])}
                    pieces.append(np.asarray(
                        [levels.get(str(v), len(levels)) for v in col], float)[:, None])
                elif kind == "hash":
                    dims = spec["dims"]
                    mat = np.zeros((n, dims), float)
                    for i, v in enumerate(col):
                        mat[i, hasher(str(v)) % dims] = 1.0
                    pieces.append(mat)
            feats = np.concatenate(pieces, axis=1) if pieces else np.zeros((n, 0))
            return {**p, out_col: vector_column(list(feats))}

        return df.map_partitions(per_part)
