"""Word2Vec — jitted skip-gram with negative sampling.

Reference surface: the Amazon Book Reviews notebook pairs Spark MLlib's
``Word2Vec`` with mmlspark's ``TrainClassifier``/``FindBestModel``
(``notebooks/TextAnalytics - Amazon Book Reviews with Word2Vec.ipynb``).
This framework replaces the Spark ML layer too, so the estimator lives
here: tokenization + vocab on host, training as ONE jitted ``lax.scan``
over minibatched (center, context, negatives) triples — the SGNS inner
loop is all dot products, which XLA fuses into a couple of HBM-friendly
batched matmuls per step instead of Spark's per-partition Scala loops.

``Word2VecModel.transform`` averages word vectors per document (exactly
MLlib's document-embedding semantics); ``find_synonyms`` does a cosine
top-k over the table.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import (DataFrame, Estimator, HasInputCol, HasOutputCol, Model,
                    Param)
from ..core.schema import vector_column


class _W2VParams(HasInputCol, HasOutputCol):
    vector_size = Param("vector_size", "embedding width", "int", default=64)
    min_count = Param("min_count", "min token occurrences", "int", default=2)
    window_size = Param("window_size", "context window", "int", default=5)
    num_negatives = Param("num_negatives", "negative samples per pair",
                          "int", default=5)
    max_iter = Param("max_iter", "epochs over the pair set", "int", default=1)
    step_size = Param("step_size", "SGD learning rate", "float",
                      default=0.25)
    batch_size = Param("batch_size", "pairs per jitted step", "int",
                       default=512)
    max_vocab = Param("max_vocab", "vocabulary cap (by frequency)", "int",
                      default=1 << 16)
    seed = Param("seed", "rng seed", "int", default=42)


def _tokens_of(col) -> List[List[str]]:
    out = []
    for doc in col:
        if isinstance(doc, (list, tuple, np.ndarray)):
            out.append([str(t) for t in doc])
        else:
            out.append(str(doc).lower().split())
    return out


class Word2Vec(Estimator, _W2VParams):
    """Fit skip-gram/negative-sampling embeddings over a text (or
    pre-tokenized list) column."""

    def __init__(self, uid: Optional[str] = None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    def _fit(self, df: DataFrame) -> "Word2VecModel":
        rng = np.random.default_rng(self.get("seed"))
        docs = _tokens_of(df.collect()[self.get_or_fail("input_col")])

        # ---- vocab (host): frequency-capped, unigram^0.75 negative table
        from collections import Counter
        counts = Counter(t for d in docs for t in d)
        vocab = [w for w, c in counts.most_common(self.get("max_vocab"))
                 if c >= self.get("min_count")]
        if not vocab:
            raise ValueError("Word2Vec: empty vocabulary "
                             "(min_count too high or empty input)")
        index = {w: i for i, w in enumerate(vocab)}
        V, D = len(vocab), self.get("vector_size")

        # ---- (center, context) pairs with random window shrink (word2vec's
        # dynamic window) — bounded memory: indices only
        win = self.get("window_size")
        centers, contexts = [], []
        for d in docs:
            ids = [index[t] for t in d if t in index]
            for i, c in enumerate(ids):
                w = int(rng.integers(1, win + 1))
                for j in range(max(0, i - w), min(len(ids), i + w + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            raise ValueError("Word2Vec: no training pairs "
                             "(documents shorter than 2 in-vocab tokens)")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        freq = np.asarray([counts[w] for w in vocab], np.float64) ** 0.75
        neg_table = (freq / freq.sum()).astype(np.float32)

        import jax
        import jax.numpy as jnp

        K = int(self.get("num_negatives"))
        lr = float(self.get("step_size"))
        n_pairs = len(centers)
        B = min(int(self.get("batch_size")), n_pairs)  # tiny corpora
        steps_per_epoch = max(1, n_pairs // B)

        def one_epoch(params, key, cen, ctx):
            """All steps of one epoch as a lax.scan — one dispatch."""
            def step(carry, sl):
                W_in, W_out = carry
                c_ids, o_ids, negs = sl
                vc = W_in[c_ids]                      # (B, D)
                vo = W_out[o_ids]                     # (B, D)
                vn = W_out[negs]                      # (B, K, D)
                pos_logit = jnp.sum(vc * vo, axis=1)
                neg_logit = jnp.einsum("bd,bkd->bk", vc, vn)
                g_pos = jax.nn.sigmoid(pos_logit) - 1.0          # (B,)
                g_neg = jax.nn.sigmoid(neg_logit)                # (B, K)
                d_vc = g_pos[:, None] * vo + jnp.einsum("bk,bkd->bd", g_neg, vn)
                d_vo = g_pos[:, None] * vc
                d_vn = g_neg[:, :, None] * vc[:, None, :]
                # a word repeated in the batch accumulates that many scatter
                # adds from stale reads — an effective step of lr*count that
                # DIVERGES on small vocabularies.  Normalize each word's
                # update by its batch multiplicity so the per-word step stays
                # bounded by lr regardless of vocab/batch ratio.
                negs_f = negs.reshape(-1)
                cnt_in = jnp.zeros((V,)).at[c_ids].add(1.0)
                cnt_out = jnp.zeros((V,)).at[o_ids].add(1.0).at[negs_f].add(1.0)
                W_in = W_in.at[c_ids].add(
                    -lr * d_vc / cnt_in[c_ids][:, None])
                W_out = W_out.at[o_ids].add(
                    -lr * d_vo / cnt_out[o_ids][:, None])
                W_out = W_out.at[negs_f].add(
                    -lr * d_vn.reshape(-1, D) / cnt_out[negs_f][:, None])
                return (W_in, W_out), None

            negs = jax.random.choice(key, V, (steps_per_epoch, B, K),
                                     p=jnp.asarray(neg_table))
            sl = (cen[:steps_per_epoch * B].reshape(steps_per_epoch, B),
                  ctx[:steps_per_epoch * B].reshape(steps_per_epoch, B),
                  negs)
            params, _ = jax.lax.scan(step, params, sl)
            return params

        from ..observability.compute import instrumented_jit
        epoch_jit = instrumented_jit(one_epoch, name="featurize.word2vec_epoch")
        scale = 0.5 / D
        params = (jnp.asarray(rng.uniform(-scale, scale, (V, D))
                              .astype(np.float32)),
                  jnp.zeros((V, D), jnp.float32))
        for ep in range(self.get("max_iter")):
            perm = rng.permutation(n_pairs)
            params = epoch_jit(params,
                               jax.random.PRNGKey(self.get("seed") + ep),
                               jnp.asarray(centers[perm]),
                               jnp.asarray(contexts[perm]))
        vectors = np.asarray(params[0])

        m = Word2VecModel()
        m._paramMap.update(self._paramMap)
        m.set("vocab", list(vocab))
        m.set("vectors", vectors.tolist())
        return m


class Word2VecModel(Model, _W2VParams):
    vocab = Param("vocab", "vocabulary (index order)", "list")
    vectors = Param("vectors", "(V, D) embedding table", "object")

    def __init__(self, uid: Optional[str] = None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    def _table(self):
        return (np.asarray(self.get("vectors"), np.float32),
                {w: i for i, w in enumerate(self.get("vocab"))})

    def _transform(self, df: DataFrame) -> DataFrame:
        vec, index = self._table()
        D = vec.shape[1]
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")

        def per_part(p):
            docs = _tokens_of(p[in_col])
            out = np.empty(len(docs), dtype=object)
            for i, d in enumerate(docs):
                ids = [index[t] for t in d if t in index]
                out[i] = vec[ids].mean(axis=0) if ids \
                    else np.zeros(D, np.float32)
            return {**p, out_col: vector_column(list(out))}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        out = dict(schema)
        out[self.get_or_fail("output_col")] = "vector"
        return out

    def find_synonyms(self, word: str, num: int = 5):
        """Cosine top-k neighbours of ``word`` -> [(token, similarity)]."""
        vec, index = self._table()
        if word not in index:
            raise KeyError(f"{word!r} not in Word2Vec vocabulary")
        q = vec[index[word]]
        norms = np.linalg.norm(vec, axis=1) * (np.linalg.norm(q) + 1e-12)
        sims = vec @ q / np.maximum(norms, 1e-12)
        sims[index[word]] = -np.inf
        top = np.argsort(-sims)[:num]
        vocab = self.get("vocab")
        return [(vocab[i], float(sims[i])) for i in top]
