from .featurize import (Featurize, CleanMissingData, CleanMissingDataModel,
                        ValueIndexer, ValueIndexerModel, IndexToValue,
                        CountSelector, CountSelectorModel, DataConversion,
                        assemble_vector_column)
from .text import TextFeaturizer, TextFeaturizerModel, MultiNGram, PageSplitter

__all__ = ["Featurize", "CleanMissingData", "CleanMissingDataModel",
           "ValueIndexer", "ValueIndexerModel", "IndexToValue",
           "CountSelector", "CountSelectorModel", "DataConversion",
           "assemble_vector_column", "TextFeaturizer", "TextFeaturizerModel",
           "MultiNGram", "PageSplitter"]
