from .featurize import (Featurize, CleanMissingData, CleanMissingDataModel,
                        ValueIndexer, ValueIndexerModel, IndexToValue,
                        CountSelector, CountSelectorModel, DataConversion,
                        assemble_vector_column)
from .text import TextFeaturizer, TextFeaturizerModel, MultiNGram, PageSplitter
from .word2vec import Word2Vec, Word2VecModel

__all__ = ["Featurize", "CleanMissingData", "CleanMissingDataModel",
           "ValueIndexer", "ValueIndexerModel", "IndexToValue",
           "CountSelector", "CountSelectorModel", "DataConversion",
           "assemble_vector_column", "TextFeaturizer", "TextFeaturizerModel",
           "Word2Vec", "Word2VecModel",
           "MultiNGram", "PageSplitter"]
