"""``python -m mmlspark_tpu <command>`` — package tool entry points.

Commands:
  graft-lint [args...]   the static-analysis gate (alias: lint, analysis);
                         same CLI as ``python -m mmlspark_tpu.analysis``
  codegen [out_dir]      regenerate the codegen artifacts (default docs/api)
  help                   this message
"""
import sys
from typing import Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    cmd = argv.pop(0) if argv else "help"
    if cmd in ("graft-lint", "lint", "analysis"):
        from .analysis.cli import main as lint_main
        return lint_main(argv)
    if cmd == "codegen":
        from .codegen.codegen import generate_all
        generate_all(argv[0] if argv else "docs/api")
        return 0
    print(__doc__.strip())
    return 0 if cmd in ("help", "-h", "--help") else 2


if __name__ == "__main__":
    sys.exit(main())
