"""ImageTransformer — chained image ops as one fused device program.

Reference: ``opencv/.../ImageTransformer.scala:42-220`` applies a pipeline of
JNI ``Mat`` stages (ResizeImage/CropImage/ColorFormat/Flip/Blur/Threshold/
GaussianKernel) per row.  TPU-first the whole op chain compiles into ONE
jitted function over NHWC batches (XLA fuses the elementwise chain; resize
and blur hit the VPU/MXU), instead of per-row native calls.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import DataFrame, HasInputCol, HasOutputCol, Param, Transformer
from ..core.schema import ColumnType
from ..ops import image as image_ops


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    stages = Param("stages", "ordered list of op dicts", "list", default=[])

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)
        if not self.is_set("stages"):
            self.set("stages", [])

    # -- fluent builders mirroring the reference stage classes ---------------
    def _add(self, op: Dict[str, Any]) -> "ImageTransformer":
        self.set("stages", list(self.get("stages")) + [op])
        return self

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add({"op": "resize", "height": height, "width": width})

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add({"op": "crop", "x": x, "y": y, "height": height, "width": width})

    def center_crop(self, height: int, width: int) -> "ImageTransformer":
        return self._add({"op": "center_crop", "height": height, "width": width})

    def color_format(self, format: str) -> "ImageTransformer":
        return self._add({"op": "color_format", "format": format})

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        # reference flipCode: 1=horizontal, 0=vertical
        return self._add({"op": "flip", "horizontal": flip_code == 1})

    def blur(self, height: float = 5, width: float = 5, sigma: float = 1.0) -> "ImageTransformer":
        return self._add({"op": "blur", "kernel_size": int(height), "sigma": sigma})

    def threshold(self, threshold: float, max_val: float = 255.0,
                  threshold_type: str = "binary") -> "ImageTransformer":
        return self._add({"op": "threshold", "threshold": threshold,
                          "max_val": max_val, "kind": threshold_type})

    def gaussian_kernel(self, apperture_size: int, sigma: float) -> "ImageTransformer":
        return self._add({"op": "blur", "kernel_size": apperture_size, "sigma": sigma})

    def normalize(self, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
                  scale: float = 1 / 255.0) -> "ImageTransformer":
        return self._add({"op": "normalize", "mean": list(mean), "std": list(std),
                          "scale": scale})

    def unroll(self) -> "ImageTransformer":
        return self._add({"op": "unroll"})

    # ------------------------------------------------------------------ run
    def _apply_chain(self, batch):
        import jax.numpy as jnp
        x = batch
        for spec in self.get("stages"):
            op = spec["op"]
            if op == "resize":
                x = image_ops.resize(x, spec["height"], spec["width"])
            elif op == "crop":
                x = image_ops.crop(x, spec["x"], spec["y"], spec["height"], spec["width"])
            elif op == "center_crop":
                x = image_ops.center_crop(x, spec["height"], spec["width"])
            elif op == "flip":
                x = image_ops.flip(x, spec["horizontal"])
            elif op == "blur":
                x = image_ops.blur(x, spec["kernel_size"], spec["sigma"])
            elif op == "threshold":
                x = image_ops.threshold(x, spec["threshold"], spec["max_val"], spec["kind"])
            elif op == "color_format":
                if spec["format"] in ("gray", "grayscale"):
                    x = image_ops.to_grayscale(x)
            elif op == "normalize":
                x = image_ops.normalize(x, spec["mean"], spec["std"], spec["scale"])
            elif op == "unroll":
                x = image_ops.unroll(x)
            else:
                raise ValueError(f"unknown image op {op!r}")
        return x

    def _transform(self, df: DataFrame) -> DataFrame:
        import jax
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")
        chain = jax.jit(self._apply_chain)

        def per_part(p):
            col = p[in_col]
            n = len(col)
            out = np.empty(n, dtype=object)
            # group by input shape so each unique shape compiles once
            by_shape: Dict[tuple, List[int]] = {}
            for i, v in enumerate(col):
                by_shape.setdefault(np.asarray(v).shape, []).append(i)
            for shape, idxs in by_shape.items():
                batch = np.stack([np.asarray(col[i], np.float32) for i in idxs])
                res = np.asarray(chain(batch))
                for j, i in enumerate(idxs):
                    out[i] = res[j]
            return {**p, out_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("input_col"))
        return schema.add(self.get_or_fail("output_col"), ColumnType.VECTOR)


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Emit original + flipped copies (reference ``ImageSetAugmenter.scala``)."""

    flip_left_right = Param("flip_left_right", "add LR flips", "bool", default=True)
    flip_up_down = Param("flip_up_down", "add UD flips", "bool", default=False)

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")
        base = df.with_column(out_col, lambda p: p[in_col])
        outs = [base]
        if self.get("flip_left_right"):
            t = ImageTransformer().set_params(input_col=in_col, output_col=out_col).flip(1)
            outs.append(t.transform(df))
        if self.get("flip_up_down"):
            t = ImageTransformer().set_params(input_col=in_col, output_col=out_col).flip(0)
            outs.append(t.transform(df))
        result = outs[0]
        for o in outs[1:]:
            result = result.union(o.select(*result.columns))
        return result
