from .image_transformer import ImageTransformer, ImageSetAugmenter

__all__ = ["ImageTransformer", "ImageSetAugmenter"]
