"""Streaming speech recognition — the SpeechToTextSDK/ConversationTranscription
equivalents, TPU-native.

Reference: ``cognitive/.../SpeechToTextSDK.scala`` — streaming recognition
through the native Speech SDK: a pull audio stream feeds the recognizer
(:419), recognition events are bridged into a row iterator by
``BlockingQueueIterator`` (:42), and ``ConversationTranscription`` (:491)
adds speaker attribution.  That SDK is a remote/native dependency; the
TPU-era equivalent is CHUNKED STREAMING INFERENCE through the model zoo:

- audio arrives as a pull stream (``io/audio.py``), chunked at
  ``chunk_s`` seconds;
- each chunk becomes log-mel features on host and one jitted encoder step
  on device — a unidirectional stacked-LSTM acoustic model whose (c, h)
  carries persist across chunks, so the device program is ONE fixed-shape
  step reused for the whole stream (no recompiles, latency = one chunk);
- greedy CTC decoding collapses each chunk's symbol posteriors into an
  incremental hypothesis ("Recognizing" events), with a final
  "Recognized" event at end of stream — mirroring the SDK's event model;
- ``ConversationTranscription`` adds online speaker attribution by
  cosine-matching chunk feature centroids ("Guest-N" ids, the SDK's
  conversation semantics).

``TranscriptionSession``/``SpeechServingModel`` bridge the same recognizer
into the serving engine: POST chunks with a session id, receive incremental
hypotheses — streaming recognition as a web service.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import ComplexParam, DataFrame, HasInputCol, HasOutputCol, Param, Transformer
from ..core.schema import ColumnType
from ..io.audio import BlockingQueueIterator, audio_stream, log_mel

DEFAULT_ALPHABET = "_abcdefghijklmnopqrstuvwxyz '"  # index 0 = CTC blank


def streaming_encoder(hidden: int = 128, num_layers: int = 2,
                      num_symbols: int = len(DEFAULT_ALPHABET)):
    """Unidirectional stacked-LSTM acoustic encoder as a flax module whose
    call signature is (carry, feats) -> (carry, logits) — the streaming
    variant of ``models/bilstm.py`` (online audio can't see the future, so
    no backward pass)."""
    import flax.linen as nn

    class StreamingEncoder(nn.Module):
        hidden_size: int = hidden
        layers: int = num_layers
        symbols: int = num_symbols

        @nn.compact
        def __call__(self, carry, feats):  # carry: ((c,h),)*layers, feats (B,T,F)
            ScanCell = nn.scan(nn.OptimizedLSTMCell, variable_broadcast="params",
                               split_rngs={"params": False}, in_axes=1, out_axes=1)
            x = feats
            new_carry = []
            for i in range(self.layers):
                c, x = ScanCell(self.hidden_size, name=f"lstm_{i}")(carry[i], x)
                new_carry.append(c)
            logits = nn.Dense(self.symbols, name="head")(x)
            return tuple(new_carry), logits

    return StreamingEncoder()


@dataclasses.dataclass
class RecognitionState:
    """Per-stream state carried across chunks."""
    carry: Any
    prev_id: int = 0
    text: str = ""
    frames_seen: int = 0
    speaker_centroids: List[np.ndarray] = dataclasses.field(default_factory=list)
    speaker: str = "Guest-1"
    pending: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.float32))
    # unframed sample tail kept so chunk-boundary frames see the SAME
    # windows a single full-utterance pass would (window > hop)
    lookback: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.float32))


class StreamingRecognizer:
    """Chunk-at-a-time recognition over a jitted encoder step."""

    def __init__(self, module=None, variables=None,
                 apply_fn: Optional[Callable] = None,
                 alphabet: str = DEFAULT_ALPHABET, sample_rate: int = 16000,
                 n_mels: int = 40, chunk_s: float = 0.5, seed: int = 0,
                 hidden_shapes: Optional[List[int]] = None):
        import jax
        import jax.numpy as jnp
        self.alphabet = alphabet
        self.sample_rate = sample_rate
        self.n_mels = n_mels
        self.chunk_samples = int(chunk_s * sample_rate)
        # single source of truth for the acoustic framing; passed through to
        # log_mel so window/hop can never drift apart
        self.frame_ms, self.hop_ms = 25.0, 10.0
        self.frame = int(sample_rate * self.frame_ms / 1000)
        self.hop = int(sample_rate * self.hop_ms / 1000)
        self.module = module if module is not None or apply_fn is not None \
            else streaming_encoder(num_symbols=len(alphabet))
        if apply_fn is not None:
            self._apply = jax.jit(apply_fn)
            self.variables = variables
            self._hidden_shapes = hidden_shapes
        else:
            self.variables = variables
            self._apply = jax.jit(
                lambda v, c, f: self.module.apply(v, c, f))
            self._hidden_shapes = hidden_shapes or \
                [self.module.hidden_size] * self.module.layers
        self._jnp = jnp
        self._jax = jax
        self._seed = seed

    # ---------------------------------------------------------------- state
    def init_carry(self, batch: int = 1):
        jnp = self._jnp
        if self._hidden_shapes is None:
            raise ValueError(
                "carry shapes unknown for an apply_fn-based recognizer: pass "
                "hidden_shapes=[h1, h2, ...] or override init_carry")
        return tuple((jnp.zeros((batch, h), jnp.float32),
                      jnp.zeros((batch, h), jnp.float32))
                     for h in self._hidden_shapes)

    def new_state(self) -> RecognitionState:
        carry = self.init_carry(1)
        if self.variables is None:
            feats = self._jnp.zeros((1, 4, self.n_mels), self._jnp.float32)
            self.variables = self.module.init(
                self._jax.random.PRNGKey(self._seed), carry, feats)
        return RecognitionState(carry=carry)

    # --------------------------------------------------------------- decode
    def _ctc_append(self, state: RecognitionState, ids: np.ndarray) -> None:
        prev = state.prev_id
        out = []
        for i in ids:
            i = int(i)
            if i != prev and i != 0:
                out.append(self.alphabet[i])
            prev = i
        state.prev_id = prev
        state.text += "".join(out)

    def _frame_chunk(self, state: RecognitionState,
                     samples: np.ndarray) -> Optional[np.ndarray]:
        """Buffered EXACT framing: prepend the unconsumed sample tail so the
        feature sequence is identical to a single full-utterance pass no
        matter how the audio was chunked (window > hop means boundary frames
        straddle chunks).  Returns (T, n_mels) features or None if fewer
        than one window is buffered."""
        buf = np.concatenate([state.lookback, np.asarray(samples, np.float32)])
        if len(buf) < self.frame:
            state.lookback = buf
            return None
        n_frames = 1 + (len(buf) - self.frame) // self.hop
        used = buf[: (n_frames - 1) * self.hop + self.frame]
        state.lookback = buf[n_frames * self.hop:]
        return log_mel(used, self.sample_rate, self.n_mels,
                       frame_ms=self.frame_ms, hop_ms=self.hop_ms)

    def _step(self, state: RecognitionState, feats: np.ndarray) -> None:
        state.carry, logits = self._apply(self.variables, state.carry,
                                          feats[None])
        ids = np.asarray(self._jnp.argmax(logits[0], axis=-1))
        self._ctc_append(state, ids)
        state.frames_seen += feats.shape[0]

    def process_chunk(self, state: RecognitionState, samples: np.ndarray,
                      speaker_hook: Optional[Callable] = None) -> Dict[str, Any]:
        """One chunk -> one device step -> incremental hypothesis event.
        ``speaker_hook(state, feats)`` runs after featurization and before
        the event is built (ConversationTranscription's diarization)."""
        offset_s = state.frames_seen * self.hop / self.sample_rate
        feats = self._frame_chunk(state, samples)
        if feats is None:
            return {"status": "Buffering", "text": state.text,
                    "offset": offset_s, "duration": 0.0,
                    "speaker": state.speaker}
        if speaker_hook is not None:
            speaker_hook(state, feats)
        self._step(state, feats)
        return {"status": "Recognizing", "text": state.text,
                "offset": offset_s,
                "duration": feats.shape[0] * self.hop / self.sample_rate,
                "speaker": state.speaker}

    def finish(self, state: RecognitionState) -> Dict[str, Any]:
        """Flush: a stream shorter than one window still yields one padded
        frame (matching batch log_mel's pad-if-short behavior); a longer
        stream's sub-window tail is dropped exactly as batch framing drops
        it."""
        if state.frames_seen == 0 and len(state.lookback):
            feats = log_mel(state.lookback, self.sample_rate, self.n_mels,
                            frame_ms=self.frame_ms, hop_ms=self.hop_ms)
            self._step(state, feats)
        state.lookback = np.zeros(0, np.float32)
        return {"status": "Recognized", "text": state.text, "offset": 0.0,
                "duration": state.frames_seen * self.hop / self.sample_rate,
                "speaker": state.speaker}

    # ------------------------------------------------------------ streaming
    def transcribe_stream(self, stream, events: Optional[BlockingQueueIterator] = None):
        """Pull-stream in, event iterator out (the SDK bridge pattern:
        producer thread pushes recognition events, consumer iterates).
        Producer errors propagate to the consumer via the queue."""
        events = events or BlockingQueueIterator()

        def produce():
            try:
                state = self.new_state()
                for chunk in stream.chunks(self.chunk_samples):
                    events.put(self.process_chunk(state, chunk))
                events.put(self.finish(state))
            except Exception as e:  # noqa: BLE001
                events.put_error(e)
            finally:
                events.close()

        threading.Thread(target=produce, daemon=True).start()
        return events


def _speaker_attribute(state: RecognitionState, feats_mean: np.ndarray,
                       threshold: float = 0.97) -> None:
    """Online diarization: cosine-match the chunk's mel centroid against
    known speaker centroids; a poor match opens a new 'Guest-N'."""
    v = feats_mean / (np.linalg.norm(feats_mean) + 1e-8)
    best, best_i = -1.0, -1
    for i, c in enumerate(state.speaker_centroids):
        sim = float(v @ c / (np.linalg.norm(c) + 1e-8))
        if sim > best:
            best, best_i = sim, i
    if best_i < 0 or best < threshold:
        state.speaker_centroids.append(v.copy())
        best_i = len(state.speaker_centroids) - 1
    else:
        c = state.speaker_centroids[best_i]
        state.speaker_centroids[best_i] = 0.9 * c + 0.1 * v
    state.speaker = f"Guest-{best_i + 1}"


class SpeechToTextSDK(Transformer, HasInputCol, HasOutputCol):
    """Streaming recognition transformer: an audio column (wav bytes or raw
    float PCM) -> a column of recognition events (list of dicts with
    status/text/offset/duration), plus a ``<output>_text`` column holding
    the final transcript.  Reference ``SpeechToTextSDK.scala:419``."""

    recognizer = ComplexParam("recognizer", "StreamingRecognizer (model bundle)")
    sample_rate = Param("sample_rate", "PCM sample rate for raw arrays", "int",
                        default=16000)
    audio_format = Param("audio_format", "wav | pcm", "string", default="wav")
    chunk_s = Param("chunk_s", "seconds of audio per streamed chunk", "float",
                    default=0.5)
    detailed = Param("detailed", "keep intermediate Recognizing events",
                     "bool", default=True)

    def __init__(self, uid: Optional[str] = None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    def _get_recognizer(self) -> StreamingRecognizer:
        rec = self.get("recognizer")
        if rec is None:
            rec = StreamingRecognizer(sample_rate=self.get("sample_rate"),
                                      chunk_s=self.get("chunk_s"))
            self.set("recognizer", rec)
        return rec

    def _stream_for(self, rec: StreamingRecognizer, cell):
        from ..io.audio import PullAudioStream, resample
        stream = audio_stream(cell, self.get("sample_rate"),
                              self.get("audio_format"))
        if stream.sample_rate != rec.sample_rate:
            # wav headers carry their own rate — resample to the model's
            # so the filterbank and offset math stay correct
            stream = PullAudioStream(resample(stream.samples,
                                              stream.sample_rate,
                                              rec.sample_rate),
                                     rec.sample_rate)
        return stream

    def _events_for(self, rec: StreamingRecognizer, cell) -> List[Dict]:
        # direct synchronous loop — the BlockingQueueIterator thread bridge
        # is only for the truly streaming transcribe_stream() API
        stream = self._stream_for(rec, cell)
        state = rec.new_state()
        events = [rec.process_chunk(state, chunk)
                  for chunk in stream.chunks(rec.chunk_samples)]
        events.append(rec.finish(state))
        if not self.get("detailed"):
            events = [e for e in events if e["status"] == "Recognized"]
        return events

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")
        rec = self._get_recognizer()

        def per_part(p):
            n = len(p[in_col])
            ev_col = np.empty(n, dtype=object)
            text_col = np.empty(n, dtype=object)
            for i in range(n):
                events = self._events_for(rec, p[in_col][i])
                ev_col[i] = events
                text_col[i] = events[-1]["text"] if events else ""
            return {**p, out_col: ev_col, f"{out_col}_text": text_col}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("input_col"))
        schema = schema.add(self.get_or_fail("output_col"), ColumnType.STRUCT)
        return schema.add(f"{self.get_or_fail('output_col')}_text",
                          ColumnType.STRING)


class ConversationTranscription(SpeechToTextSDK):
    """SpeechToTextSDK + online speaker attribution: each event carries a
    ``speaker`` id assigned by cosine-matching chunk feature centroids.
    Reference ``SpeechToTextSDK.scala:491`` (ConversationTranscription)."""

    def _events_for(self, rec: StreamingRecognizer, cell) -> List[Dict]:
        stream = self._stream_for(rec, cell)
        state = rec.new_state()
        events = []

        def hook(st, feats):  # features computed once, inside process_chunk
            _speaker_attribute(st, feats.mean(axis=0))

        for chunk in stream.chunks(rec.chunk_samples):
            events.append(rec.process_chunk(state, chunk, speaker_hook=hook))
        events.append(rec.finish(state))
        if not self.get("detailed"):
            events = [e for e in events if e["status"] == "Recognized"]
        return events


class SpeechServingModel(Transformer):
    """Serving-engine bridge: stateful sessions over the streaming source.

    Each request is ``{"session": id, "chunk": [floats], "final": bool}``;
    the reply is the incremental hypothesis for that session.  Drop this
    into ``PipelineServer``/``read_stream().transform_with(...)`` and the
    serving engine becomes a streaming transcription endpoint.
    """

    def __init__(self, recognizer: Optional[StreamingRecognizer] = None,
                 input_col: str = "request", reply_col: str = "reply",
                 session_ttl_s: float = 300.0, uid: Optional[str] = None):
        super().__init__(uid)
        self.recognizer = recognizer or StreamingRecognizer()
        self.input_col, self.reply_col = input_col, reply_col
        self._sessions: Dict[str, Tuple[float, RecognitionState,
                                        threading.Lock]] = {}
        self._lock = threading.Lock()
        self.session_ttl_s = session_ttl_s

    def _state(self, sid: str) -> Tuple[RecognitionState, threading.Lock]:
        """Returns the session's state AND its lock — callers mutate the
        state (pending buffer, LSTM carry, CTC prev_id) under the session
        lock so concurrent requests for one session serialize instead of
        corrupting the transcript."""
        import time
        with self._lock:
            now = time.monotonic()
            for k in [k for k, (t, _, _) in self._sessions.items()
                      if now - t > self.session_ttl_s]:
                del self._sessions[k]
            if sid not in self._sessions:
                self._sessions[sid] = (now, self.recognizer.new_state(),
                                       threading.Lock())
            t, st, lk = self._sessions[sid]
            self._sessions[sid] = (now, st, lk)
            return st, lk

    def _transform(self, df: DataFrame) -> DataFrame:
        def per_part(p):
            n = len(p[self.input_col])
            out = np.empty(n, dtype=object)
            for i in range(n):
                req = p[self.input_col][i]
                sid = str(req.get("session", "default"))
                state, session_lock = self._state(sid)
                rec = self.recognizer
                with session_lock:
                    # buffer client chunks into fixed device-step sizes so
                    # the compiled shape never changes mid-session (pad
                    # frames would otherwise pollute the LSTM carry)
                    incoming = np.asarray(req.get("chunk", []), np.float32)
                    state.pending = np.concatenate([state.pending, incoming])
                    ev = None
                    while len(state.pending) >= rec.chunk_samples:
                        full, state.pending = (state.pending[:rec.chunk_samples],
                                               state.pending[rec.chunk_samples:])
                        ev = rec.process_chunk(state, full)
                    if req.get("final"):
                        if len(state.pending):
                            rec.process_chunk(state, state.pending)
                            state.pending = np.zeros(0, np.float32)
                        ev = rec.finish(state)
                        with self._lock:
                            self._sessions.pop(sid, None)
                    elif ev is None:  # not enough buffered for a step yet
                        ev = {"status": "Buffering", "text": state.text,
                              "offset": state.frames_seen * rec.hop
                              / rec.sample_rate,
                              "duration": 0.0, "speaker": state.speaker}
                out[i] = ev
            return {**p, self.reply_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        return schema.add(self.reply_col, ColumnType.STRUCT)
