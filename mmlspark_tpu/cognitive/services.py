"""Concrete cognitive-service transformers.

Reference: one transformer per API under ``cognitive/src/main/scala/.../
cognitive/`` (SURVEY.md §2.8): TextAnalytics (sentiment/NER/key-phrase/
language), ComputerVision (OCR/analyze/describe/tag/thumbnail), Face,
SpeechToText, AnomalyDetector, TextTranslator, FormRecognizer,
BingImageSearch.  Schemas follow the public Azure REST contracts; this
environment is zero-egress so tests exercise them against a local mock.
"""
from __future__ import annotations

import base64
import json
from typing import Any, Optional

from ..core import Param, ServiceParam
from ..core.dataframe import Row
from ..io.http import HTTPRequestData
from .base import CognitiveServicesBase


# ---------------------------------------------------------------------------
# Text Analytics (reference TextAnalytics.scala; v3 document batch contract)
# ---------------------------------------------------------------------------

class _TextAnalyticsBase(CognitiveServicesBase):
    text = ServiceParam("text", "document text", required=True)
    language = ServiceParam("language", "document language", default="en")

    def _build_request(self, row: Row) -> Optional[HTTPRequestData]:
        text = self._resolve_service("text", row)
        if text is None:
            return None
        lang = self._resolve_service("language", row, "en")
        texts = text if isinstance(text, (list, tuple)) else [text]
        langs = lang if isinstance(lang, (list, tuple)) else [lang] * len(texts)
        docs = [{"id": str(i), "text": t, "language": l}
                for i, (t, l) in enumerate(zip(texts, langs))]
        return HTTPRequestData.post_json(self._base_url(),
                                         {"documents": docs},
                                         self._headers(row))


class TextSentiment(_TextAnalyticsBase):
    _url_path = "/text/analytics/v3.0/sentiment"


class LanguageDetector(_TextAnalyticsBase):
    _url_path = "/text/analytics/v3.0/languages"


class EntityDetector(_TextAnalyticsBase):
    _url_path = "/text/analytics/v3.0/entities/linking"


class NER(_TextAnalyticsBase):
    _url_path = "/text/analytics/v3.0/entities/recognition/general"


class PII(_TextAnalyticsBase):
    _url_path = "/text/analytics/v3.0/entities/recognition/pii"


class KeyPhraseExtractor(_TextAnalyticsBase):
    _url_path = "/text/analytics/v3.0/keyPhrases"


# ---------------------------------------------------------------------------
# Computer Vision (reference ComputerVision.scala)
# ---------------------------------------------------------------------------

class _ImageServiceBase(CognitiveServicesBase):
    image_url = ServiceParam("image_url", "public image url")
    image_bytes = ServiceParam("image_bytes", "raw image bytes")

    def _image_request(self, row: Row, url: str) -> Optional[HTTPRequestData]:
        img_url = self._resolve_service("image_url", row)
        img_bytes = self._resolve_service("image_bytes", row)
        headers = self._headers(row)
        if img_url is not None:
            return HTTPRequestData.post_json(url, {"url": img_url}, headers)
        if img_bytes is not None:
            headers["Content-Type"] = "application/octet-stream"
            return HTTPRequestData(url=url, method="POST", headers=headers,
                                   entity=bytes(img_bytes))
        return None

    def _build_request(self, row: Row) -> Optional[HTTPRequestData]:
        return self._image_request(row, self._full_url(row))

    def _full_url(self, row: Row) -> str:
        return self._base_url()


class OCR(_ImageServiceBase):
    _url_path = "/vision/v3.2/ocr"
    detect_orientation = Param("detect_orientation", "detect text orientation", "bool", default=True)

    def _full_url(self, row):
        return f"{self._base_url()}?detectOrientation={str(self.get('detect_orientation')).lower()}"


class AnalyzeImage(_ImageServiceBase):
    _url_path = "/vision/v3.2/analyze"
    visual_features = Param("visual_features", "features to extract", "list",
                            default=["Categories", "Tags", "Description"])

    def _full_url(self, row):
        return f"{self._base_url()}?visualFeatures={','.join(self.get('visual_features'))}"


class DescribeImage(_ImageServiceBase):
    _url_path = "/vision/v3.2/describe"
    max_candidates = Param("max_candidates", "caption candidates", "int", default=1)

    def _full_url(self, row):
        return f"{self._base_url()}?maxCandidates={self.get('max_candidates')}"


class TagImage(_ImageServiceBase):
    _url_path = "/vision/v3.2/tag"


class RecognizeText(_ImageServiceBase):
    _url_path = "/vision/v3.2/read/analyze"


class RecognizeDomainSpecificContent(_ImageServiceBase):
    """Domain-model image analysis (celebrities/landmarks) — reference
    ``RecognizeDomainSpecificContent`` (Celebrity Quote Analysis notebook).
    The domain model is part of the endpoint path; the URL is resolved at
    request-build time, so ``model`` and ``set_location`` may be set in any
    order."""
    model = Param("model", "domain model name (celebrities|landmarks)",
                  "string", default="celebrities")

    @property
    def _url_path(self) -> str:  # type: ignore[override]
        return f"/vision/v3.2/models/{self.get('model')}/analyze"


class GenerateThumbnails(_ImageServiceBase):
    _url_path = "/vision/v3.2/generateThumbnail"
    width = Param("width", "thumbnail width", "int", default=64)
    height = Param("height", "thumbnail height", "int", default=64)
    smart_cropping = Param("smart_cropping", "smart crop", "bool", default=True)

    def _full_url(self, row):
        return (f"{self._base_url()}?width={self.get('width')}"
                f"&height={self.get('height')}&smartCropping="
                f"{str(self.get('smart_cropping')).lower()}")

    def _parse_response(self, resp):
        return base64.b64encode(resp.entity or b"").decode()


# ---------------------------------------------------------------------------
# Face (reference Face.scala)
# ---------------------------------------------------------------------------

class DetectFace(_ImageServiceBase):
    _url_path = "/face/v1.0/detect"
    return_face_attributes = Param("return_face_attributes", "attributes", "list", default=[])

    def _full_url(self, row):
        attrs = ",".join(self.get("return_face_attributes") or [])
        suffix = f"?returnFaceAttributes={attrs}" if attrs else ""
        return self._base_url() + suffix


class _JsonBodyService(CognitiveServicesBase):
    """Services posting an explicit JSON body from a column."""
    body = ServiceParam("body", "JSON request body", required=True)

    def _build_request(self, row):
        body = self._resolve_service("body", row)
        if body is None:
            return None
        return HTTPRequestData.post_json(self._base_url(), body,
                                         self._headers(row))


class VerifyFaces(_JsonBodyService):
    _url_path = "/face/v1.0/verify"


class GroupFaces(_JsonBodyService):
    _url_path = "/face/v1.0/group"


class IdentifyFaces(_JsonBodyService):
    _url_path = "/face/v1.0/identify"


class FindSimilarFace(_JsonBodyService):
    _url_path = "/face/v1.0/findsimilars"


# ---------------------------------------------------------------------------
# Anomaly Detector (reference AnomalyDetection.scala)
# ---------------------------------------------------------------------------

class _AnomalyBase(CognitiveServicesBase):
    series = ServiceParam("series", "list of {timestamp, value} points", required=True)
    granularity = ServiceParam("granularity", "series granularity", default="daily")
    sensitivity = ServiceParam("sensitivity", "detection sensitivity 0-99")

    def _build_request(self, row):
        series = self._resolve_service("series", row)
        if series is None:
            return None
        body = {"series": [dict(p) for p in series],
                "granularity": self._resolve_service("granularity", row, "daily")}
        sens = self._resolve_service("sensitivity", row)
        if sens is not None:
            body["sensitivity"] = sens
        return HTTPRequestData.post_json(self._base_url(), body,
                                         self._headers(row))


class DetectLastAnomaly(_AnomalyBase):
    _url_path = "/anomalydetector/v1.0/timeseries/last/detect"


class DetectAnomalies(_AnomalyBase):
    _url_path = "/anomalydetector/v1.0/timeseries/entire/detect"


# ---------------------------------------------------------------------------
# Translator (reference TextTranslator.scala; global endpoint)
# ---------------------------------------------------------------------------

class _TranslatorBase(CognitiveServicesBase):
    _service = "cognitive.microsofttranslator.com"
    text = ServiceParam("text", "text(s) to process", required=True)
    to_language = ServiceParam("to_language", "target language(s)", default="en")
    subscription_region = ServiceParam("subscription_region", "resource region")

    def _headers(self, row):
        h = super()._headers(row)
        region = self._resolve_service("subscription_region", row)
        if region:
            h["Ocp-Apim-Subscription-Region"] = str(region)
        return h

    def _body(self, row):
        text = self._resolve_service("text", row)
        texts = text if isinstance(text, (list, tuple)) else [text]
        return [{"Text": t} for t in texts]

    def _build_request(self, row):
        if self._resolve_service("text", row) is None:
            return None
        return HTTPRequestData.post_json(self._full_url(row), self._body(row),
                                         self._headers(row))

    def _full_url(self, row):
        return self._base_url()


class Translate(_TranslatorBase):
    _url_path = "/translate?api-version=3.0"

    def _full_url(self, row):
        to = self._resolve_service("to_language", row, "en")
        tos = to if isinstance(to, (list, tuple)) else [to]
        return self._base_url() + "".join(f"&to={t}" for t in tos)


class Transliterate(_TranslatorBase):
    _url_path = "/transliterate?api-version=3.0"


class BreakSentence(_TranslatorBase):
    _url_path = "/breaksentence?api-version=3.0"


class Detect(_TranslatorBase):
    _url_path = "/detect?api-version=3.0"


# ---------------------------------------------------------------------------
# Form Recognizer (reference FormRecognizer.scala)
# ---------------------------------------------------------------------------

class _FormRecognizerBase(_ImageServiceBase):
    pass


class AnalyzeLayout(_FormRecognizerBase):
    _url_path = "/formrecognizer/v2.1/layout/analyze"


class AnalyzeReceipts(_FormRecognizerBase):
    _url_path = "/formrecognizer/v2.1/prebuilt/receipt/analyze"


class AnalyzeBusinessCards(_FormRecognizerBase):
    _url_path = "/formrecognizer/v2.1/prebuilt/businessCard/analyze"


class AnalyzeInvoices(_FormRecognizerBase):
    _url_path = "/formrecognizer/v2.1/prebuilt/invoice/analyze"


class AnalyzeIDDocuments(_FormRecognizerBase):
    _url_path = "/formrecognizer/v2.1/prebuilt/idDocument/analyze"


# ---------------------------------------------------------------------------
# Speech-to-text (reference SpeechToText.scala REST path; the streaming SDK
# variant SpeechToTextSDK is N/A without the native Speech SDK — the REST
# short-audio contract is provided)
# ---------------------------------------------------------------------------

class SpeechToText(CognitiveServicesBase):
    _service = "stt.speech.microsoft.com"
    _url_path = "/speech/recognition/conversation/cognitiveservices/v1"
    audio_data = ServiceParam("audio_data", "wav bytes", required=True)
    language = ServiceParam("language", "recognition language", default="en-US")
    format = ServiceParam("format", "simple|detailed", default="simple")

    def _build_request(self, row):
        audio = self._resolve_service("audio_data", row)
        if audio is None:
            return None
        lang = self._resolve_service("language", row, "en-US")
        fmt = self._resolve_service("format", row, "simple")
        headers = self._headers(row)
        headers["Content-Type"] = "audio/wav; codecs=audio/pcm; samplerate=16000"
        url = f"{self._base_url()}?language={lang}&format={fmt}"
        return HTTPRequestData(url=url, method="POST", headers=headers,
                               entity=bytes(audio))


# ---------------------------------------------------------------------------
# Bing image search (reference BingImageSearch.scala)
# ---------------------------------------------------------------------------

class BingImageSearch(CognitiveServicesBase):
    _service = "api.bing.microsoft.com"
    _url_path = "/v7.0/images/search"
    query = ServiceParam("query", "search query", required=True)
    count = Param("count", "results per query", "int", default=10)
    offset = Param("offset", "result offset", "int", default=0)

    def _build_request(self, row):
        q = self._resolve_service("query", row)
        if q is None:
            return None
        import urllib.parse
        url = (f"{self._base_url()}?q={urllib.parse.quote(str(q))}"
               f"&count={self.get('count')}&offset={self.get('offset')}")
        return HTTPRequestData(url=url, method="GET", headers=self._headers(row))

    @staticmethod
    def download_from_urls(df, url_col: str, bytes_col: str = "image_bytes",
                           concurrency: int = 8):
        """Reference BingImageSearch.downloadFromUrls helper."""
        from ..io.http import AsyncHTTPClient, HTTPRequestData as Req
        import numpy as np

        def per_part(p):
            client = AsyncHTTPClient(concurrency=concurrency)
            reqs = [None if u is None else Req(url=str(u)) for u in p[url_col]]
            resps = client.send_all(reqs)
            out = np.empty(len(reqs), dtype=object)
            for i, r in enumerate(resps):
                out[i] = r.entity if r is not None and r.status_code == 200 else None
            return {**p, bytes_col: out}

        return df.map_partitions(per_part)
