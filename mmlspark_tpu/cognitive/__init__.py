from .base import CognitiveServicesBase
from .services import (TextSentiment, LanguageDetector, EntityDetector, NER,
                       PII, KeyPhraseExtractor, OCR, AnalyzeImage,
                       DescribeImage, TagImage, RecognizeText,
                       RecognizeDomainSpecificContent,
                       GenerateThumbnails, DetectFace, VerifyFaces,
                       GroupFaces, IdentifyFaces, FindSimilarFace,
                       DetectLastAnomaly, DetectAnomalies, Translate,
                       Transliterate, BreakSentence, Detect, AnalyzeLayout,
                       AnalyzeReceipts, AnalyzeBusinessCards, AnalyzeInvoices,
                       AnalyzeIDDocuments, SpeechToText, BingImageSearch)
from .search import AzureSearchWriter
from .speech import (SpeechToTextSDK, ConversationTranscription,
                     StreamingRecognizer, SpeechServingModel)

__all__ = ["CognitiveServicesBase", "TextSentiment", "LanguageDetector",
           "EntityDetector", "NER", "PII", "KeyPhraseExtractor", "OCR",
           "AnalyzeImage", "DescribeImage", "TagImage", "RecognizeText",
           "RecognizeDomainSpecificContent",
           "GenerateThumbnails", "DetectFace", "VerifyFaces", "GroupFaces",
           "IdentifyFaces", "FindSimilarFace", "DetectLastAnomaly",
           "DetectAnomalies", "Translate", "Transliterate", "BreakSentence",
           "Detect", "AnalyzeLayout", "AnalyzeReceipts",
           "AnalyzeBusinessCards", "AnalyzeInvoices", "AnalyzeIDDocuments",
           "SpeechToText", "BingImageSearch", "AzureSearchWriter",
           "SpeechToTextSDK", "ConversationTranscription",
           "StreamingRecognizer", "SpeechServingModel"]
