from .base import CognitiveServicesBase
from .services import (TextSentiment, LanguageDetector, EntityDetector, NER,
                       PII, KeyPhraseExtractor, OCR, AnalyzeImage,
                       DescribeImage, TagImage, RecognizeText,
                       GenerateThumbnails, DetectFace, VerifyFaces,
                       GroupFaces, IdentifyFaces, FindSimilarFace,
                       DetectLastAnomaly, DetectAnomalies, Translate,
                       Transliterate, BreakSentence, Detect, AnalyzeLayout,
                       AnalyzeReceipts, AnalyzeBusinessCards, AnalyzeInvoices,
                       AnalyzeIDDocuments, SpeechToText, BingImageSearch)
from .search import AzureSearchWriter

__all__ = ["CognitiveServicesBase", "TextSentiment", "LanguageDetector",
           "EntityDetector", "NER", "PII", "KeyPhraseExtractor", "OCR",
           "AnalyzeImage", "DescribeImage", "TagImage", "RecognizeText",
           "GenerateThumbnails", "DetectFace", "VerifyFaces", "GroupFaces",
           "IdentifyFaces", "FindSimilarFace", "DetectLastAnomaly",
           "DetectAnomalies", "Translate", "Transliterate", "BreakSentence",
           "Detect", "AnalyzeLayout", "AnalyzeReceipts",
           "AnalyzeBusinessCards", "AnalyzeInvoices", "AnalyzeIDDocuments",
           "SpeechToText", "BingImageSearch", "AzureSearchWriter"]
