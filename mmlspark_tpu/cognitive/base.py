"""Cognitive-service transformer base.

Reference: ``cognitive/.../CognitiveServiceBase.scala`` —
``HasServiceParams`` (:29, value-or-column duality), ``HasCognitiveServiceInput``
(:155, URL/header/body assembly), ``HasInternalJsonOutputParser`` (:210),
``CognitiveServicesBase`` (:258: internally composes Lambda -> SimpleHTTP
Transformer -> DropColumns pipeline).

Same architecture here: subclasses declare ServiceParams and implement
``_build_request(row)``; the base resolves params per-row, posts through the
async retrying client, parses JSON into the output column with an error
column for failures.  ``set_location`` fills the standard Azure URL template;
``set_linked_service`` is accepted for API parity (resolves to url+key).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional

import numpy as np

from ..core import (DataFrame, HasOutputCol, Param, ServiceParam, Transformer)
from ..core.dataframe import Row, _part_len
from ..core.schema import ColumnType
from ..io.http import AsyncHTTPClient, HTTPRequestData, HTTPResponseData


class CognitiveServicesBase(Transformer, HasOutputCol):
    subscription_key = ServiceParam("subscription_key", "API key (value or column)")
    url = Param("url", "full endpoint URL", "string")
    location = Param("location", "Azure region; endpoint URL is resolved from "
                     "it at request-build time", "string")
    error_col = Param("error_col", "error output column", "string", default="error")
    concurrency = Param("concurrency", "max in-flight requests", "int", default=4)
    timeout = Param("timeout", "per-request timeout seconds", "float", default=60.0)
    breaker = Param("breaker", "shared CircuitBreaker guarding this service "
                    "endpoint (utils/resilience.py); open circuit -> "
                    "synthetic 503 rows in error_col, no network calls",
                    "object", default=None)

    _url_path: str = ""          # subclass: path under the location endpoint
    _service: str = "api.cognitive.microsoft.com"

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        for k, v in kwargs.items():
            if k.endswith("_col") and isinstance(type(self)._params.get(k.replace("_col", "")), ServiceParam):
                self.set_col(k.replace("_col", ""), v)
            else:
                self.set(k, v)

    # ------------------------------------------------------------- url setup
    def set_location(self, location: str):
        """Reference HasSetLocation (:244): region -> standard endpoint.

        Only the region is stored; the URL is resolved lazily by
        ``_base_url`` so params that feed ``_url_path`` (e.g.
        RecognizeDomainSpecificContent.model) can be set in any order."""
        self.set("location", location)
        return self

    def _base_url(self) -> str:
        """Endpoint resolved at request-build time: an explicitly set ``url``
        wins; otherwise it is recomputed from location + the CURRENT
        ``_url_path`` so param-set order cannot leave a stale endpoint."""
        url = self.get("url")
        if url is not None:
            return url
        loc = self.get("location")
        if loc is not None:
            return f"https://{loc}.{self._service}{self._url_path}"
        return self.get_or_fail("url")  # raises the standard missing-param error

    def set_linked_service(self, name: str):
        """Accepted for parity (reference HasSetLinkedService:223 resolves
        Synapse linked services; here it must be pre-resolved)."""
        raise NotImplementedError(
            "linked services are a Synapse-only concept; call set_location + "
            "set_subscription_key instead")

    # ------------------------------------------------------------- request
    def _headers(self, row: Row) -> Dict[str, str]:
        headers = {"Content-Type": "application/json"}
        key = self.get("subscription_key")
        if key is not None:
            headers["Ocp-Apim-Subscription-Key"] = str(key.resolve(row))
        return headers

    def _build_request(self, row: Row) -> Optional[HTTPRequestData]:
        """Subclasses build the request; None skips the row (reference
        emits null outputs for rows with missing required params)."""
        raise NotImplementedError

    def _parse_response(self, resp: HTTPResponseData) -> Any:
        return resp.json()

    def _resolve_service(self, param_name: str, row: Row, default=None):
        v = self.get(param_name)
        if v is None:
            return default
        return v.resolve(row) if hasattr(v, "resolve") else v

    # ------------------------------------------------------------- transform
    def _transform(self, df: DataFrame) -> DataFrame:
        out_col = self.get_or_fail("output_col")
        err_col = self.get("error_col")

        def per_part(p):
            n = _part_len(p)
            rows = [Row({k: p[k][i] for k in p}) for i in range(n)]
            reqs = [self._build_request(r) for r in rows]
            client = AsyncHTTPClient(concurrency=self.get("concurrency"),
                                     timeout_s=self.get("timeout"),
                                     breaker=self.get("breaker"))
            resps = client.send_all(reqs)
            out = np.empty(n, dtype=object)
            errs = np.empty(n, dtype=object)
            for i, r in enumerate(resps):
                if r is None:
                    out[i], errs[i] = None, None
                elif 200 <= r.status_code < 300:
                    try:
                        out[i], errs[i] = self._parse_response(r), None
                    except Exception as e:  # noqa: BLE001
                        out[i], errs[i] = None, f"parse: {e}"
                else:
                    out[i] = None
                    errs[i] = {"status_code": r.status_code, "reason": r.reason,
                               "body": (r.entity or b"")[:500].decode("utf-8", "replace")}
            res = {**p, out_col: out}
            if err_col:
                res[err_col] = errs
            return res

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        s = schema.add(self.get_or_fail("output_col"), ColumnType.STRUCT)
        if self.get("error_col"):
            s = s.add(self.get("error_col"), ColumnType.STRUCT)
        return s
