"""AzureSearchWriter — push frames into Azure Cognitive Search indexes.

Reference: ``cognitive/.../AzureSearch.scala:142,:332-345`` (index
auto-creation via ``AzureSearchAPI.scala``, batched document upload through
the HTTP stack).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ..core import DataFrame
from ..io.http import AsyncHTTPClient, HTTPRequestData


class AzureSearchWriter:
    API_VERSION = "2019-05-06"

    @staticmethod
    def _endpoint(service_name: str, index_name: str, path: str = "/docs/index") -> str:
        return (f"https://{service_name}.search.windows.net/indexes/{index_name}"
                f"{path}?api-version={AzureSearchWriter.API_VERSION}")

    @staticmethod
    def create_index(service_name: str, key: str, index_json: str) -> int:
        """Reference createIndex (AzureSearchAPI.scala)."""
        spec = json.loads(index_json)
        url = (f"https://{service_name}.search.windows.net/indexes"
               f"?api-version={AzureSearchWriter.API_VERSION}")
        client = AsyncHTTPClient(concurrency=1)
        resp = client.send(HTTPRequestData.post_json(url, spec, {"api-key": key}))
        return resp.status_code

    @staticmethod
    def write(df: DataFrame, service_name: str, index_name: str, key: str,
              action_col: Optional[str] = None, batch_size: int = 100,
              url_override: Optional[str] = None) -> List[int]:
        """Upload rows as search documents; returns per-batch status codes."""
        url = url_override or AzureSearchWriter._endpoint(service_name, index_name)
        client = AsyncHTTPClient(concurrency=4)
        statuses: List[int] = []
        rows = list(df.iter_rows())
        for s in range(0, len(rows), batch_size):
            docs = []
            for r in rows[s:s + batch_size]:
                doc = {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                       for k, v in r.items()}
                doc["@search.action"] = doc.pop(action_col, "mergeOrUpload") \
                    if action_col else "mergeOrUpload"
                docs.append(doc)
            resp = client.send(HTTPRequestData.post_json(
                url, {"value": docs}, {"api-key": key}))
            statuses.append(resp.status_code)
        return statuses
