"""CCY — concurrency sanitizer, the static half.

The serving plane is deeply multi-threaded (decode engine threads, watchdog
monitors, restart supervisors, checkpoint writers, prefetchers, span
flushers, federation fan-outs, drain paths) and every recent review round
caught at least one check-then-act or callback-under-lock race by hand.
This checker makes the concurrency contracts machine-checked, the same way
STG made the stage contracts machine-checked:

- **CCY001 — lock-order cycle.**  A whole-repo lock-acquisition-order graph
  is built over the scanned scope: node = a lock attribute resolved per
  class (``PipelineServer._drain_lock``) or per module
  (``collector._collector_lock``), edge = lock B acquired while A is held —
  lexically, or THROUGH a call edge (holding A and calling a function that
  acquires B).  Call edges resolve like the TRC cross-module BFS: local
  short names, ``self.`` methods, and import-table dotted targets.  Any
  cycle in the graph is a potential deadlock: two threads entering the
  cycle from different edges can block each other forever.

- **CCY002 — shared state without a lock.**  An attribute mutated both
  inside a ``threading.Thread(target=...)``/``Timer`` callback call graph
  and on a public API path, with no common lock protecting both sides, is
  a data race (the check-then-act shape every review round kept catching).

- **CCY003 — condition discipline.**  ``Condition.wait()`` outside a
  predicate loop misses wakeups (spurious wakeup / stolen predicate), and
  ``notify()`` without the condition's lock held races the very predicate
  change it is signalling.  ``wait_for`` carries its own loop and never
  fires.

- **CCY004 — thread leak.**  A started thread with no bounded ``join()``
  (or ``Timer.cancel()``) reachable from a ``close()``/``stop()``/
  ``drain()``-shaped teardown path outlives its owner: drains that "time
  out" on invisible work, interpreter-shutdown tracebacks, and chaos
  drills that cannot tell a leak from a hang.

The runtime half (``utils/concurrency.OrderedLock``) validates the same
graph dynamically; ``ConcurrencyChecker.lock_order_edges(engine)`` exports
the static edges in the runtime registry's naming, so
``validate_lock_order(static_edges=...)`` composes both halves.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .engine import AnalysisEngine, Checker, Finding, ModuleContext

__all__ = ["ConcurrencyChecker"]

#: constructor targets that make a lock-like object
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock",
               "make_lock", "make_rlock", "concurrency.make_lock",
               "concurrency.make_rlock",
               "mmlspark_tpu.utils.concurrency.make_lock",
               "mmlspark_tpu.utils.concurrency.make_rlock"}
_COND_CTORS = {"threading.Condition", "Condition", "make_condition",
               "concurrency.make_condition",
               "mmlspark_tpu.utils.concurrency.make_condition"}
_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}

#: teardown-shaped method names that root the CCY004 reachability walk
_STOP_NAMES = {"close", "stop", "drain", "shutdown", "cancel", "join",
               "stop_all", "terminate", "uninstall", "__exit__", "__del__",
               "abort"}

#: mutation targets CCY002 ignores: write-once identity fields assigned in
#: start()-shaped methods before the thread observes them would otherwise
#: dominate the findings (the thread handle itself, the httpd handle)
_CCY002_EXEMPT_SUFFIXES = ("_thread", "_httpd")


def _name_is_lock_like(name: str) -> bool:
    low = name.lower()
    return "lock" in low or "mutex" in low or "cond" in low


class _LockName:
    """Resolution of a lock expression to a stable graph-node name."""

    @staticmethod
    def resolve(expr: ast.AST, cls: Optional["_ClassRec"],
                module_tag: str) -> Optional[str]:
        target = expr
        if isinstance(target, ast.Call):        # with lock.acquire(...)
            target = target.func
        if isinstance(target, ast.Attribute) and \
                target.attr == "acquire":
            target = target.value
        if isinstance(target, ast.Attribute):
            owner = target.value
            if isinstance(owner, ast.Name) and owner.id in ("self", "cls") \
                    and cls is not None:
                if target.attr in cls.lock_attrs or \
                        target.attr in cls.cond_attrs or \
                        _name_is_lock_like(target.attr):
                    return f"{cls.name}.{target.attr}"
                return None
            if _name_is_lock_like(target.attr):
                # non-self attribute: deferred — finalize resolves the
                # owning class when exactly one class declares the attr
                return f"?.{target.attr}"
            return None
        if isinstance(target, ast.Name):
            if _name_is_lock_like(target.id):
                return f"{module_tag}.{target.id}"
            return None
        return None


class _FnRec:
    """Everything CCY needs to know about one function/method."""

    __slots__ = ("qualname", "cls", "name", "lineno",
                 "acquires", "edges", "held_calls", "calls", "ext_calls",
                 "attr_writes", "thread_targets", "thread_starts",
                 "joins", "cancels", "waits", "notifies", "handle_aliases")

    def __init__(self, qualname: str, cls: Optional[str], name: str,
                 lineno: int):
        self.qualname = qualname
        self.cls = cls                      # owning class name or None
        self.name = name                    # bare method/function name
        self.lineno = lineno
        #: lock names acquired lexically anywhere in this function
        self.acquires: List[Tuple[str, int]] = []
        #: (held, acquired, lineno) lexical order edges
        self.edges: List[Tuple[str, str, int]] = []
        #: (callee_key, held_names, lineno): call made while holding locks;
        #: callee_key is ("self", name) / ("local", name) / ("dotted", d)
        self.held_calls: List[Tuple[Tuple[str, str], Tuple[str, ...], int]] = []
        #: intra-module / intra-class call edges by bare name
        self.calls: Set[Tuple[str, str]] = set()   # (kind, name)
        self.ext_calls: Set[str] = set()
        #: (attr, locks_held, lineno, is_augmented_or_method_mutation)
        self.attr_writes: List[Tuple[str, FrozenSet[str], int]] = []
        #: method/function names passed as Thread target / Timer callback
        self.thread_targets: List[Tuple[str, str]] = []  # (kind, name)
        #: (handle, kind, daemon, lineno): handle = "self.X" / local name /
        #: "" for anonymous fire-and-forget
        self.thread_starts: List[Tuple[str, str, bool, int]] = []
        #: handle -> bounded? for .join(...) sites in this function
        self.joins: List[Tuple[str, bool, int]] = []
        self.cancels: Set[str] = set()
        #: (cond_name, inside_while, lineno)
        self.waits: List[Tuple[str, bool, int]] = []
        #: (cond_name, held_names, lineno)
        self.notifies: List[Tuple[str, Tuple[str, ...], int]] = []
        #: local name -> self attrs it aliases (``t = self._thread``,
        #: ``self._thread = t``, ``self._threads.append(t)``,
        #: ``for t in (self._a, self._b)``) — joins/cancels through an
        #: alias credit the attribute, and a start through an aliased
        #: local is owned by the attribute
        self.handle_aliases: Dict[str, Set[str]] = {}


class _ClassRec:
    __slots__ = ("name", "relpath", "lineno", "lock_attrs", "cond_attrs",
                 "methods", "bases")

    def __init__(self, name: str, relpath: str, lineno: int,
                 bases: Sequence[str]):
        self.name = name
        self.relpath = relpath
        self.lineno = lineno
        self.lock_attrs: Set[str] = set()
        self.cond_attrs: Set[str] = set()
        self.methods: Dict[str, _FnRec] = {}
        self.bases = list(bases)


class _ModRec:
    __slots__ = ("relpath", "tag", "classes", "functions", "imports")

    def __init__(self, relpath: str, tag: str, imports: Dict[str, str]):
        self.relpath = relpath
        self.tag = tag                      # last module path segment
        self.classes: Dict[str, _ClassRec] = {}
        self.functions: Dict[str, _FnRec] = {}   # module-level, by name
        self.imports = imports


def _call_dotted(ctx: ModuleContext, node: ast.Call) -> Optional[str]:
    return ctx.dotted_name(node.func)


class ConcurrencyChecker(Checker):
    """CCY001 lock-order cycle, CCY002 shared-state-without-lock,
    CCY003 condition discipline, CCY004 thread leak."""

    rules = {
        "CCY001": "lock-order cycle across the acquisition graph "
                  "(potential deadlock)",
        "CCY002": "attribute mutated on both a thread path and a public "
                  "path with no common lock",
        "CCY003": "Condition.wait() outside a predicate loop / notify() "
                  "without the condition's lock held",
        "CCY004": "started thread with no bounded join()/cancel() "
                  "reachable from a close()/stop()/drain() path",
    }

    def __init__(self):
        self._mods: Dict[str, _ModRec] = {}

    def interested(self, relpath: str) -> bool:
        return True

    # ---------------------------------------------------------- collection
    def end_module(self, ctx: ModuleContext) -> None:
        tag = ctx.relpath.rsplit("/", 1)[-1]
        tag = tag[:-3] if tag.endswith(".py") else tag
        mod = _ModRec(ctx.relpath, tag, dict(ctx.imports))
        self._mods[ctx.relpath] = mod
        for node in ctx.tree.body:
            self._collect_top(node, ctx, mod)

    def _collect_top(self, node: ast.stmt, ctx: ModuleContext,
                     mod: _ModRec) -> None:
        if isinstance(node, ast.ClassDef):
            bases = []
            for b in node.bases:
                dotted = ctx.dotted_name(b)
                if dotted:
                    bases.append(dotted.split(".")[-1])
            cls = _ClassRec(node.name, ctx.relpath, node.lineno, bases)
            mod.classes[node.name] = cls
            # pre-pass: lock/cond attribute declarations anywhere in the
            # class body (usually __init__), so method walks can resolve
            for sub in ast.walk(node):
                self._collect_lock_decl(sub, ctx, cls)
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = _FnRec(f"{node.name}.{stmt.name}", node.name,
                                stmt.name, stmt.lineno)
                    cls.methods[stmt.name] = fn
                    _FnWalker(ctx, mod, cls, fn).walk_body(stmt)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = _FnRec(node.name, None, node.name, node.lineno)
            mod.functions[node.name] = fn
            _FnWalker(ctx, mod, None, fn).walk_body(node)

    def _collect_lock_decl(self, node: ast.AST, ctx: ModuleContext,
                           cls: _ClassRec) -> None:
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call)):
            return
        dotted = _call_dotted(ctx, node.value) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        is_lock = dotted in _LOCK_CTORS or leaf in ("Lock", "RLock",
                                                    "make_lock",
                                                    "make_rlock")
        is_cond = dotted in _COND_CTORS or leaf in ("Condition",
                                                    "make_condition")
        if not (is_lock or is_cond):
            return
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                (cls.cond_attrs if is_cond else cls.lock_attrs).add(tgt.attr)

    # ------------------------------------------------------------ finalize
    def finalize(self, engine: AnalysisEngine) -> List[Finding]:
        findings: List[Finding] = []
        resolver = _Resolver(self._mods)
        findings.extend(self._check_lock_order(resolver))
        for mod in self._mods.values():
            for cls in mod.classes.values():
                findings.extend(self._check_shared_state(mod, cls))
                findings.extend(self._check_conditions(mod, cls))
            findings.extend(self._check_thread_leaks(mod, resolver))
        return findings

    # ------------------------------------------------- CCY001: lock order
    def lock_order_edges(self, engine: Optional[AnalysisEngine] = None
                         ) -> List[Tuple[str, str]]:
        """The static acquisition-order edge set, in the runtime
        registry's node naming — feed to
        ``utils.concurrency.validate_lock_order(static_edges=...)``."""
        resolver = _Resolver(self._mods)
        return sorted({(a, b) for (a, b) in
                       self._edge_sites(resolver)})

    def _edge_sites(self, resolver: "_Resolver"
                    ) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
        """(held, acquired) -> first (relpath, lineno, symbol) site,
        lexical edges plus call-propagated edges."""
        sites: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        disamb = resolver.disambiguate_lock
        # transitive acquires per function (call-graph fixpoint)
        acq: Dict[int, Set[str]] = {}
        fns = resolver.all_fns
        for key, (mod, fn) in fns.items():
            acq[key] = {disamb(a) for a, _ in fn.acquires
                        if disamb(a) is not None}
        changed = True
        while changed:
            changed = False
            for key, (mod, fn) in fns.items():
                for callee_key, _, _ in fn.held_calls:
                    tgt = resolver.resolve_call(mod, fn, callee_key)
                    if tgt is None:
                        continue
                    extra = acq.get(tgt, ())
                    if not set(extra) <= acq[key]:
                        acq[key] |= set(extra)
                        changed = True
                for kind_name in fn.calls:
                    tgt = resolver.resolve_call(mod, fn, kind_name)
                    if tgt is None:
                        continue
                    if not acq.get(tgt, set()) <= acq[key]:
                        acq[key] |= acq.get(tgt, set())
                        changed = True
        for key, (mod, fn) in fns.items():
            sym = fn.qualname
            for held, got, lineno in fn.edges:
                h, g = disamb(held), disamb(got)
                if h and g and h != g:
                    sites.setdefault((h, g), (mod.relpath, lineno, sym))
            for callee_key, held_names, lineno in fn.held_calls:
                tgt = resolver.resolve_call(mod, fn, callee_key)
                if tgt is None:
                    continue
                for h0 in held_names:
                    h = disamb(h0)
                    if h is None:
                        continue
                    for g in acq.get(tgt, ()):
                        if g != h:
                            sites.setdefault(
                                (h, g), (mod.relpath, lineno, sym))
        return sites

    def _check_lock_order(self, resolver: "_Resolver") -> List[Finding]:
        sites = self._edge_sites(resolver)
        graph: Dict[str, Set[str]] = {}
        for (a, b) in sites:
            graph.setdefault(a, set()).add(b)
        findings: List[Finding] = []
        for scc in _sccs(graph):
            # describe the cycle with its edges' first-observed sites
            cyc_edges = sorted((a, b) for (a, b) in sites
                               if a in scc and b in scc)
            detail = "; ".join(
                f"{a} -> {b} at {sites[(a, b)][0]}:{sites[(a, b)][1]}"
                for a, b in cyc_edges[:4])
            rel, lineno, sym = sites[cyc_edges[0]]
            findings.append(Finding(
                rule="CCY001", file=rel, line=lineno,
                message=f"lock-order cycle {' <-> '.join(scc)} — "
                        f"potential deadlock ({detail})",
                symbol=sym))
        return findings

    # --------------------------------------------- CCY002: shared state
    def _thread_reachable(self, cls: _ClassRec) -> Set[str]:
        """Method names reachable from this class's thread-entry points
        (targets of Thread/Timer constructions anywhere in the class)."""
        entries: Set[str] = set()
        for fn in cls.methods.values():
            for _, name in fn.thread_targets:
                if name in cls.methods:
                    entries.add(name)
        frontier = list(entries)
        while frontier:
            cur = frontier.pop()
            fn = cls.methods.get(cur)
            if fn is None:
                continue
            for kind, name in fn.calls:
                if kind == "self" and name in cls.methods \
                        and name not in entries:
                    entries.add(name)
                    frontier.append(name)
        return entries

    def _check_shared_state(self, mod: _ModRec,
                            cls: _ClassRec) -> List[Finding]:
        thread_side = self._thread_reachable(cls)
        if not thread_side:
            return []
        findings: List[Finding] = []
        #: attr -> [(method, locks, lineno, sides)]
        writes: Dict[str, List[Tuple[_FnRec, FrozenSet[str], int, str]]] = {}
        for mname, fn in cls.methods.items():
            if mname in ("__init__", "__new__"):
                continue   # construction happens-before every thread
            in_thread = mname in thread_side
            is_public = not mname.startswith("_") or mname in _STOP_NAMES
            if not (in_thread or is_public):
                continue
            side = ("thread" if in_thread else "") + \
                   ("+public" if is_public else "")
            for attr, locks, lineno in fn.attr_writes:
                if attr in cls.lock_attrs or attr in cls.cond_attrs or \
                        attr.endswith(_CCY002_EXEMPT_SUFFIXES):
                    continue
                writes.setdefault(attr, []).append((fn, locks, lineno, side))
        for attr, rows in sorted(writes.items()):
            t_rows = [r for r in rows if "thread" in r[3]]
            p_rows = [r for r in rows if "public" in r[3]]
            if not t_rows or not p_rows:
                continue
            hit = None
            for tfn, tlocks, tline, _ in t_rows:
                for pfn, plocks, pline, _ in p_rows:
                    if not (tlocks & plocks):
                        hit = (tfn, tlocks, tline, pfn, plocks, pline)
                        break
                if hit:
                    break
            if hit is None:
                continue
            tfn, tlocks, tline, pfn, plocks, pline = hit
            def _fmt(locks: FrozenSet[str]) -> str:
                return "{" + ", ".join(sorted(locks)) + "}" if locks \
                    else "no lock"
            findings.append(Finding(
                rule="CCY002", file=mod.relpath, line=pline,
                message=f"attribute '{attr}' mutated on a thread path "
                        f"({tfn.qualname}:{tline} under {_fmt(tlocks)}) "
                        f"and a public path ({pfn.qualname}:{pline} under "
                        f"{_fmt(plocks)}) with no common lock — data race",
                symbol=pfn.qualname))
        return findings

    # ---------------------------------------------- CCY003: conditions
    def _check_conditions(self, mod: _ModRec,
                          cls: _ClassRec) -> List[Finding]:
        findings: List[Finding] = []
        for fn in cls.methods.values():
            for cond, in_while, lineno in fn.waits:
                if not in_while:
                    findings.append(Finding(
                        rule="CCY003", file=mod.relpath, line=lineno,
                        message=f"{cond}.wait() outside a predicate loop "
                                "— a spurious wakeup or stolen predicate "
                                "proceeds on stale state (use `while not "
                                "pred: cond.wait()` or wait_for)",
                        symbol=fn.qualname))
            for cond, held, lineno in fn.notifies:
                if cond not in held:
                    findings.append(Finding(
                        rule="CCY003", file=mod.relpath, line=lineno,
                        message=f"{cond}.notify() without the condition's "
                                "lock held — the waiter can miss the "
                                "wakeup racing the predicate write",
                        symbol=fn.qualname))
        return findings

    # --------------------------------------------- CCY004: thread leaks
    def _stop_reachable(self, cls: _ClassRec) -> Set[str]:
        entries = {m for m in cls.methods if m in _STOP_NAMES}
        frontier = list(entries)
        while frontier:
            cur = frontier.pop()
            fn = cls.methods.get(cur)
            if fn is None:
                continue
            for kind, name in fn.calls:
                if kind == "self" and name in cls.methods \
                        and name not in entries:
                    entries.add(name)
                    frontier.append(name)
        return entries

    def _check_thread_leaks(self, mod: _ModRec,
                            resolver: "_Resolver") -> List[Finding]:
        findings: List[Finding] = []
        for cls in mod.classes.values():
            stop_side = self._stop_reachable(cls)
            # class-wide join/cancel inventory on self attributes
            attr_joined: Set[str] = set()
            attr_cancelled: Set[str] = set()
            for mname in stop_side:
                fn = cls.methods[mname]
                for handle, bounded, _ in fn.joins:
                    if handle.startswith("self.") and bounded:
                        attr_joined.add(handle[5:])
                attr_cancelled |= {h[5:] for h in fn.cancels
                                   if h.startswith("self.")}
            for fn in cls.methods.values():
                findings.extend(self._leaks_in_fn(
                    mod, fn, attr_joined, attr_cancelled))
        for fn in mod.functions.values():
            findings.extend(self._leaks_in_fn(mod, fn, set(), set()))
        return findings

    def _leaks_in_fn(self, mod: _ModRec, fn: _FnRec,
                     attr_joined: Set[str],
                     attr_cancelled: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        local_joined = {h for h, bounded, _ in fn.joins if bounded}
        for handle, kind, daemon, lineno in fn.thread_starts:
            ok = False
            if handle.startswith("self."):
                attr = handle[5:]
                ok = attr in attr_joined or \
                    (kind == "timer" and attr in attr_cancelled)
            elif handle:
                ok = handle in local_joined or \
                    (kind == "timer" and handle in fn.cancels)
                for a in fn.handle_aliases.get(handle, ()):
                    ok = ok or a in attr_joined or \
                        (kind == "timer" and a in attr_cancelled)
            if ok:
                continue
            what = "Timer" if kind == "timer" else "thread"
            where = f"{handle!r}" if handle else "anonymous handle"
            findings.append(Finding(
                rule="CCY004", file=mod.relpath, line=lineno,
                message=f"started {what} ({where}"
                        f"{', daemon' if daemon else ''}) with no bounded "
                        "join()/cancel() reachable from a close()/stop()/"
                        "drain() path — the thread outlives its owner "
                        "(invisible work during drain, shutdown "
                        "tracebacks, leaked sockets)",
                symbol=fn.qualname))
        return findings


# ---------------------------------------------------------------------------
# per-function AST walk
# ---------------------------------------------------------------------------

class _FnWalker:
    """Recursive walk of one function body tracking the lexical held-lock
    stack, while-loop depth, and local thread handles."""

    def __init__(self, ctx: ModuleContext, mod: _ModRec,
                 cls: Optional[_ClassRec], fn: _FnRec):
        self.ctx = ctx
        self.mod = mod
        self.cls = cls
        self.fn = fn
        self.held: List[str] = []
        self.while_depth = 0
        #: local name -> "thread"|"timer" for Thread()/Timer() assignments
        self.local_threads: Dict[str, str] = {}

    # -------------------------------------------------------------- utils
    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        return _LockName.resolve(expr, self.cls, self.mod.tag)

    def _thread_ctor_kind(self, call: ast.Call) -> Optional[str]:
        dotted = self.ctx.dotted_name(call.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        if dotted in _THREAD_CTORS or leaf == "Thread":
            return "thread"
        if dotted in _TIMER_CTORS or leaf == "Timer":
            return "timer"
        return None

    def _note_thread_target(self, call: ast.Call, kind: str) -> None:
        cand: List[ast.AST] = []
        for kw in call.keywords:
            if kw.arg in ("target", "function"):
                cand.append(kw.value)
        if kind == "timer" and len(call.args) >= 2:
            cand.append(call.args[1])
        for expr in cand:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id in ("self", "cls"):
                self.fn.thread_targets.append((kind, expr.attr))
            elif isinstance(expr, ast.Name):
                self.fn.thread_targets.append((kind, expr.id))

    @staticmethod
    def _handle_of(expr: ast.AST) -> str:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return f"self.{expr.attr}"
        if isinstance(expr, ast.Name):
            return expr.id
        return ""

    # --------------------------------------------------------------- walk
    def walk_body(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested defs (thread bodies defined inline, callbacks): the
            # lexical lock stack does not cross the boundary — the nested
            # function runs later, possibly on another thread — but its
            # calls/acquires still belong to this record (the nested fn is
            # only reachable through us)
            saved_held, self.held = self.held, []
            saved_while, self.while_depth = self.while_depth, 0
            for child in ast.iter_child_nodes(node):
                self._walk(child)
            self.held, self.while_depth = saved_held, saved_while
            return
        if isinstance(node, ast.With):
            self._walk_with(node)
            return
        if isinstance(node, (ast.While, ast.For)):
            # a for-loop re-checks its iterator like a while re-checks its
            # predicate: both satisfy the wait-in-a-loop discipline
            if isinstance(node, ast.For):
                self._note_for_alias(node)
            self.while_depth += 1
            for child in ast.iter_child_nodes(node):
                self._walk(child)
            self.while_depth -= 1
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            self._note_assign(node)
        if isinstance(node, ast.Call):
            self._note_call(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _walk_with(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            name = self._lock_name(item.context_expr)
            if name is not None:
                for h in self.held:
                    if h != name:
                        self.fn.edges.append((h, name, node.lineno))
                self.fn.acquires.append((name, node.lineno))
                acquired.append(name)
            # the context expression itself may contain calls
            self._walk(item.context_expr)
            if item.optional_vars is not None:
                self._walk(item.optional_vars)
        self.held.extend(acquired)
        try:
            for stmt in node.body:
                self._walk(stmt)
        finally:
            for _ in acquired:
                self.held.pop()

    @staticmethod
    def _self_attr_of(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return expr.attr
        return None

    def _alias(self, local: str, attr: str) -> None:
        self.fn.handle_aliases.setdefault(local, set()).add(attr)

    def _note_alias_pair(self, tgt: ast.AST, value: ast.AST) -> None:
        """One (target, value) assignment pair, possibly inside a tuple
        unpack: ``t = self._thread`` and ``self._thread = t`` both tie
        the local to the attribute (the idiomatic hand-off in every
        stop(): ``thread, self._thread = self._thread, None``)."""
        attr = self._self_attr_of(value)
        if attr is not None and isinstance(tgt, ast.Name):
            self._alias(tgt.id, attr)
            return
        attr = self._self_attr_of(tgt)
        if attr is not None and isinstance(value, ast.Name) and \
                value.id in self.local_threads:
            self._alias(value.id, attr)
            self.local_threads[f"self.{attr}"] = \
                self.local_threads[value.id]

    def _note_for_alias(self, node: ast.For) -> None:
        """``for t in (self._a, self._b):`` / ``for t in self._threads:``
        — joins on the loop variable credit every attribute iterated."""
        if not isinstance(node.target, ast.Name):
            return
        items = node.iter.elts \
            if isinstance(node.iter, (ast.Tuple, ast.List)) else [node.iter]
        for item in items:
            attr = self._self_attr_of(item)
            if attr is not None:
                self._alias(node.target.id, attr)

    def _note_assign(self, node) -> None:
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        value = node.value
        # thread handle bookkeeping: self.X = Thread(...) / t = Thread(...)
        if isinstance(value, ast.Call):
            kind = self._thread_ctor_kind(value)
            if kind is not None:
                self._note_thread_target(value, kind)
                for tgt in targets:
                    handle = self._handle_of(tgt)
                    if handle and not handle.startswith("self."):
                        self.local_threads[handle] = kind
                    if handle.startswith("self.") and self.cls is not None:
                        # started separately via self.X.start()
                        self.local_threads[handle] = kind
        # alias bookkeeping, tuple unpack included
        for tgt in targets:
            if isinstance(tgt, ast.Tuple) and \
                    isinstance(value, ast.Tuple) and \
                    len(tgt.elts) == len(value.elts):
                for t_el, v_el in zip(tgt.elts, value.elts):
                    self._note_alias_pair(t_el, v_el)
            else:
                self._note_alias_pair(tgt, value)
        for tgt in targets:
            elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for el in elts:
                attr = self._self_attr_of(el)
                if attr is not None:
                    self.fn.attr_writes.append(
                        (attr, frozenset(self.held), node.lineno))

    def _note_call(self, node: ast.Call) -> None:
        ctx = self.ctx
        func = node.func
        kind = self._thread_ctor_kind(node)
        if kind is not None:
            self._note_thread_target(node, kind)
            # anonymous Thread(...).start() has no handle to join
        dotted = ctx.dotted_name(func)
        if isinstance(func, ast.Attribute):
            attr = func.attr
            owner = func.value
            handle = self._handle_of(owner)
            if attr == "start":
                started = None
                if isinstance(owner, ast.Call):
                    k = self._thread_ctor_kind(owner)
                    if k is not None:
                        started = ("", k)
                elif handle and handle in self.local_threads:
                    started = (handle, self.local_threads[handle])
                elif handle.startswith("self.") and self.cls is not None:
                    # self.X.start(): treat as a thread start when some
                    # method assigned Thread()/Timer() to self.X
                    k = self._self_attr_thread_kind(handle[5:])
                    if k is not None:
                        started = (handle, k)
                if started is not None:
                    daemon = self._daemon_of(owner)
                    self.fn.thread_starts.append(
                        (started[0], started[1], daemon, node.lineno))
            elif attr == "join":
                bounded = bool(node.args) or \
                    any(kw.arg == "timeout" for kw in node.keywords)
                if handle:
                    self.fn.joins.append((handle, bounded, node.lineno))
                    for a in self.fn.handle_aliases.get(handle, ()):
                        self.fn.joins.append(
                            (f"self.{a}", bounded, node.lineno))
            elif attr == "cancel" and handle:
                self.fn.cancels.add(handle)
                self.fn.cancels |= {f"self.{a}" for a in
                                    self.fn.handle_aliases.get(handle, ())}
            elif attr == "append" and len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in self.local_threads:
                coll = self._self_attr_of(owner)
                if coll is not None:
                    # self._threads.append(t): joins iterated over the
                    # collection later credit this local's start
                    self._alias(node.args[0].id, coll)
            elif attr == "wait":
                cond = self._cond_of(owner)
                if cond is not None:
                    self.fn.waits.append(
                        (cond, self.while_depth > 0, node.lineno))
            elif attr in ("notify", "notify_all"):
                cond = self._cond_of(owner)
                if cond is not None:
                    self.fn.notifies.append(
                        (cond, tuple(self.held), node.lineno))
            elif attr == "acquire":
                name = self._lock_name(owner)
                if name is not None:
                    for h in self.held:
                        if h != name:
                            self.fn.edges.append((h, name, node.lineno))
                    self.fn.acquires.append((name, node.lineno))
            # call-graph edges
            if isinstance(owner, ast.Name) and owner.id in ("self", "cls"):
                self.fn.calls.add(("self", attr))
                if self.held:
                    self.fn.held_calls.append(
                        (("self", attr), tuple(self.held), node.lineno))
            elif dotted and "." in dotted:
                self.fn.ext_calls.add(dotted)
                self.fn.calls.add(("dotted", dotted))
                if self.held:
                    self.fn.held_calls.append(
                        (("dotted", dotted), tuple(self.held), node.lineno))
        elif isinstance(func, ast.Name):
            target = ctx.imports.get(func.id, func.id)
            if target != func.id and "." in target:
                self.fn.calls.add(("dotted", target))
                if self.held:
                    self.fn.held_calls.append(
                        (("dotted", target), tuple(self.held), node.lineno))
            else:
                self.fn.calls.add(("local", func.id))
                if self.held:
                    self.fn.held_calls.append(
                        (("local", func.id), tuple(self.held), node.lineno))

    def _self_attr_thread_kind(self, attr: str) -> Optional[str]:
        if self.cls is None:
            return None
        for m in self.cls.methods.values():
            for handle, kind, _, _ in m.thread_starts:
                if handle == f"self.{attr}":
                    return kind
        # assignment may not have been walked yet: look for the ctor
        # assignment pattern in the raw local_threads of this walker
        return self.local_threads.get(f"self.{attr}")

    @staticmethod
    def _daemon_of(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            for kw in expr.keywords:
                if kw.arg == "daemon" and \
                        isinstance(kw.value, ast.Constant):
                    return bool(kw.value.value)
        return False

    def _cond_of(self, owner: ast.AST) -> Optional[str]:
        """Resolve ``X`` of ``X.wait()``/``X.notify()`` to a condition
        node name, only when X is statically known to be a Condition —
        Event.wait lookalikes must not fire."""
        if isinstance(owner, ast.Attribute) and \
                isinstance(owner.value, ast.Name) and \
                owner.value.id in ("self", "cls") and self.cls is not None:
            if owner.attr in self.cls.cond_attrs:
                return f"{self.cls.name}.{owner.attr}"
        if isinstance(owner, ast.Name) and self.cls is None:
            # module-level condition object
            return None
        return None


# ---------------------------------------------------------------------------
# cross-module resolution
# ---------------------------------------------------------------------------

class _Resolver:
    """Name resolution over every module's records: call targets (like the
    TRC BFS: self methods, module-local names, import-table dotted paths)
    and deferred ``?.attr`` lock owners (unique-declaring-class rule)."""

    def __init__(self, mods: Dict[str, _ModRec]):
        self.mods = mods
        self.all_fns: Dict[int, Tuple[_ModRec, _FnRec]] = {}
        self._fn_key: Dict[Tuple[str, str], int] = {}
        #: lock attr -> {class names declaring it}
        self._lock_owners: Dict[str, Set[str]] = {}
        #: class name -> (relpath, _ClassRec); last definition wins
        self._classes: Dict[str, Tuple[str, _ClassRec]] = {}
        self._by_dotted = {self._module_dotted(rel): rel for rel in mods}
        k = 0
        for rel, mod in mods.items():
            for fname, fn in mod.functions.items():
                self.all_fns[k] = (mod, fn)
                self._fn_key[(rel, fn.qualname)] = k
                k += 1
            for cname, cls in mod.classes.items():
                self._classes[cname] = (rel, cls)
                for attr in cls.lock_attrs | cls.cond_attrs:
                    self._lock_owners.setdefault(attr, set()).add(cname)
                for mname, fn in cls.methods.items():
                    self.all_fns[k] = (mod, fn)
                    self._fn_key[(rel, fn.qualname)] = k
                    k += 1

    @staticmethod
    def _module_dotted(relpath: str) -> str:
        path = relpath[:-3] if relpath.endswith(".py") else relpath
        if path.endswith("/__init__"):
            path = path[: -len("/__init__")]
        return path.replace("/", ".")

    def disambiguate_lock(self, name: str) -> Optional[str]:
        """``?.attr`` resolves to ``Cls.attr`` when exactly ONE scanned
        class declares a lock/cond attribute of that name; ambiguous or
        unknown owners are dropped (a merged node would invent edges
        between unrelated locks — false cycles)."""
        if not name.startswith("?."):
            return name
        attr = name[2:]
        owners = self._lock_owners.get(attr, set())
        if len(owners) == 1:
            return f"{next(iter(owners))}.{attr}"
        return None

    def resolve_call(self, mod: _ModRec, fn: _FnRec,
                     callee: Tuple[str, str]) -> Optional[int]:
        kind, name = callee
        if kind == "self" and fn.cls is not None:
            cls = mod.classes.get(fn.cls)
            hit = self._method_in_class(mod.relpath, cls, name)
            if hit is not None:
                return hit
            return None
        if kind == "local":
            if name in mod.functions:
                return self._fn_key.get((mod.relpath, name))
            dotted = mod.imports.get(name)
            if dotted and dotted != name:
                return self._resolve_dotted(dotted)
            return None
        if kind == "dotted":
            return self._resolve_dotted(name)
        return None

    def _method_in_class(self, relpath: str, cls: Optional[_ClassRec],
                         name: str) -> Optional[int]:
        seen: Set[str] = set()
        while cls is not None and cls.name not in seen:
            seen.add(cls.name)
            if name in cls.methods:
                return self._fn_key.get((relpath, f"{cls.name}.{name}"))
            # single static base resolution (bases by short name)
            nxt = None
            for b in cls.bases:
                hit = self._classes.get(b)
                if hit is not None:
                    relpath, nxt = hit
                    break
            cls = nxt
        return None

    def _resolve_dotted(self, dotted: str) -> Optional[int]:
        mod_path, _, leaf = dotted.rpartition(".")
        if not mod_path:
            return None
        for scanned, rel in self._by_dotted.items():
            if scanned == mod_path or scanned.endswith("." + mod_path):
                if leaf in self.mods[rel].functions:
                    return self._fn_key.get((rel, leaf))
        return None


def _sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Non-trivial strongly connected components, each sorted — one
    CCY001 finding per cycle however many rotations it has."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    out: List[List[str]] = []
    nodes = set(graph)
    for vs in graph.values():
        nodes |= vs
    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
    return out
