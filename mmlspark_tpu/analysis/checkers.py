"""graft-lint checkers: TRC tracer safety, RES resilience coverage,
LCK lock discipline, HOT hot-path hygiene.

Each rule encodes an invariant this repo has actually shipped a fix for —
see ``docs/STATIC_ANALYSIS.md`` for the catalog with the review history
behind every rule.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .engine import Checker, Finding, ModuleContext, with_lock_items

__all__ = ["TracerSafetyChecker", "ResilienceCoverageChecker",
           "UndeadlinedRetryChecker", "CheckpointAtomicityChecker",
           "LockDisciplineChecker", "HotPathChecker",
           "TransferDisciplineChecker", "UnboundedBlockingChecker"]


# ---------------------------------------------------------------------------
# TRC — tracer safety
# ---------------------------------------------------------------------------

#: transforms whose function argument is traced by XLA: a host call inside
#: silently becomes either a compile-time constant (wrong results) or a
#: forced host sync/recompile (the latency cliff the north-star forbids)
_TRACING_ENTRY_POINTS = {
    "jax.jit", "jit", "jax.pmap", "pmap", "jax.vmap", "vmap",
    "jax.shard_map", "shard_map", "jax.experimental.shard_map.shard_map",
    "jax.grad", "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.lax.map", "lax.map",
    # observability/compute.py's jax.jit drop-in: sites routed through it
    # (the compute-plane telemetry contract) keep their TRC coverage —
    # every from-import depth of the canonical path resolves here
    "instrumented_jit", "compute.instrumented_jit",
    "observability.compute.instrumented_jit",
    "mmlspark_tpu.observability.compute.instrumented_jit",
    # Pallas kernel bodies are traced exactly like jitted functions — a
    # host clock/RNG/print inside one either constant-folds or breaks the
    # Mosaic lowering outright (ISSUE 8: ops/pallas_histogram.py kernels)
    "pallas_call", "pl.pallas_call", "pallas.pallas_call",
    "jax.experimental.pallas.pallas_call",
}

#: host-side calls that must never run under a tracer
_TRC_BANNED_PREFIXES = {
    "time.time": "reads the host clock (traced to a constant)",
    "time.monotonic": "reads the host clock (traced to a constant)",
    "time.perf_counter": "reads the host clock (traced to a constant)",
    "datetime.datetime.now": "reads the host clock (traced to a constant)",
    "numpy.random": "host RNG (traced to a constant; use jax.random)",
    "uuid": "host entropy (traced to a constant)",
    "os.urandom": "host entropy syscall (forces a host sync)",
    "random.random": "host RNG (traced to a constant)",
    "random.randint": "host RNG (traced to a constant)",
    "threading.Lock": "host lock under a tracer",
    "threading.RLock": "host lock under a tracer",
}


def _dotted_prefix_hit(dotted: str, table: Dict[str, str]) -> Optional[Tuple[str, str]]:
    for prefix, why in table.items():
        if dotted == prefix or dotted.startswith(prefix + "."):
            return prefix, why
    return None


class _FnInfo:
    __slots__ = ("node", "qualname", "calls", "ext_calls", "banned",
                 "param_names")

    def __init__(self, node: ast.AST, qualname: str):
        self.node = node
        self.qualname = qualname
        #: local names this function calls (intra-module edges)
        self.calls: Set[str] = set()
        #: dotted call targets resolved through the import table — the
        #: cross-module edge candidates (``transformer.decode_step``)
        self.ext_calls: Set[str] = set()
        #: (node, message) banned sites found inside this function
        self.banned: List[Tuple[ast.AST, str, str]] = []
        args = node.args
        self.param_names = {a.arg for a in
                            args.posonlyargs + args.args + args.kwonlyargs}


class _ModRecord:
    """One scanned module's TRC state, held until the cross-module pass."""

    __slots__ = ("functions", "roots", "ext_roots", "imports")

    def __init__(self, functions, roots, ext_roots, imports):
        self.functions: Dict[str, _FnInfo] = functions
        self.roots: Set[str] = roots
        self.ext_roots: Set[str] = ext_roots
        self.imports: Dict[str, str] = imports


def _module_dotted(relpath: str) -> str:
    """``mmlspark_tpu/models/transformer.py`` -> the dotted module path the
    import table speaks (``__init__.py`` collapses to its package)."""
    path = relpath[:-3] if relpath.endswith(".py") else relpath
    if path.endswith("/__init__"):
        path = path[: -len("/__init__")]
    return path.replace("/", ".")


class TracerSafetyChecker(Checker):
    """TRC — functions reachable from jit/shard_map/pmap/scan call sites
    must stay traceable: no host clocks/RNG/entropy, no print, no locks,
    no ``.item()``/``float()`` host syncs on array arguments.

    Reachability is CROSS-MODULE over the scanned scope (ISSUE 9 carried
    follow-up; it was module-local through PR 8): roots are functions
    decorated with (or passed to) a tracing entry point — including
    imported functions, resolved through each module's import table — and
    edges are calls by name, local or through an import.  An apply fn
    defined in ``models/transformer.py`` and jitted by
    ``models/runner.py`` is swept exactly like a locally-jitted one.
    """

    rules = {
        "TRC001": "host clock/RNG/entropy call inside traced code",
        "TRC002": "print() inside traced code",
        "TRC003": "lock acquisition inside traced code",
        "TRC004": "host sync (.item()/float()/int() on a traced arg) "
                  "inside traced code",
    }

    SCOPE_DIRS = ("parallel/", "ops/", "models/", "lightgbm/")

    def __init__(self):
        #: relpath -> _ModRecord, consumed by the finalize cross-module BFS
        self._records: Dict[str, _ModRecord] = {}

    def interested(self, relpath: str) -> bool:
        return any(f"/{d}" in f"/{relpath}" for d in self.SCOPE_DIRS)

    def begin_module(self, ctx: ModuleContext) -> None:
        ctx._trc_functions: Dict[str, _FnInfo] = {}
        ctx._trc_roots: Set[str] = set()
        ctx._trc_ext_roots: Set[str] = set()
        ctx._trc_stack: List[_FnInfo] = []

    # ------------------------------------------------------------- helpers
    def _is_tracing_call(self, node: ast.Call, ctx: ModuleContext) -> bool:
        dotted = ctx.dotted_name(node.func)
        if dotted in _TRACING_ENTRY_POINTS:
            return True
        # functools.partial(jax.jit, ...) used as a decorator factory
        if dotted in ("functools.partial", "partial") and node.args:
            inner = ctx.dotted_name(node.args[0])
            return inner in _TRACING_ENTRY_POINTS
        return False

    def _mark_function_args(self, node: ast.Call, ctx: ModuleContext) -> None:
        """Names passed into a tracing entry point become roots — local
        short names AND, when the name resolves through the import table,
        the dotted target in its defining module (cross-module roots)."""
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                ctx._trc_roots.add(arg.id)
                dotted = ctx.imports.get(arg.id)
                if dotted and dotted != arg.id:
                    ctx._trc_ext_roots.add(dotted)
            elif isinstance(arg, ast.Attribute):
                # self._step / cls.step — root by attribute name; an
                # imported-module attribute (transformer.decode_step) also
                # roots the target module's function
                ctx._trc_roots.add(arg.attr)
                dotted = ctx.dotted_name(arg)
                if dotted and "." in dotted:
                    ctx._trc_ext_roots.add(dotted)
            elif isinstance(arg, ast.Call) and ctx.dotted_name(arg.func) in \
                    ("functools.partial", "partial"):
                # pallas_call(partial(_kernel, cfg), ...) — the partial's
                # function argument is what gets traced
                self._mark_function_args(arg, ctx)

    # ------------------------------------------------------------- events
    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = (ctx.scope_qualname() + "." if ctx.scope_stack else "") \
                + node.name
            info = _FnInfo(node, qn)
            # last short-name definition wins; module-local resolution
            ctx._trc_functions[node.name] = info
            for dec in node.decorator_list:
                dec_target = dec.func if isinstance(dec, ast.Call) else dec
                dotted = ctx.dotted_name(dec_target)
                if dotted in _TRACING_ENTRY_POINTS:
                    ctx._trc_roots.add(node.name)
                elif isinstance(dec, ast.Call) and \
                        self._is_tracing_call(dec, ctx):
                    ctx._trc_roots.add(node.name)
            return
        if isinstance(node, ast.Call) and self._is_tracing_call(node, ctx):
            # jax.jit(f) / lax.scan(step, ...) at ANY scope roots its
            # function arguments, including module-level `step = jit(fn)`
            self._mark_function_args(node, ctx)
            return
        fn = self._enclosing(ctx)
        if fn is None or not isinstance(node, (ast.Call, ast.With)):
            return
        if isinstance(node, ast.With):
            if with_lock_items(node):
                fn.banned.append((node, "TRC003",
                                  "lock held inside traced code"))
            return
        dotted = ctx.dotted_name(node.func)
        if dotted is not None:
            hit = _dotted_prefix_hit(dotted, _TRC_BANNED_PREFIXES)
            if hit is not None:
                fn.banned.append((node, "TRC001",
                                  f"{dotted}() — {hit[1]}"))
                return
            if dotted == "print":
                fn.banned.append((node, "TRC002",
                                  "print() forces a host sync under jit"))
                return
            if dotted in ("float", "int", "bool") and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in fn.param_names:
                fn.banned.append((
                    node, "TRC004",
                    f"{dotted}({node.args[0].id}) concretizes a traced "
                    "argument (host sync / ConcretizationTypeError)"))
                return
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args:
                fn.banned.append((node, "TRC004",
                                  ".item() forces a device->host sync"))
            elif node.func.attr == "acquire":
                fn.banned.append((node, "TRC003",
                                  "lock.acquire() inside traced code"))
            elif isinstance(node.func.value, ast.Name):
                fn.calls.add(node.func.attr)  # self.method / mod.func edge
                if dotted and "." in dotted:
                    fn.ext_calls.add(dotted)  # imported-module call edge
        elif isinstance(node.func, ast.Name):
            fn.calls.add(node.func.id)
            imported = ctx.imports.get(node.func.id)
            if imported and imported != node.func.id:
                fn.ext_calls.add(imported)  # from-imported call edge

    def _enclosing(self, ctx: ModuleContext) -> Optional[_FnInfo]:
        fnode = ctx.enclosing_function()
        if fnode is None:
            return None
        for info in ctx._trc_functions.values():
            if info.node is fnode:
                return info
        return None

    def end_module(self, ctx: ModuleContext) -> None:
        # emission moves to finalize: the reachability walk is global, so a
        # module's verdict isn't known until every module has been parsed
        self._records[ctx.relpath] = _ModRecord(
            ctx._trc_functions, ctx._trc_roots, ctx._trc_ext_roots,
            dict(ctx.imports))

    # --------------------------------------------------- cross-module pass
    def _resolve(self, dotted: str, by_dotted: Dict[str, str]
                 ) -> Optional[Tuple[str, str]]:
        """``models.transformer.decode_step`` -> (relpath, fn name) when the
        defining module is in the scanned set.  Relative imports drop their
        leading package segments, so modules match by dotted-path suffix."""
        mod_path, _, leaf = dotted.rpartition(".")
        if not mod_path:
            return None
        for scanned, relpath in by_dotted.items():
            if scanned == mod_path or scanned.endswith("." + mod_path):
                if leaf in self._records[relpath].functions:
                    return relpath, leaf
        return None

    def finalize(self, engine) -> List[Finding]:
        by_dotted = {_module_dotted(rel): rel for rel in self._records}
        # roots: locally rooted names + imported names rooted elsewhere
        frontier: List[Tuple[str, str]] = []
        for rel, rec in self._records.items():
            frontier.extend((rel, r) for r in rec.roots
                            if r in rec.functions)
            for dotted in rec.ext_roots:
                target = self._resolve(dotted, by_dotted)
                if target is not None:
                    frontier.append(target)
        # BFS over local short-name edges + import-resolved edges
        traced: Set[Tuple[str, str]] = set()
        while frontier:
            node = frontier.pop()
            if node in traced:
                continue
            traced.add(node)
            rel, name = node
            rec = self._records[rel]
            info = rec.functions[name]
            for callee in info.calls:
                if callee in rec.functions:
                    frontier.append((rel, callee))
                else:
                    # a from-imported short name: resolve via the table
                    dotted = rec.imports.get(callee)
                    if dotted and dotted != callee:
                        target = self._resolve(dotted, by_dotted)
                        if target is not None:
                            frontier.append(target)
            for dotted in info.ext_calls:
                target = self._resolve(dotted, by_dotted)
                if target is not None:
                    frontier.append(target)
        findings: List[Finding] = []
        for rel, name in sorted(traced):
            info = self._records[rel].functions[name]
            for node, rule, message in info.banned:
                findings.append(Finding(
                    rule=rule, file=rel, line=node.lineno,
                    message=message, symbol=info.qualname))
        return findings


# ---------------------------------------------------------------------------
# RES — resilience coverage
# ---------------------------------------------------------------------------

_RES_BANNED = {
    "urllib.request.urlopen": "raw urlopen bypasses breaker + deadline "
                              "clipping (route through io/http.py clients)",
    "urllib.request.Request": "raw urllib request construction outside the "
                              "resilient clients",
    "urllib.request.build_opener": "raw urllib opener outside the resilient "
                                   "clients",
    "http.client.HTTPConnection": "raw http.client bypasses the resilient "
                                  "clients",
    "http.client.HTTPSConnection": "raw http.client bypasses the resilient "
                                   "clients",
    "requests.get": "requests bypasses breaker + deadline clipping",
    "requests.post": "requests bypasses breaker + deadline clipping",
    "requests.put": "requests bypasses breaker + deadline clipping",
    "requests.delete": "requests bypasses breaker + deadline clipping",
    "requests.request": "requests bypasses breaker + deadline clipping",
    "requests.Session": "requests bypasses breaker + deadline clipping",
    "socket.socket": "raw socket outside the resilient clients",
    "socket.create_connection": "raw socket connection outside the "
                                "resilient clients",
}


class ResilienceCoverageChecker(Checker):
    """RES — every remote call outside ``io/http.py`` and ``serving/``
    internals must route through the breaker/deadline-aware clients
    (PR 1's contract; raw urllib has no budget and no circuit)."""

    rules = {"RES001": "raw urllib/requests/socket outside the resilient "
                       "HTTP clients"}

    #: modules allowed to touch raw transports: the resilient clients
    #: themselves and the serving internals that ARE the server side
    ALLOWED = ("io/http.py", "serving/", "testing/chaos.py")

    def interested(self, relpath: str) -> bool:
        norm = f"/{relpath}"
        return not any(f"/{a}" in norm or norm.endswith(f"/{a}")
                       for a in (f"mmlspark_tpu/{p}" for p in self.ALLOWED))

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.Call):
            return
        dotted = ctx.dotted_name(node.func)
        if dotted is None:
            return
        hit = _dotted_prefix_hit(dotted, _RES_BANNED)
        if hit is not None:
            ctx.report("RES001", node, f"{dotted}() — {hit[1]}")


#: retry helpers whose backoff loops are unbounded without a budget
_RETRY_HELPERS = {"with_retries", "retry_with_timeout"}

#: with-items that install an ambient Deadline for their block
_DEADLINE_SCOPES = {"deadline_scope"}


class UndeadlinedRetryChecker(Checker):
    """RES002 — a ``with_retries``/``retry_with_timeout`` call site with no
    deadline in scope retries on its own configured schedule, unbounded by
    any caller budget (PR 1's contract: budgets clip every retry loop).
    Statically visible evidence of a budget, any one of which passes:

    - an explicit ``deadline=`` argument;
    - the call sits lexically inside ``with deadline_scope(...)`` or
      ``with trace_span(..., deadline_s=...)``;
    - the enclosing function declares a ``deadline`` parameter (it is the
      documented convention for threading an explicit budget through).

    A site whose budget is installed by a *caller* (runtime-ambient, not
    lexically visible) is a known false positive — pragma it with the
    reason, or baseline it, exactly like RES001 local-socket sites.
    """

    rules = {"RES002": "with_retries/retry_with_timeout call site with no "
                       "ambient Deadline/deadline_scope in scope"}

    #: the primitives' own modules (definitions + facade) are exempt
    EXCLUDED = ("utils/resilience.py", "utils/fault.py", "testing/")

    def interested(self, relpath: str) -> bool:
        norm = f"/{relpath}"
        return not any(f"/mmlspark_tpu/{e}" in norm for e in self.EXCLUDED)

    # The engine walk has no scope-exit hook, so ambient-deadline depth is
    # tracked by a private recursive pass over the module tree instead.
    def end_module(self, ctx: ModuleContext) -> None:
        self._walk(ctx.tree, ctx, depth=0, fn_stack=[])

    def _installs_deadline(self, node: ast.With, ctx: ModuleContext) -> bool:
        for item in node.items:
            expr = item.context_expr
            if not isinstance(expr, ast.Call):
                continue
            dotted = ctx.dotted_name(expr.func) or ""
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf in _DEADLINE_SCOPES:
                return True
            if leaf == "trace_span" and any(kw.arg == "deadline_s"
                                            for kw in expr.keywords):
                return True
        return False

    def _walk(self, node: ast.AST, ctx: ModuleContext, depth: int,
              fn_stack: List[ast.AST]) -> None:
        if isinstance(node, ast.With) and self._installs_deadline(node, ctx):
            depth += 1
        is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))
        if is_fn:
            fn_stack = fn_stack + [node]
            # a def/lambda under a deadline_scope block runs LATER, when
            # the scope is gone — the lexical With above it is no budget
            # for the body, so the depth resets at the function boundary
            depth = 0
        if isinstance(node, ast.Call):
            dotted = ctx.dotted_name(node.func) or ""
            if dotted.rsplit(".", 1)[-1] in _RETRY_HELPERS and depth == 0 \
                    and not any(kw.arg == "deadline" for kw in node.keywords) \
                    and not self._fn_threads_deadline(fn_stack):
                ctx._findings.append(Finding(
                    rule="RES002", file=ctx.relpath, line=node.lineno,
                    message=f"{dotted.rsplit('.', 1)[-1]}() without an "
                            "ambient deadline — retries/backoff are "
                            "unbounded by any caller budget (wrap in "
                            "deadline_scope or pass deadline=)",
                    symbol=".".join(getattr(f, "name", "<lambda>")
                                    for f in fn_stack)))
        for child in ast.iter_child_nodes(node):
            self._walk(child, ctx, depth, fn_stack)

    @staticmethod
    def _fn_threads_deadline(fn_stack: List[ast.AST]) -> bool:
        for fn in reversed(fn_stack):
            args = fn.args
            if any(a.arg == "deadline" for a in
                   args.posonlyargs + args.args + args.kwonlyargs):
                return True
        return False


#: open() modes that create/modify the target — the torn-write hazard
_WRITE_MODE_CHARS = set("wax+")


class CheckpointAtomicityChecker(Checker):
    """RES003 — a direct ``open(..., "w"/"wb"/"a"/...)`` write inside a
    checkpoint module bypasses the atomic temp-file + ``os.replace``
    publish contract (``io/checkpoint.atomic_write``): a crash mid-write
    tears the very snapshot the module exists to protect, and resume then
    has nothing valid to fall back to.  Route every checkpoint-path write
    through the atomic writer; reads are fine."""

    rules = {"RES003": "direct open(..., 'w'/'wb'/'a') write in a "
                       "checkpoint module — route through "
                       "io.checkpoint.atomic_write"}

    # io/checkpoint.py itself is scanned too (ISSUE 14): only the one
    # raw open INSIDE atomic_write is sanctioned, via its inline pragma —
    # a whole-file exclusion would let a new writer (e.g. a topology-
    # stanza sidecar) land unatomically in the very module that defines
    # the contract.  The flight recorder (ISSUE 15) is held to the same
    # contract: a postmortem dump racing the crash that triggered it must
    # publish whole or not at all, so its writes go through atomic_write
    # only.
    def interested(self, relpath: str) -> bool:
        name = relpath.rsplit("/", 1)[-1]
        return "checkpoint" in name or "flightrecorder" in name

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.Call):
            return
        dotted = ctx.dotted_name(node.func)
        if dotted not in ("open", "io.open", "builtins.open"):
            return
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is None:
            return  # default "r": reads are fine
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            if not (_WRITE_MODE_CHARS & set(mode.value)):
                return  # read-only mode
        # non-constant modes are flagged too: the checker cannot prove
        # they are read-only, and checkpoint writes must be provably atomic
        shown = repr(mode.value) if isinstance(mode, ast.Constant) \
            else "<dynamic>"
        ctx.report("RES003", node,
                   f"{dotted}(..., mode={shown}) — checkpoint writes must "
                   "publish via io.checkpoint.atomic_write (temp file + "
                   "os.replace)")


# ---------------------------------------------------------------------------
# CMP — compute-plane transfer discipline
# ---------------------------------------------------------------------------

class TransferDisciplineChecker(Checker):
    """CMP — every host->device placement must route through
    ``observability.compute.device_put`` so
    ``mmlspark_device_transfer_bytes_total{site}`` sees it.  The out-of-core
    streaming pipeline tunes tile sizes against those counters: a raw
    ``jax.device_put`` is a transfer that silently escapes the accounting,
    making the prefetch-overlap numbers lie exactly where they matter."""

    rules = {"CMP001": "raw jax.device_put outside observability/compute.py "
                       "(bypasses the per-site transfer counters)"}

    #: the instrumented wrapper itself is the one sanctioned call site
    ALLOWED = ("observability/compute.py",)

    def interested(self, relpath: str) -> bool:
        norm = f"/{relpath}"
        return not any(norm.endswith(f"/{a}") for a in self.ALLOWED)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.Call):
            return
        dotted = ctx.dotted_name(node.func)
        if dotted == "jax.device_put":
            ctx.report(
                "CMP001", node,
                "jax.device_put() — untracked host->device transfer; route "
                "through observability.compute.device_put(site=...) so the "
                "transfer counters (and the out-of-core overlap tuning "
                "built on them) stay truthful")


# ---------------------------------------------------------------------------
# LCK — lock discipline
# ---------------------------------------------------------------------------

_LCK_IO_CALLS = {
    "open": "file I/O under a lock",
    "print": "console I/O under a lock",
    "json.dumps": "serialization under a lock (PR 2: log_event now dumps "
                  "outside; check-then-serialize instead)",
    "json.dump": "serialization under a lock",
    "json.loads": "deserialization under a lock",
    "time.sleep": "sleeping under a lock",
    "urllib.request.urlopen": "network I/O under a lock",
    "socket.socket": "socket work under a lock",
    "subprocess.run": "subprocess under a lock",
}

_LCK_CALLBACK_NAME = re.compile(r"^(fn|cb|callback|listener|hook|prober|"
                                r"sampler)s?(_\w+)?$|^on_[a-z_]+$")


class LockDisciplineChecker(Checker):
    """LCK — nothing slow or re-entrant may run inside a ``with <lock>:``
    body in the observability layer or the resilience primitives: no I/O
    or serialization, no user-callback invocation (three PR 2 review fixes
    were exactly this shape: drain under the lock, notify outside), and no
    nested lock acquisition (ordering deadlocks)."""

    rules = {
        "LCK001": "I/O or serialization under a lock",
        "LCK002": "callback invocation under a lock",
        "LCK003": "nested lock acquisition",
    }

    SCOPE = ("observability/", "utils/resilience.py")

    def interested(self, relpath: str) -> bool:
        return any(f"/{s}" in f"/{relpath}" for s in self.SCOPE)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if isinstance(node, ast.With) and ctx.lock_depth > 0 and \
                with_lock_items(node):
            ctx.report("LCK003", node,
                       "nested lock acquisition (lock-ordering deadlock "
                       "risk — copy state out, release, then lock)")
            return
        if ctx.lock_depth == 0 or not isinstance(node, ast.Call):
            return
        dotted = ctx.dotted_name(node.func)
        if dotted is not None:
            hit = _dotted_prefix_hit(dotted, _LCK_IO_CALLS)
            if hit is not None:
                ctx.report("LCK001", node, f"{dotted}() — {hit[1]}")
                return
        if isinstance(node.func, ast.Name) and \
                _LCK_CALLBACK_NAME.match(node.func.id):
            ctx.report(
                "LCK002", node,
                f"callback {node.func.id}() invoked under a lock — drain "
                "the work list under the lock, call outside it")
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            ctx.report("LCK003", node,
                       "lock.acquire() while already holding a lock")


# ---------------------------------------------------------------------------
# HOT — hot-path hygiene
# ---------------------------------------------------------------------------

_HOT_BANNED = {
    "uuid.uuid4": "per-call os.urandom syscall (~40us) in the serialized "
                  "hot path — use a counter + process prefix "
                  "(observability/tracing.py pattern)",
    "uuid.uuid1": "uuid in the hot path — use a counter + process prefix",
    "os.urandom": "entropy syscall in the hot path — amortize at module "
                  "scope (one prefix per process)",
}

_HOT_LOG_CALL = re.compile(r"(^|\.)(log\w*|debug|info|warning|error|"
                           r"exception|critical)$")


class HotPathChecker(Checker):
    """HOT — the serving score path and span creation must stay syscall-
    and allocation-lean: PR 2 held serving overhead to ~10% only after
    hand-removing uuid4/os.urandom from the serialized section and making
    log serialization conditional.  Module-level use is exempt (that IS
    the amortization pattern)."""

    rules = {
        "HOT001": "uuid4/os.urandom inside a hot-path function",
        "HOT002": "f-string eagerly formatted into a logging call on the "
                  "hot path",
    }

    SCOPE = ("serving/", "observability/tracing.py")

    def interested(self, relpath: str) -> bool:
        return any(f"/{s}" in f"/{relpath}" for s in self.SCOPE)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.Call):
            return
        if ctx.enclosing_function() is None:
            return  # module-level amortization is the sanctioned pattern
        dotted = ctx.dotted_name(node.func)
        if dotted is not None:
            hit = _dotted_prefix_hit(dotted, _HOT_BANNED)
            if hit is not None:
                ctx.report("HOT001", node, f"{dotted}() — {hit[1]}")
                return
        name = dotted or (node.func.attr
                          if isinstance(node.func, ast.Attribute) else "")
        if name and _HOT_LOG_CALL.search(name):
            for arg in node.args:
                if isinstance(arg, ast.JoinedStr):
                    ctx.report(
                        "HOT002", node,
                        "f-string formatted before the logging call can "
                        "decide to drop it — pass structured fields and "
                        "format lazily (core/logging gates on listeners)")
                    return


# ---------------------------------------------------------------------------
# RES004 — unbounded blocking
# ---------------------------------------------------------------------------

#: blocking primitives whose zero-timeout form parks the calling thread
#: forever; the message names the canonical owner of each method
_RES_BLOCKING_ATTRS = {
    "join": "Thread.join",
    "get": "Queue.get",
    "wait": "Event.wait / Condition.wait",
}


class UnboundedBlockingChecker(Checker):
    """RES004 — ``Thread.join()`` / ``Queue.get()`` / ``Event.wait()``
    with no timeout inside the serving layer or the runner hot path is a
    latent hang: a hung device dispatch or a peer that accepts and never
    replies parks the thread forever — exactly the slow-failure class the
    dispatch watchdog exists for (ISSUE 16).  Pass a timeout (and handle
    expiry), or baseline the site with a justification for why it cannot
    hang (e.g. the waited-on event is set by a watchdog-guarded engine
    that resolves every handle on abort)."""

    rules = {
        "RES004": "unbounded blocking call (join/get/wait with no "
                  "timeout) on a serving/runner hot path",
    }

    SCOPE = ("serving/", "models/runner.py")

    def interested(self, relpath: str) -> bool:
        return any(f"/{s}" in f"/{relpath}" for s in self.SCOPE)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            return
        attr = node.func.attr
        owner = _RES_BLOCKING_ATTRS.get(attr)
        if owner is None:
            return
        # a positional arg is the timeout for all three primitives (and
        # excludes the str.join/dict.get false positives wholesale); a
        # `timeout=` keyword bounds the call explicitly
        if node.args or any(kw.arg == "timeout" for kw in node.keywords):
            return
        ctx.report(
            "RES004", node,
            f".{attr}() with no timeout ({owner} shape) — an unbounded "
            "block is a latent hang on this path: pass a timeout and "
            "handle expiry, or baseline the site with a justification")
