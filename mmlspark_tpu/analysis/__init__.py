"""graft-lint — repo-wide static analysis for the invariants this codebase
actually enforces in review: tracer safety under XLA (TRC), resilience
coverage at remote boundaries (RES), lock discipline in the telemetry layer
(LCK), hot-path hygiene in serving (HOT), and stage contracts mirroring the
fuzzing harness (STG).

Usage::

    python -m mmlspark_tpu.analysis                 # gate: 0 = clean
    python -m mmlspark_tpu.analysis --format json
    python -m mmlspark_tpu.analysis --update-baseline

See ``docs/STATIC_ANALYSIS.md`` for the rule catalog, the pragma/baseline
workflow, and how to add a checker.
"""
from .baseline import (BaselineEntry, load_baseline, save_baseline,
                       split_findings, update_baseline)
from .checkers import (CheckpointAtomicityChecker, HotPathChecker,
                       LockDisciplineChecker, ResilienceCoverageChecker,
                       TracerSafetyChecker, TransferDisciplineChecker,
                       UnboundedBlockingChecker, UndeadlinedRetryChecker)
from .cli import default_checkers, main, rule_catalog, run_analysis
from .concurrency import ConcurrencyChecker
from .engine import AnalysisEngine, Checker, Finding, iter_python_files
from .stagecheck import StageContractChecker

__all__ = [
    "AnalysisEngine", "BaselineEntry", "Checker", "CheckpointAtomicityChecker",
    "ConcurrencyChecker",
    "Finding", "HotPathChecker", "LockDisciplineChecker", "ResilienceCoverageChecker",
    "StageContractChecker", "TracerSafetyChecker",
    "TransferDisciplineChecker", "UnboundedBlockingChecker",
    "UndeadlinedRetryChecker",
    "default_checkers", "iter_python_files", "load_baseline", "main",
    "rule_catalog", "run_analysis", "save_baseline", "split_findings",
    "update_baseline",
]
