"""graft-lint CLI.

    python -m mmlspark_tpu.analysis [paths...] [--format text|json]
                                    [--update-baseline] [--baseline FILE]
                                    [--rules TRC001,RES001,...] [--no-baseline]
                                    [--changed-only]

Exit status: 0 when every finding is baselined (or none), 1 when any
unbaselined finding exists, 2 on usage errors.  Default scan target is the
``mmlspark_tpu`` package the module was imported from; default baseline is
``analysis-baseline.toml`` next to the package (the repo root).

``--changed-only`` scopes REPORTING to files git sees as changed (staged,
unstaged, and untracked), while the analysis still parses the whole
package: the cross-module passes (STG inheritance, TRC call BFS, the CCY
lock graph) need every module in view to resolve — a staged-files-only
SCAN would false-positive — but a reviewer only wants findings their
diff can have introduced.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from .baseline import (DEFAULT_BASELINE_NAME, load_baseline, split_findings,
                       update_baseline)
from .checkers import (CheckpointAtomicityChecker, HotPathChecker,
                       LockDisciplineChecker, ResilienceCoverageChecker,
                       TracerSafetyChecker, TransferDisciplineChecker,
                       UnboundedBlockingChecker, UndeadlinedRetryChecker)
from .concurrency import ConcurrencyChecker
from .engine import AnalysisEngine, Checker, Finding, iter_python_files
from .stagecheck import StageContractChecker

__all__ = ["default_checkers", "run_analysis", "main", "rule_catalog"]


def default_checkers() -> List[Checker]:
    return [TracerSafetyChecker(), ResilienceCoverageChecker(),
            UndeadlinedRetryChecker(), CheckpointAtomicityChecker(),
            LockDisciplineChecker(), HotPathChecker(),
            TransferDisciplineChecker(), StageContractChecker(),
            UnboundedBlockingChecker(), ConcurrencyChecker()]


def rule_catalog() -> dict:
    """rule id -> description across all shipped checkers."""
    catalog = {}
    for checker in default_checkers():
        catalog.update(checker.rules)
    return catalog


def git_changed_files(root: str) -> Optional[List[str]]:
    """Repo-relative paths of changed ``.py`` files (staged + unstaged +
    untracked), or None when ``root`` is not a git work tree — the caller
    then falls back to an unscoped report rather than reporting nothing."""
    import subprocess
    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain", "-uall"],
            capture_output=True, text=True, timeout=30, check=True).stdout
    except Exception:  # noqa: BLE001 — not a repo / no git: fall back
        return None
    changed: List[str] = []
    for line in out.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:           # rename: report the new side
            path = path.split(" -> ", 1)[1]
        path = path.strip().strip('"')
        if path.endswith(".py"):
            changed.append(path)
    return changed


def _package_root() -> str:
    """The directory CONTAINING the mmlspark_tpu package (the repo root in
    a checkout) — findings are reported relative to it."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


def run_analysis(paths: Optional[Sequence[str]] = None,
                 root: Optional[str] = None,
                 checkers: Optional[Sequence[Checker]] = None,
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Programmatic entry: scan ``paths`` (default: the installed
    mmlspark_tpu package), return all findings before baselining."""
    root = root or _package_root()
    if paths is None:
        paths = [os.path.join(root, "mmlspark_tpu")]
    files: List[str] = []
    for p in paths:
        files.extend(iter_python_files(p))
    engine = AnalysisEngine(checkers or default_checkers(), root=root)
    findings = engine.run(files)
    if rules:
        # exact ids or family prefixes: "STG" matches STG001..STG003 (the
        # pre-commit hook restricts by family without hardcoding every id);
        # empty strings would prefix-match everything, so they are dropped
        wanted = tuple(r for r in rules if r)
        if wanted:
            findings = [f for f in findings if f.rule.startswith(wanted)]
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graft-lint",
        description="AST invariant checker: tracer safety (TRC), resilience "
                    "coverage (RES), lock discipline (LCK), hot-path "
                    "hygiene (HOT), transfer discipline (CMP), stage "
                    "contracts (STG), concurrency/deadlock (CCY).")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to scan (default: the "
                             "mmlspark_tpu package)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: "
                             f"{DEFAULT_BASELINE_NAME} at the repo root)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report every finding "
                             "and fail on any")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(existing justifications are preserved)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids or family prefixes "
                             "to restrict to (e.g. STG001,STG002 or "
                             "TRC,RES,LCK,HOT)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--root", default=None,
                        help="repo root for relative paths (default: the "
                             "package's parent directory)")
    parser.add_argument("--changed-only", action="store_true",
                        help="report findings only in git-changed files "
                             "(staged+unstaged+untracked); the full "
                             "package is still parsed so cross-module "
                             "rules resolve correctly")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(rule_catalog().items()):
            print(f"{rule}  {desc}")
        return 0

    root = os.path.abspath(args.root) if args.root else _package_root()
    # drop empty segments: a stray trailing comma would otherwise become a
    # ""-prefix that matches EVERY rule, silently un-restricting the scan
    rules = [r.strip() for r in args.rules.split(",") if r.strip()] \
        if args.rules else None
    findings = run_analysis(args.paths or None, root=root, rules=rules)

    changed_scope: Optional[List[str]] = None
    if args.changed_only:
        if args.update_baseline:
            parser.error("--changed-only cannot combine with "
                         "--update-baseline (a scoped rewrite would drop "
                         "every entry outside the diff)")
        changed_scope = git_changed_files(root)
        if changed_scope is not None:
            in_scope = set(changed_scope)
            findings = [f for f in findings if f.file in in_scope]

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE_NAME)
    if args.update_baseline:
        # a rule-restricted rewrite must not drop other families' entries:
        # findings were filtered, so out-of-scope entries would all look
        # "no longer firing" to the merge and be deleted with their
        # human-written justifications
        preserved = [e for e in load_baseline(baseline_path)
                     if not e.rule.startswith(tuple(rules))] if rules else []
        entries = update_baseline(baseline_path, findings, preserved)
        print(f"baseline written: {baseline_path} ({len(entries)} entries)")
        todo = sum(1 for e in entries if e.justification.startswith("TODO"))
        if todo:
            print(f"  {todo} entries need a justification before merge")
        return 0

    entries = [] if args.no_baseline else load_baseline(baseline_path)
    if rules:
        # a restricted scan must not report out-of-scope entries as stale
        entries = [e for e in entries if e.rule.startswith(tuple(rules))]
    if changed_scope is not None:
        # same guard for the diff scope: an entry for an unchanged file has
        # no matching finding left after the filter above and would be
        # reported stale on every pre-commit run
        in_scope = set(changed_scope)
        entries = [e for e in entries if e.file in in_scope]
    new, accepted, stale = split_findings(findings, entries)

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "baselined": [vars(f) for f in accepted],
            "stale_baseline_entries": [
                {"rule": e.rule, "file": e.file, "symbol": e.symbol}
                for e in stale],
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"-- {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed sites — "
                  "remove from the baseline):")
            for e in stale:
                print(f"   {e.rule} {e.file} [{e.symbol}]")
        print(f"graft-lint: {len(new)} finding"
              f"{'' if len(new) == 1 else 's'}, {len(accepted)} baselined, "
              f"{len(stale)} stale")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
