"""``python -m mmlspark_tpu.analysis`` — the graft-lint gate."""
import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
