"""STG — stage-contract checker: the static complement to testing/fuzzing.py.

The reflection harness (``codegen/registry.py`` + ``testing/fuzzing.py``)
enforces coverage at TEST time, but only over classes it can discover and
import.  A stage whose module sits outside the registry's ``SUBPACKAGES``
list, or whose ``Param`` attribute name drifts from the declared param name,
silently drops out of codegen, fuzzing, AND the generated bindings at once.
This checker re-derives the stage universe from source alone (no imports, no
jax) and cross-checks the three contracts.

The class graph is static and name-based: bases are resolved by final
segment against every class the scan saw, so `class Foo(Transformer)` and
`class Bar(base.CognitiveServicesBase)` both link.  Private classes
(``_``-prefixed) mirror the registry's own exclusion rule.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import AnalysisEngine, Checker, Finding, ModuleContext

__all__ = ["StageContractChecker"]

#: class names that root the Params/stage hierarchies (core/params.py and
#: core/pipeline.py); everything transitively derived is in scope.  The
#: framework bases are named explicitly so a fixture (or an out-of-tree
#: stage) subclassing `Transformer` links without scanning core itself.
_PARAMS_ROOTS = {"Params"}
_STAGE_ROOTS = {"PipelineStage", "Transformer", "Estimator", "Model",
                "UnaryTransformer"}

#: Param-declaring call targets
_PARAM_CALLS = {"Param", "ComplexParam", "ServiceParam"}

#: accessor names the Params base itself defines — not per-param accessors
_ACCESSOR_WHITELIST = {"set_params", "set_col", "get_param", "get_or_fail"}


class _ClassInfo:
    __slots__ = ("name", "relpath", "lineno", "bases", "param_names",
                 "param_attr_mismatches", "accessors", "is_private")

    def __init__(self, name: str, relpath: str, lineno: int,
                 bases: Sequence[str]):
        self.name = name
        self.relpath = relpath
        self.lineno = lineno
        self.bases = list(bases)
        #: declared param NAMES (first arg of Param(...) class attributes)
        self.param_names: Set[str] = set()
        #: (attr_name, param_name, lineno) where the two disagree
        self.param_attr_mismatches: List[Tuple[str, str, int]] = []
        #: manually defined set_x/get_x method names with linenos
        self.accessors: List[Tuple[str, int]] = []
        self.is_private = name.startswith("_")


class StageContractChecker(Checker):
    """STG001 param attribute/name drift, STG002 stage outside the codegen
    registry, STG003 manual accessor for an undeclared param."""

    rules = {
        "STG001": "Param attribute name != declared param name (breaks "
                  "set_/get_ synthesis and serialization)",
        "STG002": "stage class not discoverable by the codegen registry "
                  "(module outside SUBPACKAGES)",
        "STG003": "manual set_/get_ accessor without a declared param",
    }

    def __init__(self, subpackages: Optional[Sequence[str]] = None,
                 package: str = "mmlspark_tpu"):
        #: explicit SUBPACKAGES override (fixtures); None = read it from
        #: the scanned codegen/registry.py source in finalize
        self.subpackages = tuple(subpackages) if subpackages else None
        self.package = package
        self._classes: Dict[str, _ClassInfo] = {}

    def interested(self, relpath: str) -> bool:
        return True

    # ------------------------------------------------------------- events
    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.ClassDef):
            return
        bases = []
        for b in node.bases:
            dotted = ctx.dotted_name(b)
            if dotted:
                bases.append(dotted.split(".")[-1])
        info = _ClassInfo(node.name, ctx.relpath, node.lineno, bases)
        for stmt in node.body:
            self._collect_member(stmt, info)
        # last definition of a short name wins (names are unique in-tree)
        self._classes[node.name] = info

    def _collect_member(self, stmt: ast.stmt, info: _ClassInfo) -> None:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Call):
            func = stmt.value.func
            fname = func.id if isinstance(func, ast.Name) else \
                (func.attr if isinstance(func, ast.Attribute) else "")
            if fname in _PARAM_CALLS:
                attr = stmt.targets[0].id
                pname = self._param_name(stmt.value)
                if pname is None:
                    return  # dynamic name — nothing checkable statically
                info.param_names.add(pname)
                if pname != attr:
                    info.param_attr_mismatches.append(
                        (attr, pname, stmt.lineno))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (stmt.name.startswith("set_") or
                    stmt.name.startswith("get_")) and \
                    stmt.name not in _ACCESSOR_WHITELIST:
                info.accessors.append((stmt.name, stmt.lineno))

    @staticmethod
    def _param_name(call: ast.Call) -> Optional[str]:
        if call.args and isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            return call.args[0].value
        for kw in call.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                return kw.value.value
        return None

    # ----------------------------------------------------------- finalize
    def _descendants(self, roots: Set[str]) -> Set[str]:
        """Transitive closure over the static base-name graph."""
        out = set(roots)
        changed = True
        while changed:
            changed = False
            for name, info in self._classes.items():
                if name not in out and any(b in out for b in info.bases):
                    out.add(name)
                    changed = True
        return out

    def _registry_subpackages(self, engine: AnalysisEngine
                              ) -> Optional[Tuple[str, ...]]:
        if self.subpackages is not None:
            return self.subpackages
        ctx = engine.modules.get(f"{self.package}/codegen/registry.py")
        if ctx is None:
            return None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == "SUBPACKAGES" and \
                    isinstance(node.value, ast.List):
                return tuple(e.value for e in node.value.elts
                             if isinstance(e, ast.Constant))
        return None

    def _ancestor_params(self, name: str) -> Set[str]:
        """Param names declared on the class or any static ancestor."""
        out: Set[str] = set()
        seen: Set[str] = set()
        stack = [name]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            info = self._classes.get(cur)
            if info is None:
                continue
            out |= info.param_names
            stack.extend(info.bases)
        return out

    def finalize(self, engine: AnalysisEngine) -> List[Finding]:
        findings: List[Finding] = []
        params_classes = self._descendants(_PARAMS_ROOTS | _STAGE_ROOTS)
        stage_classes = self._descendants(_STAGE_ROOTS)
        subpackages = self._registry_subpackages(engine)
        for name in sorted(params_classes):
            info = self._classes.get(name)
            if info is None:
                continue
            for attr, pname, lineno in info.param_attr_mismatches:
                findings.append(Finding(
                    rule="STG001", file=info.relpath, line=lineno,
                    message=f"class attribute '{attr}' declares param "
                            f"'{pname}' — the names must match for "
                            "set_/get_ synthesis and codegen",
                    symbol=f"{name}.{attr}"))
            if name in stage_classes and not info.is_private and \
                    name not in _STAGE_ROOTS:
                declared = self._ancestor_params(name)
                for acc, lineno in info.accessors:
                    pname = acc[4:]
                    if pname and pname not in declared:
                        findings.append(Finding(
                            rule="STG003", file=info.relpath, line=lineno,
                            message=f"manual accessor {acc}() has no "
                                    f"declared param '{pname}' — declare "
                                    "it via core/params or rename",
                            symbol=f"{name}.{acc}"))
                if subpackages is not None and \
                        self._outside_registry(info, subpackages):
                    findings.append(Finding(
                        rule="STG002", file=info.relpath, line=info.lineno,
                        message=f"stage {name} lives outside the codegen "
                                "registry SUBPACKAGES — codegen and the "
                                "fuzzing sweep cannot discover it",
                        symbol=name))
        return findings

    def _outside_registry(self, info: _ClassInfo,
                          subpackages: Sequence[str]) -> bool:
        parts = info.relpath.split("/")
        if parts[0] != self.package:
            return False  # fixtures and tools are out of registry scope
        return len(parts) < 3 or parts[1] not in subpackages
