"""graft-lint engine — one AST walk per module, events to registered checkers.

The repo's quality bar is a set of invariants that reviews kept re-enforcing
by hand (deadline clipping at remote boundaries, no work under registry/
breaker locks, no entropy syscalls in the serialized score path, tracer
safety under ``jax.jit``).  This engine makes them machine-checked: every
module is parsed ONCE, each AST node is dispatched to every registered
checker along with a :class:`ModuleContext` (import table, enclosing-function
stack, lock-nesting depth), and checkers emit :class:`Finding` records.
Cross-module checkers accumulate state per module and emit in ``finalize``.

No mmlspark_tpu runtime module is imported by the engine — analysis is pure
source-level, so the tier-1 sweep costs one parse pass, not a jax import.

Suppression is two-layer (see ``baseline.py`` for the repo baseline file):
an inline pragma on the offending line silences a rule at that site::

    x = uuid.uuid4()  # graft-lint: disable=HOT001

``# graft-lint: disable-file=RULE`` anywhere in a file silences the rule for
the whole file; ``all`` matches every rule.  Pragmas are for sites where the
violation is load-bearing and local; the baseline is for repo-wide curation.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "Checker", "ModuleContext", "AnalysisEngine",
           "iter_python_files"]

_PRAGMA_RE = re.compile(r"#\s*graft-lint:\s*(disable(?:-file)?)\s*=\s*"
                        r"([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location.

    ``key()`` is the baseline identity: rule + file + symbol (the enclosing
    function/class), deliberately excluding the line number so unrelated
    edits above a baselined site do not invalidate the baseline.
    """
    rule: str
    file: str          # repo-relative posix path
    line: int
    message: str
    severity: str = "error"
    symbol: str = ""   # enclosing def/class qualname ("" = module level)

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.symbol)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.file}:{self.line}: {self.rule} {self.message}{sym}"


class ModuleContext:
    """Per-module state handed to checkers with every node event."""

    def __init__(self, path: str, relpath: str, tree: ast.Module,
                 source_lines: Sequence[str]):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.tree = tree
        self.source_lines = source_lines
        #: alias -> fully qualified dotted name ("np" -> "numpy",
        #: "urlopen" -> "urllib.request.urlopen")
        self.imports: Dict[str, str] = {}
        #: stack of enclosing FunctionDef/AsyncFunctionDef/ClassDef nodes
        self.scope_stack: List[ast.AST] = []
        #: nesting depth of `with <lock>:` bodies at the current node
        self.lock_depth: int = 0
        self._findings: List[Finding] = []
        self._build_imports(tree)

    # ------------------------------------------------------------- imports
    def _build_imports(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        self.imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def dotted_name(self, node: ast.AST) -> Optional[str]:
        """Best-effort canonical dotted path of a Name/Attribute chain,
        resolving the leading segment through the import table:
        ``np.random.default_rng`` -> ``numpy.random.default_rng``."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(self.imports.get(node.id, node.id))
        elif isinstance(node, ast.Call):
            # foo().bar — resolve through the call's target
            inner = self.dotted_name(node.func)
            if inner is None:
                return None
            parts.append(inner)
        else:
            return None
        return ".".join(reversed(parts))

    # ------------------------------------------------------------- scope
    def scope_qualname(self) -> str:
        names = [getattr(n, "name", "<lambda>") for n in self.scope_stack]
        return ".".join(names)

    def enclosing_function(self) -> Optional[ast.AST]:
        for node in reversed(self.scope_stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return None

    # ------------------------------------------------------------- report
    def report(self, rule: str, node: ast.AST, message: str,
               severity: str = "error") -> None:
        self._findings.append(Finding(
            rule=rule, file=self.relpath,
            line=getattr(node, "lineno", 0), message=message,
            severity=severity, symbol=self.scope_qualname()))


class Checker:
    """Base checker: override the event hooks you need.

    ``visit`` fires for EVERY node of every interesting module, in source
    order, with scope/lock context already updated on ``ctx``.
    """

    #: rule id -> one-line description (drives the docs catalog + CLI help)
    rules: Dict[str, str] = {}

    def interested(self, relpath: str) -> bool:
        """Module filter; default: every scanned module."""
        return True

    def begin_module(self, ctx: ModuleContext) -> None:
        pass

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        pass

    def end_module(self, ctx: ModuleContext) -> None:
        pass

    def finalize(self, engine: "AnalysisEngine") -> List[Finding]:
        """Cross-module findings, after every module has been walked."""
        return []


def _looks_like_lock(node: ast.AST) -> bool:
    """Heuristic: the context expression of `with X:` names a lock
    (`self._lock`, `stats.lock`, `_global_lock`, `lock.acquire()`...)."""
    target = node
    if isinstance(target, ast.Call):   # with lock.acquire(...) / Lock()
        target = target.func
    name = None
    if isinstance(target, ast.Attribute):
        name = target.attr
    elif isinstance(target, ast.Name):
        name = target.id
    if name is None:
        return False
    if name == "acquire":
        inner = target.value if isinstance(target, ast.Attribute) else None
        return inner is not None and _looks_like_lock(inner)
    return "lock" in name.lower() or "mutex" in name.lower()


def with_lock_items(node: ast.With) -> List[ast.AST]:
    """The lock-like context expressions of a With statement."""
    return [item.context_expr for item in node.items
            if _looks_like_lock(item.context_expr)]


class _Walker:
    """Single recursive walk maintaining scope + lock depth on the ctx."""

    def __init__(self, checkers: Sequence[Checker], ctx: ModuleContext):
        self.checkers = checkers
        self.ctx = ctx

    def walk(self, node: ast.AST) -> None:
        ctx = self.ctx
        is_scope = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef, ast.Lambda))
        holds_lock = isinstance(node, ast.With) and bool(with_lock_items(node))
        for checker in self.checkers:
            checker.visit(node, ctx)
        if is_scope:
            ctx.scope_stack.append(node)
        if holds_lock:
            ctx.lock_depth += 1
        try:
            for child in ast.iter_child_nodes(node):
                self.walk(child)
        finally:
            if holds_lock:
                ctx.lock_depth -= 1
            if is_scope:
                ctx.scope_stack.pop()


def _parse_pragmas(source_lines: Sequence[str]
                   ) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """-> ({line_no: {rules}}, {file_wide_rules}); "all" matches any rule."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for i, line in enumerate(source_lines, start=1):
        for kind, rules in _PRAGMA_RE.findall(line):
            ids = {r.strip() for r in rules.split(",") if r.strip()}
            if kind == "disable-file":
                file_wide |= ids
            else:
                per_line.setdefault(i, set()).update(ids)
    return per_line, file_wide


def iter_python_files(root: str) -> Iterable[str]:
    """Every .py under root, skipping caches and generated trees."""
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


class AnalysisEngine:
    """Parse each module once; dispatch to checkers; collect findings.

    ``root`` anchors the repo-relative paths findings carry (and the path
    prefixes checkers filter on): scanning ``<repo>/mmlspark_tpu`` with
    ``root=<repo>`` yields paths like ``mmlspark_tpu/serving/server.py``.
    """

    def __init__(self, checkers: Sequence[Checker], root: str):
        self.checkers = list(checkers)
        self.root = os.path.abspath(root)
        #: relpath -> ModuleContext, for cross-module finalize passes
        self.modules: Dict[str, ModuleContext] = {}
        self.parse_errors: List[Finding] = []

    def run(self, paths: Iterable[str]) -> List[Finding]:
        findings: List[Finding] = []
        for path in paths:
            findings.extend(self._run_module(os.path.abspath(path)))
        for checker in self.checkers:
            for f in checker.finalize(self):
                ctx = self.modules.get(f.file)
                if ctx is None or not _suppressed(f, ctx):
                    findings.append(f)
        findings.extend(self.parse_errors)
        findings.sort(key=lambda f: (f.file, f.line, f.rule))
        return findings

    def _run_module(self, path: str) -> List[Finding]:
        relpath = os.path.relpath(path, self.root)
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_errors.append(Finding(
                rule="ENG001", file=relpath.replace(os.sep, "/"),
                line=e.lineno or 0, message=f"syntax error: {e.msg}"))
            return []
        ctx = ModuleContext(path, relpath, tree, source.splitlines())
        self.modules[ctx.relpath] = ctx
        active = [c for c in self.checkers if c.interested(ctx.relpath)]
        if not active:
            return []
        for c in active:
            c.begin_module(ctx)
        _Walker(active, ctx).walk(tree)
        for c in active:
            c.end_module(ctx)
        return [f for f in ctx._findings if not _suppressed(f, ctx)]


def _suppressed(finding: Finding, ctx: ModuleContext) -> bool:
    pragmas = getattr(ctx, "_pragmas", None)
    if pragmas is None:
        pragmas = ctx._pragmas = _parse_pragmas(ctx.source_lines)
    per_line, file_wide = pragmas
    if "all" in file_wide or finding.rule in file_wide:
        return True
    rules = per_line.get(finding.line, ())
    return "all" in rules or finding.rule in rules
