"""Baseline file — the repo's curated list of accepted findings.

``analysis-baseline.toml`` (repo root) records every finding the team has
looked at and deliberately kept, each with a one-line justification.  The
CLI exits nonzero on any finding NOT in the baseline, so the gate ratchets:
new violations fail CI immediately, old accepted ones stay visible and
justified instead of silently pragma'd away.

Identity is ``(rule, file, symbol)`` (see ``Finding.key``) — line numbers
are recorded for the reader but do not participate in matching, so edits
elsewhere in a file never invalidate its baseline entries.

The file is a deliberately tiny TOML subset (``[[suppression]]`` tables of
string keys) read/written by this module directly: the container's Python
predates ``tomllib`` and the repo vendors nothing.
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, Sequence, Tuple

from .engine import Finding

__all__ = ["BaselineEntry", "load_baseline", "save_baseline",
           "split_findings", "update_baseline", "DEFAULT_BASELINE_NAME"]

DEFAULT_BASELINE_NAME = "analysis-baseline.toml"

_HEADER = """\
# graft-lint baseline — accepted findings, one justified entry each.
# Regenerate scaffolding with: python -m mmlspark_tpu.analysis --update-baseline
# Matching is (rule, file, symbol); `line` is informational only.
"""


class BaselineEntry:
    __slots__ = ("rule", "file", "symbol", "line", "justification", "count")

    def __init__(self, rule: str, file: str, symbol: str = "",
                 line: int = 0, justification: str = "", count: int = 1):
        self.rule = rule
        self.file = file
        self.symbol = symbol
        self.line = int(line)
        self.justification = justification
        #: how many findings this entry covers — the ratchet: a SECOND
        #: same-rule violation appearing inside an already-baselined
        #: function is a NEW finding, not silently accepted
        self.count = max(1, int(count))

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.symbol)

    @classmethod
    def for_finding(cls, f: Finding, justification: str) -> "BaselineEntry":
        return cls(rule=f.rule, file=f.file, symbol=f.symbol, line=f.line,
                   justification=justification)


def _toml_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def _toml_unescape(s: str) -> str:
    return s.replace('\\"', '"').replace("\\\\", "\\")


def load_baseline(path: str) -> List[BaselineEntry]:
    """Parse the baseline's TOML subset; missing file = empty baseline."""
    if not os.path.exists(path):
        return []
    entries: List[BaselineEntry] = []
    current: Dict[str, str] = {}
    in_table = False
    with open(path, encoding="utf-8") as fh:
        for raw_line in fh:
            line = raw_line.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[suppression]]":
                if in_table:
                    entries.append(_entry_from(current))
                current, in_table = {}, True
                continue
            if "=" in line and in_table:
                key, _, value = line.partition("=")
                key, value = key.strip(), value.strip()
                if value.startswith('"') and value.endswith('"'):
                    current[key] = _toml_unescape(value[1:-1])
                else:
                    current[key] = value  # bare int (line = 42)
    if in_table:
        entries.append(_entry_from(current))
    return entries


def _entry_from(d: Dict[str, str]) -> BaselineEntry:
    def _int(key, default):
        try:
            return int(d.get(key, default))
        except ValueError:
            return default
    return BaselineEntry(rule=d.get("rule", ""), file=d.get("file", ""),
                         symbol=d.get("symbol", ""), line=_int("line", 0),
                         justification=d.get("justification", ""),
                         count=_int("count", 1))


def save_baseline(path: str, entries: Sequence[BaselineEntry]) -> None:
    chunks = [_HEADER]
    for e in sorted(entries, key=lambda e: e.key()):
        count = f"count = {e.count}\n" if e.count > 1 else ""
        chunks.append(
            "\n[[suppression]]\n"
            f'rule = "{_toml_escape(e.rule)}"\n'
            f'file = "{_toml_escape(e.file)}"\n'
            f'symbol = "{_toml_escape(e.symbol)}"\n'
            f"line = {e.line}\n" + count +
            f'justification = "{_toml_escape(e.justification)}"\n')
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("".join(chunks))


def split_findings(findings: Iterable[Finding],
                   entries: Sequence[BaselineEntry]
                   ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
    """-> (unbaselined, baselined, stale_entries).

    Each entry covers at most ``count`` findings (default 1): a second
    same-rule violation landing inside an already-baselined function is
    NEW, so the ratchet holds even within baselined symbols.  Stale
    entries (baselined sites that no longer fire) are surfaced so the
    baseline shrinks as violations get fixed — they warn, never fail."""
    remaining = {e.key(): e.count for e in entries}
    matched: set = set()
    new: List[Finding] = []
    accepted: List[Finding] = []
    for f in findings:
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            matched.add(f.key())
            accepted.append(f)
        else:
            new.append(f)
    stale = [e for e in entries if e.key() not in matched]
    return new, accepted, stale


def update_baseline(path: str, findings: Iterable[Finding],
                    preserved: Sequence[BaselineEntry] = ()
                    ) -> List[BaselineEntry]:
    """Merge current findings into the baseline: existing justifications are
    preserved, new findings get a TODO placeholder (CI policy: a reviewer
    replaces it before merge), entries that no longer fire are dropped.

    ``preserved`` entries are written back verbatim regardless of the
    findings — a rule-restricted scan (``--rules STG --update-baseline``)
    passes its out-of-scope entries here, so restricting the scan can
    never silently delete another family's justified suppressions."""
    existing = {e.key(): e for e in load_baseline(path)}
    merged: Dict[Tuple[str, str, str], BaselineEntry] = {}
    for f in findings:
        prior = merged.get(f.key())
        if prior is not None:  # Nth same-key finding: widen the count
            prior.count += 1
            continue
        prior = existing.get(f.key())
        if prior is not None and prior.justification and \
                not prior.justification.startswith("TODO"):
            prior.line = f.line  # refresh the informational line
            prior.count = 1     # recounted from the live findings
            merged[f.key()] = prior
        else:
            merged[f.key()] = BaselineEntry.for_finding(
                f, "TODO: justify or fix")
    for e in preserved:
        merged.setdefault(e.key(), e)
    entries = list(merged.values())
    save_baseline(path, entries)
    return entries
