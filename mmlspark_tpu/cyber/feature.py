"""CyberML feature utilities — partitioned indexers and scalers.

Reference: ``core/src/main/python/mmlspark/cyber/feature/indexers.py`` and
``scalers.py``: per-tenant id indexing and per-tenant score scaling.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..core import DataFrame, Estimator, HasInputCol, HasOutputCol, Model, Param


class _PerTenantBase:
    tenant_col = Param("tenant_col", "partition/tenant column", "string", default="tenant")


class IdIndexer(Estimator, HasInputCol, HasOutputCol):
    """Per-tenant contiguous id assignment (1-based, reference indexers)."""
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    reset_per_partition = Param("reset_per_partition", "restart ids per tenant",
                                "bool", default=True)

    def _fit(self, df):
        data = df.collect()
        tc, ic = self.get("tenant_col"), self.get_or_fail("input_col")
        mapping: Dict[str, Dict[str, int]] = {}
        per_tenant = self.get("reset_per_partition")
        for i in range(len(data[ic])):
            tenant = str(data[tc][i]) if tc in data and per_tenant else "_"
            sub = mapping.setdefault(tenant, {})
            key = str(data[ic][i])
            if key not in sub:
                sub[key] = len(sub) + 1
        m = IdIndexerModel()
        m.set("input_col", ic)
        m.set("output_col", self.get_or_fail("output_col"))
        m.set("tenant_col", tc)
        m.set("mapping", mapping)
        return m


class IdIndexerModel(Model, HasInputCol, HasOutputCol):
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    mapping = Param("mapping", "tenant -> value -> id", "object")

    def _transform(self, df):
        mapping = self.get_or_fail("mapping")
        tc, ic = self.get("tenant_col"), self.get_or_fail("input_col")

        def per_part(p):
            out = np.zeros(len(p[ic]), np.float64)
            for i in range(len(out)):
                tenant = str(p[tc][i]) if tc in p else "_"
                sub = mapping.get(tenant) or mapping.get("_", {})
                out[i] = sub.get(str(p[ic][i]), 0)
            return {**p, self.get_or_fail("output_col"): out}

        return df.map_partitions(per_part)


class StandardScalarScaler(Estimator, HasInputCol, HasOutputCol):
    """Per-tenant z-scaling (reference scalers.py StandardScalarScaler)."""
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    coefficient_factor = Param("coefficient_factor", "std multiplier", "float", default=1.0)

    def _fit(self, df):
        data = df.collect()
        tc, ic = self.get("tenant_col"), self.get_or_fail("input_col")
        stats: Dict[str, tuple] = {}
        tenants = data[tc].astype(str) if tc in data else np.full(len(data[ic]), "_")
        vals = np.asarray(data[ic], np.float64)
        for t in set(tenants.tolist()):
            v = vals[tenants == t]
            stats[t] = (float(v.mean()), float(v.std()) or 1.0)
        m = StandardScalarScalerModel()
        m.set("input_col", ic)
        m.set("output_col", self.get_or_fail("output_col"))
        m.set("tenant_col", tc)
        m.set("stats", stats)
        m.set("coefficient_factor", self.get("coefficient_factor"))
        return m


class StandardScalarScalerModel(Model, HasInputCol, HasOutputCol):
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    stats = Param("stats", "tenant -> (mean, std)", "object")
    coefficient_factor = Param("coefficient_factor", "std multiplier", "float", default=1.0)

    def _transform(self, df):
        stats = self.get_or_fail("stats")
        cf = self.get("coefficient_factor")
        tc, ic = self.get("tenant_col"), self.get_or_fail("input_col")

        def per_part(p):
            out = np.zeros(len(p[ic]), np.float64)
            for i in range(len(out)):
                t = str(p[tc][i]) if tc in p else "_"
                mu, sd = stats.get(t, (0.0, 1.0))
                out[i] = cf * (float(p[ic][i]) - mu) / sd
            return {**p, self.get_or_fail("output_col"): out}

        return df.map_partitions(per_part)


class LinearScalarScaler(Estimator, HasInputCol, HasOutputCol):
    """Per-tenant min-max scaling to [min_value, max_value]."""
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    min_required_value = Param("min_required_value", "output min", "float", default=0.0)
    max_required_value = Param("max_required_value", "output max", "float", default=1.0)

    def _fit(self, df):
        data = df.collect()
        tc, ic = self.get("tenant_col"), self.get_or_fail("input_col")
        tenants = data[tc].astype(str) if tc in data else np.full(len(data[ic]), "_")
        vals = np.asarray(data[ic], np.float64)
        rng: Dict[str, tuple] = {}
        for t in set(tenants.tolist()):
            v = vals[tenants == t]
            rng[t] = (float(v.min()), float(v.max()))
        m = LinearScalarScalerModel()
        m.set("input_col", ic)
        m.set("output_col", self.get_or_fail("output_col"))
        m.set("tenant_col", tc)
        m.set("ranges", rng)
        m.set("min_required_value", self.get("min_required_value"))
        m.set("max_required_value", self.get("max_required_value"))
        return m


class LinearScalarScalerModel(Model, HasInputCol, HasOutputCol):
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    ranges = Param("ranges", "tenant -> (min, max)", "object")
    min_required_value = Param("min_required_value", "output min", "float", default=0.0)
    max_required_value = Param("max_required_value", "output max", "float", default=1.0)

    def _transform(self, df):
        ranges = self.get_or_fail("ranges")
        lo, hi = self.get("min_required_value"), self.get("max_required_value")
        tc, ic = self.get("tenant_col"), self.get_or_fail("input_col")

        def per_part(p):
            out = np.zeros(len(p[ic]), np.float64)
            for i in range(len(out)):
                t = str(p[tc][i]) if tc in p else "_"
                vmin, vmax = ranges.get(t, (0.0, 1.0))
                span = (vmax - vmin) or 1.0
                out[i] = lo + (float(p[ic][i]) - vmin) / span * (hi - lo)
            return {**p, self.get_or_fail("output_col"): out}

        return df.map_partitions(per_part)
