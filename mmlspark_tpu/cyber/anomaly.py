"""AccessAnomaly — anomalous user->resource access detection.

Reference: ``core/src/main/python/mmlspark/cyber/anomaly/
collaborative_filtering.py`` (988 LoC): per-tenant ALS collaborative
filtering over (user, resource) access counts, implicit-CF by default
(``default_apply_implicit_cf``), complement sampling of unobserved pairs as
explicit negatives otherwise, and score standardisation so higher output =
more anomalous.

TPU-native, SPARSE: observations stay in COO form end to end.  Each ALS
half-step builds per-row normal equations with ``segment_sum`` over the
nonzeros (chunked so nnz*k^2 never materialises beyond a fixed budget) and
solves them with one vmapped ``linalg.solve`` — O(nnz k^2 + rows k^3) per
sweep, never O(users x resources).  Implicit mode is Hu-Koren confidence
weighting: the all-pairs term collapses to the k x k gram matrix V^T V, so
unobserved pairs cost nothing.  Scoring uses hash-map index lookups and a
factor dot per row.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, Model, Param)
from ..core.dataframe import _as_column

_CHUNK_NNZ = 250_000  # caps the (chunk, k, k) outer-product buffer


def _get_accumulate():
    """Module-level jitted kernels so every half-sweep hits the jit cache
    (fresh closures inside the sweep would recompile 2*iters times)."""
    global _ACCUMULATE, _SOLVE_ALL
    if _ACCUMULATE is None:
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_rows", "implicit"))
        def accumulate(F, tgt, cf, seg, n_rows, implicit):
            # implicit: A += (c-1) f f^T, b += c*t*f ; explicit: A += c f f^T
            w_outer = cf - 1.0 if implicit else cf
            outer = (F[:, :, None] * F[:, None, :]) * w_outer[:, None, None]
            a = jax.ops.segment_sum(outer, seg, num_segments=n_rows)
            b = jax.ops.segment_sum(F * (cf * tgt)[:, None], seg,
                                    num_segments=n_rows)
            return a, b

        @jax.jit
        def solve_all(A, B, base):
            return jax.vmap(jnp.linalg.solve)(A + base, B)

        _ACCUMULATE, _SOLVE_ALL = accumulate, solve_all
    return _ACCUMULATE, _SOLVE_ALL


_ACCUMULATE = _SOLVE_ALL = None


def _solve_side(other: np.ndarray, row_idx: np.ndarray, col_idx: np.ndarray,
                target: np.ndarray, conf: np.ndarray, n_rows: int,
                reg: float, gram: Optional[np.ndarray]) -> np.ndarray:
    """One ALS half-sweep from COO triples.

    For each row r: solve (gram? + sum_nnz c f f^T + reg I) x = b with
    segment-summed normal equations.  ``gram`` is the implicit-CF all-pairs
    term V^T V (None for explicit mode, where only the nonzeros carry
    weight and ``conf`` is the per-entry weight directly).
    """
    import jax.numpy as jnp

    accumulate, solve_all = _get_accumulate()
    k = other.shape[1]
    A = np.zeros((n_rows, k, k), np.float32)
    B = np.zeros((n_rows, k), np.float32)
    for s in range(0, len(row_idx), _CHUNK_NNZ):
        e = s + _CHUNK_NNZ
        a, b = accumulate(jnp.asarray(other[col_idx[s:e]]),
                          jnp.asarray(target[s:e]), jnp.asarray(conf[s:e]),
                          jnp.asarray(row_idx[s:e]), n_rows=n_rows,
                          implicit=gram is not None)
        A += np.asarray(a)
        B += np.asarray(b)
    base = (gram if gram is not None else 0.0) + reg * np.eye(k, dtype=np.float32)
    return np.asarray(solve_all(jnp.asarray(A), jnp.asarray(B),
                                jnp.asarray(base)))


def sparse_als(u_idx: np.ndarray, r_idx: np.ndarray, counts: np.ndarray,
               n_u: int, n_i: int, rank: int, reg: float, iters: int,
               seed: int, implicit: bool = True, alpha: float = 10.0,
               neg_u: Optional[np.ndarray] = None,
               neg_r: Optional[np.ndarray] = None,
               neg_score: float = 0.0, neg_weight: float = 0.5,
               pos_score: float = 1.0) -> Tuple[np.ndarray, np.ndarray]:
    """Per-tenant ALS over COO observations.

    implicit=True: Hu-Koren implicit CF (reference
    ``default_apply_implicit_cf``) — confidence c = 1 + alpha*count on
    observed pairs, preference p = 1; unobserved pairs enter only through
    the k x k gram term.
    implicit=False: explicit ridge ALS over observed entries (target
    ``pos_score`` scaled by count) plus the supplied complement-sampled
    negatives at ``neg_score`` with weight ``neg_weight``.
    """
    rng = np.random.default_rng(seed)
    U = rng.normal(scale=0.1, size=(n_u, rank)).astype(np.float32)
    V = rng.normal(scale=0.1, size=(n_i, rank)).astype(np.float32)
    counts = np.asarray(counts, np.float32)

    if implicit:
        conf = 1.0 + alpha * counts
        tgt = np.ones_like(conf)
        uu, rr = u_idx, r_idx
    else:
        tgt = np.maximum(counts, pos_score)
        conf = np.ones_like(tgt)
        uu, rr = u_idx, r_idx
        if neg_u is not None and len(neg_u):
            # exclude sampled pairs the user actually accessed — a collision
            # would append a contradictory zero target for an observed cell
            obs_keys = np.unique(u_idx.astype(np.int64) * n_i + r_idx)
            neg_keys = neg_u.astype(np.int64) * n_i + neg_r
            keep = ~np.isin(neg_keys, obs_keys)
            neg_u, neg_r = neg_u[keep], neg_r[keep]
            uu = np.concatenate([u_idx, neg_u])
            rr = np.concatenate([r_idx, neg_r])
            tgt = np.concatenate([tgt, np.full(len(neg_u), neg_score, np.float32)])
            conf = np.concatenate([conf, np.full(len(neg_u), neg_weight, np.float32)])

    for _ in range(iters):
        gram_v = (V.T @ V).astype(np.float32) if implicit else None
        U = _solve_side(V, uu, rr, tgt, conf, n_u, reg, gram_v)
        gram_u = (U.T @ U).astype(np.float32) if implicit else None
        V = _solve_side(U, rr, uu, tgt, conf, n_i, reg, gram_u)
    return U, V


class ComplementAccessTransformer:
    """Sample unobserved (user, resource) pairs — the implicit negatives
    (reference ``ComplementAccessTransformer``)."""

    def __init__(self, tenant_col: str = "tenant", user_col: str = "user",
                 res_col: str = "res", complement_factor: int = 2, seed: int = 0):
        self.tenant_col, self.user_col, self.res_col = tenant_col, user_col, res_col
        self.factor = complement_factor
        self.seed = seed

    def transform(self, df: DataFrame) -> DataFrame:
        data = df.collect()
        rng = np.random.default_rng(self.seed)
        tc, uc, rc = self.tenant_col, self.user_col, self.res_col
        tenants = data[tc].astype(str) if tc in data else np.full(len(data[uc]), "_")
        rows = []
        for t in sorted(set(tenants.tolist())):
            sel = tenants == t
            users = sorted(set(data[uc][sel].astype(str).tolist()))
            ress = sorted(set(data[rc][sel].astype(str).tolist()))
            seen = set(zip(data[uc][sel].astype(str), data[rc][sel].astype(str)))
            want = min(self.factor * int(sel.sum()),
                       max(0, len(users) * len(ress) - len(seen)))
            tries = 0
            got = set()
            while len(got) < want and tries < want * 20:
                u = users[rng.integers(0, len(users))]
                r = ress[rng.integers(0, len(ress))]
                if (u, r) not in seen and (u, r) not in got:
                    got.add((u, r))
                tries += 1
            for u, r in sorted(got):
                rows.append({tc: t, uc: u, rc: r})
        return DataFrame.from_rows(rows)


class AccessAnomaly(Estimator):
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    user_col = Param("user_col", "user column", "string", default="user")
    res_col = Param("res_col", "resource column", "string", default="res")
    likelihood_col = Param("likelihood_col", "access count column (optional)",
                           "string", default=None)
    rank = Param("rank", "latent factor rank", "int", default=10)
    max_iter = Param("max_iter", "ALS iterations", "int", default=10)
    reg_param = Param("reg_param", "ridge regularization", "float", default=0.1)
    implicit_cf = Param("implicit_cf", "Hu-Koren implicit CF (reference "
                        "default_apply_implicit_cf); False = explicit targets "
                        "with sampled complement negatives", "bool", default=True)
    alpha = Param("alpha", "implicit-CF confidence scale", "float", default=10.0)
    complementset_factor = Param("complementset_factor", "negatives per positive "
                                 "(explicit mode)", "int", default=2)
    neg_score = Param("neg_score", "implicit negative target", "float", default=0.0)
    pos_score = Param("pos_score", "observed access target", "float", default=1.0)
    seed = Param("seed", "random seed", "int", default=0)

    def _fit(self, df: DataFrame) -> "AccessAnomalyModel":
        data = df.collect()
        tc = self.get("tenant_col")
        uc, rc = self.get("user_col"), self.get("res_col")
        tenants = data[tc].astype(str) if tc in data else np.full(len(data[uc]), "_")
        factors: Dict[str, Dict] = {}
        for t in sorted(set(tenants.tolist())):
            sel = tenants == t
            users, u_idx = np.unique(data[uc][sel].astype(str), return_inverse=True)
            ress, r_idx = np.unique(data[rc][sel].astype(str), return_inverse=True)
            n_u, n_i = len(users), len(ress)
            lc = self.get("likelihood_col")
            counts = np.asarray(data[lc], np.float64)[sel].astype(np.float32) \
                if lc and lc in data else np.ones(int(sel.sum()), np.float32)
            # aggregate duplicate (user, resource) observations: implicit CF
            # SUMS counts (c = 1 + alpha * total accesses, Hu-Koren);
            # explicit mode AVERAGES the rating (d log lines at rating v are
            # one observation of v, matching the old dense assignment)
            keys = u_idx.astype(np.int64) * n_i + r_idx
            uniq_keys, inv = np.unique(keys, return_inverse=True)
            sums = np.bincount(inv, weights=counts)
            if self.get("implicit_cf"):
                counts = sums.astype(np.float32)
            else:
                counts = (sums / np.bincount(inv)).astype(np.float32)
            u_idx = (uniq_keys // n_i).astype(np.int64)
            r_idx = (uniq_keys % n_i).astype(np.int64)
            rank = min(self.get("rank"), min(n_u, n_i))
            rng = np.random.default_rng(self.get("seed"))
            neg_u = neg_r = None
            if not self.get("implicit_cf"):
                n_neg = min(self.get("complementset_factor") * int(sel.sum()),
                            n_u * n_i)
                neg_u = rng.integers(0, n_u, n_neg).astype(np.int32)
                neg_r = rng.integers(0, n_i, n_neg).astype(np.int32)
            U, V = sparse_als(u_idx.astype(np.int32), r_idx.astype(np.int32),
                              counts, n_u, n_i, rank,
                              self.get("reg_param"), self.get("max_iter"),
                              self.get("seed"),
                              implicit=self.get("implicit_cf"),
                              alpha=self.get("alpha"),
                              neg_u=neg_u, neg_r=neg_r,
                              neg_score=self.get("neg_score"),
                              pos_score=self.get("pos_score"))
            # standardisation stats over OBSERVED pairs only — a gather, not
            # a dense (n_u, n_i) matmul
            obs = np.einsum("nk,nk->n", U[u_idx], V[r_idx])
            mu, sd = float(obs.mean()), float(obs.std()) or 1.0
            factors[t] = {"users": users.tolist(), "ress": ress.tolist(),
                          "U": U, "V": V, "mean": mu, "std": sd}
        m = AccessAnomalyModel()
        m.set("factors", factors)
        for pcol in ("tenant_col", "user_col", "res_col"):
            m.set(pcol, self.get(pcol))
        return m


class AccessAnomalyModel(Model):
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    user_col = Param("user_col", "user column", "string", default="user")
    res_col = Param("res_col", "resource column", "string", default="res")
    output_col = Param("output_col", "anomaly score column", "string",
                       default="anomaly_score")
    factors = ComplexParam("factors", "per-tenant factor matrices")

    def _post_load(self):
        self._lookup_cache = None

    def _lookups(self, factors) -> Dict[str, Tuple[Dict, Dict]]:
        """Hash-map index lookups built once per tenant (round-1 weak item
        4: scoring did a Python list.index PER ROW — O(n*m)).  Keyed by the
        factors object so a ``set("factors", ...)`` invalidates the cache."""
        cached = getattr(self, "_lookup_cache", None)
        if cached is not None and cached[0] is factors:
            return cached[1]
        maps = {t: ({u: i for i, u in enumerate(f["users"])},
                    {r: i for i, r in enumerate(f["ress"])})
                for t, f in factors.items()}
        self._lookup_cache = (factors, maps)
        return maps

    def _transform(self, df: DataFrame) -> DataFrame:
        factors = self.get_or_fail("factors")
        lookups = self._lookups(factors)
        tc, uc, rc = self.get("tenant_col"), self.get("user_col"), self.get("res_col")

        def per_part(p):
            n = len(p[uc])
            out = np.zeros(n, np.float64)
            for i in range(n):
                t = str(p[tc][i]) if tc in p else "_"
                f = factors.get(t)
                if f is None:
                    out[i] = 0.0
                    continue
                umap, rmap = lookups[t]
                ui = umap.get(str(p[uc][i]))
                ri = rmap.get(str(p[rc][i]))
                if ui is None or ri is None:  # unseen user/resource: max anomaly
                    out[i] = 3.0
                    continue
                score = float(f["U"][ui] @ f["V"][ri])
                # higher score = more expected => anomaly = negative z
                out[i] = -(score - f["mean"]) / f["std"]
            return {**p, self.get("output_col"): out}

        return df.map_partitions(per_part)
