"""AccessAnomaly — anomalous user->resource access detection.

Reference: ``core/src/main/python/mmlspark/cyber/anomaly/
collaborative_filtering.py`` (988 LoC): per-tenant ALS collaborative
filtering over (user, resource) access counts, complement sampling of
unobserved pairs as implicit negatives, and score standardisation so higher
output = more anomalous.

TPU-native: the ALS alternating ridge solves are jitted batched linear
solves; scoring is a dense factor matmul.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, Model, Param)
from ..core.dataframe import _as_column


def _als(ratings: np.ndarray, mask: np.ndarray, rank: int, reg: float,
         iters: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Masked ALS via jitted alternating ridge solves."""
    import jax
    import jax.numpy as jnp

    n_u, n_i = ratings.shape
    rng = np.random.default_rng(seed)
    U = jnp.asarray(rng.normal(scale=0.1, size=(n_u, rank)).astype(np.float32))
    V = jnp.asarray(rng.normal(scale=0.1, size=(n_i, rank)).astype(np.float32))
    R = jnp.asarray(ratings, jnp.float32)
    M = jnp.asarray(mask, jnp.float32)

    @jax.jit
    def solve_side(F_other, R_side, M_side):
        # for each row r: (F^T diag(m) F + reg I)^-1 F^T diag(m) y
        def one(m_row, y_row):
            Fw = F_other * m_row[:, None]
            A = Fw.T @ F_other + reg * jnp.eye(rank)
            b = Fw.T @ y_row
            return jnp.linalg.solve(A, b)
        return jax.vmap(one)(M_side, R_side)

    for _ in range(iters):
        U = solve_side(V, R, M)
        V = solve_side(U, R.T, M.T)
    return np.asarray(U), np.asarray(V)


class ComplementAccessTransformer:
    """Sample unobserved (user, resource) pairs — the implicit negatives
    (reference ``ComplementAccessTransformer``)."""

    def __init__(self, tenant_col: str = "tenant", user_col: str = "user",
                 res_col: str = "res", complement_factor: int = 2, seed: int = 0):
        self.tenant_col, self.user_col, self.res_col = tenant_col, user_col, res_col
        self.factor = complement_factor
        self.seed = seed

    def transform(self, df: DataFrame) -> DataFrame:
        data = df.collect()
        rng = np.random.default_rng(self.seed)
        tc, uc, rc = self.tenant_col, self.user_col, self.res_col
        tenants = data[tc].astype(str) if tc in data else np.full(len(data[uc]), "_")
        rows = []
        for t in sorted(set(tenants.tolist())):
            sel = tenants == t
            users = sorted(set(data[uc][sel].astype(str).tolist()))
            ress = sorted(set(data[rc][sel].astype(str).tolist()))
            seen = set(zip(data[uc][sel].astype(str), data[rc][sel].astype(str)))
            want = min(self.factor * int(sel.sum()),
                       max(0, len(users) * len(ress) - len(seen)))
            tries = 0
            got = set()
            while len(got) < want and tries < want * 20:
                u = users[rng.integers(0, len(users))]
                r = ress[rng.integers(0, len(ress))]
                if (u, r) not in seen and (u, r) not in got:
                    got.add((u, r))
                tries += 1
            for u, r in sorted(got):
                rows.append({tc: t, uc: u, rc: r})
        return DataFrame.from_rows(rows)


class AccessAnomaly(Estimator):
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    user_col = Param("user_col", "user column", "string", default="user")
    res_col = Param("res_col", "resource column", "string", default="res")
    likelihood_col = Param("likelihood_col", "access count column (optional)",
                           "string", default=None)
    rank_param = Param("rank", "latent factor rank", "int", default=10)
    max_iter = Param("max_iter", "ALS iterations", "int", default=10)
    reg_param = Param("reg_param", "ridge regularization", "float", default=0.1)
    complementset_factor = Param("complementset_factor", "negatives per positive",
                                 "int", default=2)
    neg_score = Param("neg_score", "implicit negative target", "float", default=0.0)
    pos_score = Param("pos_score", "observed access target", "float", default=1.0)
    seed = Param("seed", "random seed", "int", default=0)

    def _fit(self, df: DataFrame) -> "AccessAnomalyModel":
        data = df.collect()
        tc = self.get("tenant_col")
        uc, rc = self.get("user_col"), self.get("res_col")
        tenants = data[tc].astype(str) if tc in data else np.full(len(data[uc]), "_")
        factors: Dict[str, Dict] = {}
        for t in sorted(set(tenants.tolist())):
            sel = tenants == t
            users, u_idx = np.unique(data[uc][sel].astype(str), return_inverse=True)
            ress, r_idx = np.unique(data[rc][sel].astype(str), return_inverse=True)
            n_u, n_i = len(users), len(ress)
            R = np.full((n_u, n_i), self.get("neg_score"), np.float32)
            lc = self.get("likelihood_col")
            vals = np.asarray(data[lc], np.float64)[sel] if lc and lc in data \
                else np.full(sel.sum(), self.get("pos_score"))
            R[u_idx, r_idx] = np.maximum(vals, self.get("pos_score"))
            # observed pairs + sampled complement get mass in the mask
            M = np.zeros((n_u, n_i), np.float32)
            M[u_idx, r_idx] = 1.0
            rng = np.random.default_rng(self.get("seed"))
            n_neg = min(self.get("complementset_factor") * int(sel.sum()), n_u * n_i)
            neg_u = rng.integers(0, n_u, n_neg)
            neg_r = rng.integers(0, n_i, n_neg)
            M[neg_u, neg_r] = np.maximum(M[neg_u, neg_r], 0.5)
            U, V = _als(R, M, min(self.get("rank"), min(n_u, n_i)),
                        self.get("reg_param"), self.get("max_iter"),
                        self.get("seed"))
            scores = (U @ V.T)
            obs = scores[u_idx, r_idx]
            mu, sd = float(obs.mean()), float(obs.std()) or 1.0
            factors[t] = {"users": users.tolist(), "ress": ress.tolist(),
                          "U": U, "V": V, "mean": mu, "std": sd}
        m = AccessAnomalyModel()
        m.set("factors", factors)
        for pcol in ("tenant_col", "user_col", "res_col"):
            m.set(pcol, self.get(pcol))
        return m


class AccessAnomalyModel(Model):
    tenant_col = Param("tenant_col", "tenant column", "string", default="tenant")
    user_col = Param("user_col", "user column", "string", default="user")
    res_col = Param("res_col", "resource column", "string", default="res")
    output_col = Param("output_col", "anomaly score column", "string",
                       default="anomaly_score")
    factors = ComplexParam("factors", "per-tenant factor matrices")

    def _transform(self, df: DataFrame) -> DataFrame:
        factors = self.get_or_fail("factors")
        tc, uc, rc = self.get("tenant_col"), self.get("user_col"), self.get("res_col")

        def per_part(p):
            n = len(p[uc])
            out = np.zeros(n, np.float64)
            for i in range(n):
                t = str(p[tc][i]) if tc in p else "_"
                f = factors.get(t)
                if f is None:
                    out[i] = 0.0
                    continue
                try:
                    ui = f["users"].index(str(p[uc][i]))
                    ri = f["ress"].index(str(p[rc][i]))
                    score = float(f["U"][ui] @ f["V"][ri])
                    # higher score = more expected => anomaly = negative z
                    out[i] = -(score - f["mean"]) / f["std"]
                except ValueError:  # unseen user/resource: max anomaly
                    out[i] = 3.0
            return {**p, self.get("output_col"): out}

        return df.map_partitions(per_part)
