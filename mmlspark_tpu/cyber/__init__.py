from .anomaly import AccessAnomaly, AccessAnomalyModel, ComplementAccessTransformer
from .feature import IdIndexer, IdIndexerModel, StandardScalarScaler, \
    StandardScalarScalerModel, LinearScalarScaler, LinearScalarScalerModel

__all__ = ["AccessAnomaly", "AccessAnomalyModel", "ComplementAccessTransformer",
           "IdIndexer", "IdIndexerModel", "StandardScalarScaler",
           "StandardScalarScalerModel", "LinearScalarScaler",
           "LinearScalarScalerModel"]
