from .tune import (TuneHyperparameters, TuneHyperparametersModel,
                   HyperparamBuilder, GridSpace, RandomSpace, RangeHyperParam,
                   DiscreteHyperParam, DefaultHyperparams)
from .best import FindBestModel, BestModel

__all__ = ["TuneHyperparameters", "TuneHyperparametersModel",
           "HyperparamBuilder", "GridSpace", "RandomSpace", "RangeHyperParam",
           "DiscreteHyperParam", "DefaultHyperparams", "FindBestModel",
           "BestModel"]
