"""FindBestModel — evaluate fitted models and keep the winner.

Reference: ``automl/FindBestModel.scala`` (``BestModel`` exposes the winning
transformer, evaluation results and ROC data).
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..core import ComplexParam, DataFrame, Estimator, Model, Param
from .tune import _metric_value


class FindBestModel(Estimator):
    models = ComplexParam("models", "fitted transformers to compare")
    evaluation_metric = Param("evaluation_metric", "metric name", "string",
                              default="accuracy")
    label_col = Param("label_col", "label column", "string", default="label")

    def _fit(self, df: DataFrame) -> "BestModel":
        models = self.get_or_fail("models")
        metric = self.get("evaluation_metric")
        scores = []
        larger_better = True
        for m in models:
            scored = m.transform(df)
            v, larger_better = _metric_value(scored, self.get("label_col"), metric)
            scores.append(v)
        best_i = int(np.argmax(scores) if larger_better else np.argmin(scores))
        out = BestModel()
        out.set("best_model", models[best_i])
        out.set("best_model_metrics", scores[best_i])
        out.set("all_model_metrics", scores)
        return out


class BestModel(Model):
    best_model = ComplexParam("best_model", "winning transformer")
    best_model_metrics = Param("best_model_metrics", "winning metric", "float")
    all_model_metrics = Param("all_model_metrics", "all metrics", "list")

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_fail("best_model").transform(df)

    def get_evaluation_results(self) -> List[float]:
        return self.get("all_model_metrics")
