"""Hyperparameter search.

Reference: ``automl/TuneHyperparameters.scala:144`` (parallel random/grid
search with train/validation split and unified metric evaluation) plus the
``HyperparamBuilder``/``ParamSpace``/``RandomSpace`` DSL (``ParamSpace.scala``)
and ``DefaultHyperparams``.
"""
from __future__ import annotations

import concurrent.futures
import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, Model, Param)
from ..train.metrics import classification_metrics, regression_metrics


class RangeHyperParam:
    def __init__(self, low, high, is_int: bool = False):
        self.low, self.high, self.is_int = low, high, is_int

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return int(round(v)) if self.is_int else float(v)

    def grid(self, n: int = 3):
        vals = np.linspace(self.low, self.high, n)
        return [int(round(v)) if self.is_int else float(v) for v in vals]


class DiscreteHyperParam:
    def __init__(self, values: Sequence):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self, n: int = 0):
        return list(self.values)


class HyperparamBuilder:
    """Reference HyperparamBuilder: accumulate (param, space) pairs."""

    def __init__(self):
        self._spaces: List[Tuple[str, Any]] = []

    def add_hyperparam(self, param_name: str, space) -> "HyperparamBuilder":
        self._spaces.append((param_name, space))
        return self

    def build(self):
        return list(self._spaces)


class GridSpace:
    def __init__(self, spaces, points_per_range: int = 3):
        self.spaces = spaces
        self.points = points_per_range

    def param_maps(self):
        names = [n for n, _ in self.spaces]
        grids = [s.grid(self.points) for _, s in self.spaces]
        for combo in itertools.product(*grids):
            yield dict(zip(names, combo))


class RandomSpace:
    def __init__(self, spaces, seed: int = 0):
        self.spaces = spaces
        self.rng = np.random.default_rng(seed)

    def param_maps(self):
        while True:
            yield {n: s.sample(self.rng) for n, s in self.spaces}


class DefaultHyperparams:
    """Reference DefaultHyperparams: sensible search spaces per learner."""

    @staticmethod
    def lightgbm_classifier():
        return HyperparamBuilder() \
            .add_hyperparam("num_leaves", DiscreteHyperParam([15, 31, 63])) \
            .add_hyperparam("learning_rate", RangeHyperParam(0.01, 0.3)) \
            .add_hyperparam("num_iterations", DiscreteHyperParam([50, 100])) \
            .build()

    @staticmethod
    def vw_classifier():
        return HyperparamBuilder() \
            .add_hyperparam("learning_rate", RangeHyperParam(0.05, 1.0)) \
            .add_hyperparam("num_passes", DiscreteHyperParam([1, 3, 5])) \
            .build()


def _metric_value(df: DataFrame, label_col: str, metric: str) -> Tuple[float, bool]:
    data = df.collect()
    y = np.asarray(data[label_col], np.float64)
    pred = np.asarray(data["prediction"], np.float64)
    cls = classification_metrics(y, pred)
    reg = regression_metrics(y, pred)
    table = {**cls, **reg}
    larger_better = metric not in ("mean_squared_error", "root_mean_squared_error",
                                   "mean_absolute_error")
    return float(table[metric]), larger_better


class TuneHyperparameters(Estimator):
    """Search over models x param spaces with parallel evaluation
    (reference fit :144 evaluates candidates on a thread pool)."""

    models = ComplexParam("models", "candidate estimators")
    param_space = ComplexParam("param_space", "GridSpace or RandomSpace")
    evaluation_metric = Param("evaluation_metric", "metric name", "string",
                              default="accuracy")
    number_of_runs = Param("number_of_runs", "candidates to evaluate (random "
                           "search)", "int", default=8)
    parallelism = Param("parallelism", "concurrent fits", "int", default=2)
    train_ratio = Param("train_ratio", "train fraction", "float", default=0.8)
    label_col = Param("label_col", "label column", "string", default="label")
    seed = Param("seed", "split seed", "int", default=0)

    def _fit(self, df: DataFrame) -> "TuneHyperparametersModel":
        models = self.get_or_fail("models")
        if not isinstance(models, list):
            models = [models]
        space = self.get_or_fail("param_space")
        metric = self.get("evaluation_metric")
        label_col = self.get("label_col")
        train, valid = df.random_split([self.get("train_ratio"),
                                        1 - self.get("train_ratio")],
                                       seed=self.get("seed"))

        gen = space.param_maps()
        if isinstance(space, GridSpace):
            candidates = [(m, pm) for m in models for pm in space.param_maps()]
        else:
            candidates = [(models[i % len(models)], next(gen))
                          for i in range(self.get("number_of_runs"))]

        def evaluate(cand):
            est, pm = cand
            est = est.copy()
            for k, v in pm.items():
                if k in type(est)._params:
                    est.set(k, v)
            model = est.fit(train)
            scored = model.transform(valid)
            value, larger_better = _metric_value(scored, label_col, metric)
            return model, pm, value, larger_better

        results = []
        with concurrent.futures.ThreadPoolExecutor(self.get("parallelism")) as ex:
            for res in ex.map(evaluate, candidates):
                results.append(res)
        larger_better = results[0][3]
        best = max(results, key=lambda r: r[2]) if larger_better else \
            min(results, key=lambda r: r[2])
        out = TuneHyperparametersModel()
        out.set("best_model", best[0])
        out.set("best_metric", best[2])
        out.set("best_params", best[1])
        out.set("all_metrics", [r[2] for r in results])
        return out


class TuneHyperparametersModel(Model):
    best_model = ComplexParam("best_model", "winning fitted model")
    best_metric = Param("best_metric", "winning metric value", "float")
    best_params = Param("best_params", "winning param map", "object")
    all_metrics = Param("all_metrics", "all candidate metrics", "list")

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_fail("best_model").transform(df)

    def get_best_model_info(self) -> str:
        return f"metric={self.get('best_metric')} params={self.get('best_params')}"
