"""RecommendationIndexer — string user/item ids to contiguous indices.

Reference: ``recommendation/RecommendationIndexer.scala`` (wraps two
StringIndexers so ALS/SAR consume integer ids).
"""
from __future__ import annotations

import numpy as np

from ..core import DataFrame, Estimator, Model, Param


class RecommendationIndexer(Estimator):
    user_input_col = Param("user_input_col", "raw user column", "string", default="user")
    user_output_col = Param("user_output_col", "indexed user column", "string", default="user_idx")
    item_input_col = Param("item_input_col", "raw item column", "string", default="item")
    item_output_col = Param("item_output_col", "indexed item column", "string", default="item_idx")
    rating_col = Param("rating_col", "rating column", "string", default="rating")

    def _fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        data = df.collect()
        users = sorted(set(str(v) for v in data[self.get("user_input_col")]))
        items = sorted(set(str(v) for v in data[self.get("item_input_col")]))
        m = RecommendationIndexerModel()
        for pcol in ("user_input_col", "user_output_col", "item_input_col",
                     "item_output_col", "rating_col"):
            m.set(pcol, self.get(pcol))
        m.set("user_vocab", users)
        m.set("item_vocab", items)
        return m


class RecommendationIndexerModel(Model):
    user_input_col = Param("user_input_col", "raw user column", "string", default="user")
    user_output_col = Param("user_output_col", "indexed user column", "string", default="user_idx")
    item_input_col = Param("item_input_col", "raw item column", "string", default="item")
    item_output_col = Param("item_output_col", "indexed item column", "string", default="item_idx")
    rating_col = Param("rating_col", "rating column", "string", default="rating")
    user_vocab = Param("user_vocab", "user values", "list")
    item_vocab = Param("item_vocab", "item values", "list")

    def _transform(self, df: DataFrame) -> DataFrame:
        u_map = {v: float(i) for i, v in enumerate(self.get("user_vocab"))}
        i_map = {v: float(i) for i, v in enumerate(self.get("item_vocab"))}
        uc, ic = self.get("user_input_col"), self.get("item_input_col")
        out = df.with_column(self.get("user_output_col"),
                             lambda p: np.asarray([u_map.get(str(v), -1.0) for v in p[uc]]))
        return out.with_column(self.get("item_output_col"),
                               lambda p: np.asarray([i_map.get(str(v), -1.0) for v in p[ic]]))

    def recover_user(self, idx: int):
        return self.get("user_vocab")[int(idx)]

    def recover_item(self, idx: int):
        return self.get("item_vocab")[int(idx)]
