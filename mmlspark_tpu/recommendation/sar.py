"""SAR — Smart Adaptive Recommendations.

Reference: ``recommendation/SAR.scala:36`` (item-item similarity via
cooccurrence / jaccard / lift with time-decayed user affinity) and
``SARModel.recommendForAllUsers`` (``SARModel.scala:53``; the distributed
score matrix multiply :106).

TPU-native: the item-item similarity and the affinity x similarity scoring
are dense matmuls on the MXU (jitted); the reference's Spark joins collapse
into index arrays.
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, Model, Param)
from ..core.dataframe import _as_column


class SAR(Estimator):
    user_col = Param("user_col", "user id column", "string", default="user")
    item_col = Param("item_col", "item id column", "string", default="item")
    rating_col = Param("rating_col", "rating column", "string", default="rating")
    time_col = Param("time_col", "event timestamp column (seconds)", "string", default=None)
    support_threshold = Param("support_threshold", "min cooccurrence", "int", default=4)
    similarity_function = Param("similarity_function", "jaccard|lift|cooccurrence",
                                "string", default="jaccard")
    time_decay_coeff = Param("time_decay_coeff", "half-life days", "float", default=30.0)
    start_time = Param("start_time", "reference timestamp (seconds)", "float", default=None)

    def _fit(self, df: DataFrame) -> "SARModel":
        data = df.collect()
        uc, ic, rc = self.get("user_col"), self.get("item_col"), self.get("rating_col")
        users_raw = data[uc]
        items_raw = data[ic]
        ratings = np.asarray(data[rc], np.float64) if rc in data else np.ones(len(users_raw))

        user_ids, u_idx = np.unique(users_raw.astype(str), return_inverse=True)
        item_ids, i_idx = np.unique(items_raw.astype(str), return_inverse=True)
        n_u, n_i = len(user_ids), len(item_ids)

        # time-decayed affinity (reference: exp2(-(t0 - t)/T))
        tc = self.get("time_col")
        if tc and tc in data:
            t = np.asarray(data[tc], np.float64)
            t0 = self.get("start_time") or float(t.max())
            half_life_s = self.get("time_decay_coeff") * 86400.0
            decay = np.power(2.0, -(t0 - t) / half_life_s)
        else:
            decay = np.ones(len(u_idx))
        affinity = np.zeros((n_u, n_i), np.float64)
        np.add.at(affinity, (u_idx, i_idx), ratings * decay)

        # item-item cooccurrence on the device (one matmul)
        seen = np.zeros((n_u, n_i), np.float32)
        seen[u_idx, i_idx] = 1.0
        import jax.numpy as jnp
        cooc = np.asarray(jnp.asarray(seen).T @ jnp.asarray(seen), np.float64)
        thresh = self.get("support_threshold")
        cooc = np.where(cooc >= thresh, cooc, 0.0)
        diag = np.diag(cooc).copy()
        sim_fn = self.get("similarity_function")
        if sim_fn == "cooccurrence":
            sim = cooc
        elif sim_fn == "lift":
            denom = np.outer(diag, diag)
            sim = np.divide(cooc, denom, out=np.zeros_like(cooc), where=denom > 0)
        else:  # jaccard
            denom = diag[:, None] + diag[None, :] - cooc
            sim = np.divide(cooc, denom, out=np.zeros_like(cooc), where=denom > 0)

        m = SARModel()
        m.set("user_ids", user_ids.tolist())
        m.set("item_ids", item_ids.tolist())
        m.set("affinity", affinity.astype(np.float32))
        m.set("similarity", sim.astype(np.float32))
        m.set("seen", seen)
        for pcol in ("user_col", "item_col", "rating_col"):
            m.set(pcol, self.get(pcol))
        return m


class SARModel(Model):
    user_col = Param("user_col", "user id column", "string", default="user")
    item_col = Param("item_col", "item id column", "string", default="item")
    rating_col = Param("rating_col", "rating column", "string", default="rating")
    affinity = ComplexParam("affinity", "user x item affinity")
    similarity = ComplexParam("similarity", "item x item similarity")
    seen = ComplexParam("seen", "user x item seen mask")
    user_ids = Param("user_ids", "user vocabulary", "list")
    item_ids = Param("item_ids", "item vocabulary", "list")

    def _scores(self) -> np.ndarray:
        """affinity @ similarity on the MXU (reference SARModel.scala:106)."""
        import jax.numpy as jnp
        A = jnp.asarray(self.get_or_fail("affinity"))
        S = jnp.asarray(self.get_or_fail("similarity"))
        return np.asarray(A @ S, np.float64)

    def recommend_for_all_users(self, num_items: int = 10,
                                remove_seen: bool = True) -> DataFrame:
        scores = self._scores()
        if remove_seen:
            scores = np.where(self.get_or_fail("seen") > 0, -np.inf, scores)
        top = np.argsort(-scores, axis=1)[:, :num_items]
        user_ids = self.get("user_ids")
        item_ids = np.asarray(self.get("item_ids"), dtype=object)
        recs = np.empty(len(user_ids), dtype=object)
        ratings = np.empty(len(user_ids), dtype=object)
        for u in range(len(user_ids)):
            items = top[u]
            valid = np.isfinite(scores[u, items])
            recs[u] = list(item_ids[items[valid]])
            ratings[u] = [float(s) for s in scores[u, items[valid]]]
        return DataFrame.from_dict({
            self.get("user_col"): _as_column(list(user_ids)),
            "recommendations": recs, "ratings": ratings})

    def _transform(self, df: DataFrame) -> DataFrame:
        """Score (user, item) pairs."""
        scores = self._scores()
        u_map = {v: i for i, v in enumerate(self.get("user_ids"))}
        i_map = {v: i for i, v in enumerate(self.get("item_ids"))}
        uc, ic = self.get("user_col"), self.get("item_col")

        def per_part(p):
            out = np.zeros(len(p[uc]), np.float64)
            for i in range(len(out)):
                u = u_map.get(str(p[uc][i]))
                it = i_map.get(str(p[ic][i]))
                out[i] = scores[u, it] if u is not None and it is not None else 0.0
            return {**p, "prediction": out}

        return df.map_partitions(per_part)
