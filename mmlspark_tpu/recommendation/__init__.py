from .sar import SAR, SARModel
from .indexer import RecommendationIndexer, RecommendationIndexerModel
from .ranking import (RankingAdapter, RankingAdapterModel, RankingEvaluator,
                      RankingTrainValidationSplit)

__all__ = ["SAR", "SARModel", "RecommendationIndexer",
           "RecommendationIndexerModel", "RankingAdapter",
           "RankingAdapterModel", "RankingEvaluator",
           "RankingTrainValidationSplit"]
