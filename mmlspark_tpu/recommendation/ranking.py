"""Ranking evaluation infrastructure.

Reference: ``recommendation/RankingAdapter.scala:69`` (wraps a recommender so
its per-user top-k output can be evaluated), ``RankingEvaluator`` (ndcgAt /
map / precisionAtK / recallAtK), ``RankingTrainValidationSplit.scala:25``
(per-user time/ratio splits :94).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, Evaluator, Model, Param)
from ..core.dataframe import _as_column


class RankingAdapter(Estimator):
    """Fit the wrapped recommender; transform emits per-user (recs, ground
    truth) for the evaluator."""
    recommender = ComplexParam("recommender", "underlying recommender estimator")
    k = Param("k", "recommendations per user", "int", default=10)
    user_col = Param("user_col", "user column", "string", default="user")
    item_col = Param("item_col", "item column", "string", default="item")
    rating_col = Param("rating_col", "rating column", "string", default="rating")

    def __init__(self, recommender=None, uid=None, **kwargs):
        super().__init__(uid)
        if recommender is not None:
            self.set("recommender", recommender)
        if kwargs:
            self.set_params(**kwargs)

    def _fit(self, df: DataFrame) -> "RankingAdapterModel":
        fitted = self.get_or_fail("recommender").fit(df)
        m = RankingAdapterModel()
        m.set("fitted", fitted)
        for pcol in ("k", "user_col", "item_col", "rating_col"):
            m.set(pcol, self.get(pcol))
        return m


class RankingAdapterModel(Model):
    fitted = ComplexParam("fitted", "fitted recommender")
    k = Param("k", "recommendations per user", "int", default=10)
    user_col = Param("user_col", "user column", "string", default="user")
    item_col = Param("item_col", "item column", "string", default="item")
    rating_col = Param("rating_col", "rating column", "string", default="rating")

    def _transform(self, df: DataFrame) -> DataFrame:
        """Emit one row per user: prediction = recommended items, label =
        ground-truth items (sorted by rating)."""
        fitted = self.get_or_fail("fitted")
        uc, ic, rc = self.get("user_col"), self.get("item_col"), self.get("rating_col")
        recs = fitted.recommend_for_all_users(self.get("k"), remove_seen=False)
        rec_map = {str(r[uc]): list(r["recommendations"]) for r in recs.iter_rows()}
        data = df.collect()
        truth: Dict[str, List] = {}
        for i in range(len(data[uc])):
            truth.setdefault(str(data[uc][i]), []).append(
                (float(data[rc][i]) if rc in data else 1.0, data[ic][i]))
        users = sorted(truth)
        pred_col = np.empty(len(users), dtype=object)
        label_col = np.empty(len(users), dtype=object)
        for i, u in enumerate(users):
            pred_col[i] = [str(x) for x in rec_map.get(u, [])]
            label_col[i] = [str(it) for _, it in sorted(truth[u], reverse=True,
                                                        key=lambda t: t[0])]
        return DataFrame.from_dict({self.get("user_col"): _as_column(users),
                                    "prediction": pred_col, "label": label_col})


class RankingEvaluator(Evaluator):
    k = Param("k", "cutoff", "int", default=10)
    metric_name = Param("metric_name", "ndcgAt|map|precisionAtk|recallAtK|fcp",
                        "string", default="ndcgAt")
    prediction_col = Param("prediction_col", "ranked prediction lists", "string",
                           default="prediction")
    label_col = Param("label_col", "ground-truth lists", "string", default="label")

    def evaluate(self, df: DataFrame) -> float:
        k = self.get("k")
        metric = self.get("metric_name")
        data = df.collect()
        preds = data[self.get("prediction_col")]
        labels = data[self.get("label_col")]
        vals = []
        for pred, truth in zip(preds, labels):
            pred = list(pred)[:k]
            truth_set = set(truth)
            if not truth_set:
                continue
            hits = [1.0 if p in truth_set else 0.0 for p in pred]
            if metric == "precisionAtk":
                vals.append(sum(hits) / k)
            elif metric == "recallAtK":
                vals.append(sum(hits) / len(truth_set))
            elif metric == "map":
                s, h = 0.0, 0
                for i, hit in enumerate(hits):
                    if hit:
                        h += 1
                        s += h / (i + 1)
                vals.append(s / min(len(truth_set), k))
            else:  # ndcgAt
                dcg = sum(h / np.log2(i + 2) for i, h in enumerate(hits))
                idcg = sum(1.0 / np.log2(i + 2) for i in range(min(len(truth_set), k)))
                vals.append(dcg / idcg if idcg > 0 else 0.0)
        return float(np.mean(vals)) if vals else 0.0


class RankingTrainValidationSplit(Estimator):
    """Per-user holdout split + fit + evaluate (reference :25, split :94)."""
    estimator = ComplexParam("estimator", "ranking adapter / recommender")
    evaluator = ComplexParam("evaluator", "RankingEvaluator")
    train_ratio = Param("train_ratio", "per-user train fraction", "float", default=0.75)
    user_col = Param("user_col", "user column", "string", default="user")
    item_col = Param("item_col", "item column", "string", default="item")
    min_ratings_per_user = Param("min_ratings_per_user", "drop sparse users", "int", default=1)
    seed = Param("seed", "shuffle seed", "int", default=0)

    def _fit(self, df: DataFrame):
        uc = self.get("user_col")
        rng = np.random.default_rng(self.get("seed"))
        whole = df.collect()
        n = len(whole[uc])
        by_user: Dict[str, List[int]] = {}
        for i in range(n):
            by_user.setdefault(str(whole[uc][i]), []).append(i)
        train_idx, test_idx = [], []
        ratio = self.get("train_ratio")
        for u, idxs in by_user.items():
            if len(idxs) < self.get("min_ratings_per_user"):
                continue
            idxs = list(idxs)
            rng.shuffle(idxs)
            cut = max(1, int(round(len(idxs) * ratio)))
            train_idx.extend(idxs[:cut])
            test_idx.extend(idxs[cut:])
        tr = DataFrame([{k: v[np.asarray(train_idx, int)] for k, v in whole.items()}])
        te = DataFrame([{k: v[np.asarray(test_idx, int)] for k, v in whole.items()}]) \
            if test_idx else tr
        model = self.get_or_fail("estimator").fit(tr)
        ev = self.get("evaluator")
        self.validation_metrics = [ev.evaluate(model.transform(te))] if ev else []
        return model
