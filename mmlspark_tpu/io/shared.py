"""Shared per-process lazy singletons.

Reference: ``io/http/SharedVariable.scala:18,:37`` — lazily-constructed
objects shared across tasks in one executor JVM (used for non-serializable
state captured in closures: clients, native handles, servers).  Here the
scope is the executor process.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, Optional, TypeVar

T = TypeVar("T")


class SharedVariable(Generic[T]):
    """Lazily constructed, process-shared value."""

    def __init__(self, factory: Callable[[], T]):
        self._factory = factory
        self._lock = threading.Lock()
        self._value: Optional[T] = None
        self._built = False

    def get(self) -> T:
        if not self._built:
            with self._lock:
                if not self._built:
                    self._value = self._factory()
                    self._built = True
        return self._value


class SharedSingleton:
    """Keyed process-wide singletons (reference SharedSingleton:37 keyed by
    constructor; used by LightGBM SharedState per executor)."""

    _instances: Dict[str, SharedVariable] = {}
    _lock = threading.Lock()

    @classmethod
    def get_or_create(cls, key: str, factory: Callable[[], T]) -> T:
        with cls._lock:
            if key not in cls._instances:
                cls._instances[key] = SharedVariable(factory)
        return cls._instances[key].get()

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instances.clear()
