"""Request/response parsers for the HTTP stack.

Reference: ``io/http/Parsers.scala`` (HTTPInputParser / JSONOutputParser /
CustomInputParser / CustomOutputParser).  These are the named building blocks
``SimpleHTTPTransformer`` composes; exposed here with the reference's names
so pipelines can declare parsing stages explicitly.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Optional

import numpy as np

from ..core import DataFrame, HasInputCol, HasOutputCol, Param, Transformer
from ..core.params import ComplexParam
from .http import HTTPRequestData, HTTPResponseData, RESPONSE_BINDING


class JSONInputParser(Transformer, HasInputCol, HasOutputCol):
    """Cell -> HTTPRequestData POSTing the cell as JSON (reference
    JSONInputParser)."""
    url = Param("url", "target endpoint", "string")
    method = Param("method", "HTTP method", "string", default="POST")
    headers = Param("headers", "extra headers", "object", default=None)

    def _transform(self, df: DataFrame) -> DataFrame:
        url = self.get_or_fail("url")
        headers = self.get("headers") or {}
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                out[i] = None if v is None else \
                    HTTPRequestData.post_json(url, v, headers)
            return {**p, out_col: out}

        return df.map_partitions(per_part)


class JSONOutputParser(Transformer, HasInputCol, HasOutputCol):
    """HTTPResponseData -> parsed JSON cell (reference JSONOutputParser)."""

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                if v is None:
                    out[i] = None
                    continue
                resp = v if isinstance(v, HTTPResponseData) else \
                    RESPONSE_BINDING._decode(HTTPResponseData, v)
                try:
                    out[i] = resp.json()
                except (ValueError, AttributeError):
                    out[i] = None
            return {**p, out_col: out}

        return df.map_partitions(per_part)


class CustomInputParser(Transformer, HasInputCol, HasOutputCol):
    """Cell -> HTTPRequestData via a user function (reference CustomInputParser)."""
    udf = ComplexParam("udf", "cell -> HTTPRequestData function")

    def _transform(self, df: DataFrame) -> DataFrame:
        fn: Callable = self.get_or_fail("udf")
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                out[i] = None if v is None else fn(v)
            return {**p, out_col: out}

        return df.map_partitions(per_part)


class CustomOutputParser(Transformer, HasInputCol, HasOutputCol):
    """HTTPResponseData -> cell via a user function (reference CustomOutputParser)."""
    udf = ComplexParam("udf", "HTTPResponseData -> cell function")

    def _transform(self, df: DataFrame) -> DataFrame:
        fn: Callable = self.get_or_fail("udf")
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                if v is None:
                    out[i] = None
                    continue
                resp = v if isinstance(v, HTTPResponseData) else \
                    RESPONSE_BINDING._decode(HTTPResponseData, v)
                out[i] = fn(resp)
            return {**p, out_col: out}

        return df.map_partitions(per_part)
