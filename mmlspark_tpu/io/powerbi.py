"""PowerBI writer — POST frames to a PowerBI streaming dataset.

Reference: ``core/.../io/powerbi/PowerBIWriter.scala:27-110`` (batch +
streaming POST through the HTTP transformer stack).
"""
from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ..core import DataFrame
from ..io.http import AsyncHTTPClient, HTTPRequestData


def write(df: DataFrame, url: str, batch_size: int = 100,
          concurrency: int = 4) -> List[int]:
    """POST rows in batches to the PowerBI push URL; returns status codes."""
    client = AsyncHTTPClient(concurrency=concurrency)
    rows = []
    for r in df.iter_rows():
        rows.append({k: (v.tolist() if isinstance(v, np.ndarray) else
                         v.item() if isinstance(v, (np.floating, np.integer)) else v)
                     for k, v in r.items()})
    reqs = [HTTPRequestData.post_json(url, rows[s:s + batch_size])
            for s in range(0, len(rows), batch_size)]
    resps = client.send_all(reqs)
    return [r.status_code if r else 0 for r in resps]


def stream(source_df_fn, url: str, interval_s: float = 1.0, max_batches: int = 0):
    """Streaming variant: poll source_df_fn() for new frames and push them.
    Returns a stop() handle (reference PowerBIWriter.stream)."""
    import threading

    stop_evt = threading.Event()

    def loop():
        count = 0
        while not stop_evt.is_set():
            df = source_df_fn()
            if df is not None and df.count():
                write(df, url)
            count += 1
            if max_batches and count >= max_batches:
                break
            stop_evt.wait(interval_s)

    t = threading.Thread(target=loop, daemon=True)
    t.start()

    def stop() -> None:
        stop_evt.set()
        # the loop wakes within one interval; join so callers observe the
        # final push complete instead of racing it into teardown
        t.join(timeout=interval_s + 5.0)

    return stop
