"""HTTP-on-frame — full HTTP request/response as typed columns.

Reference: ``core/.../io/http/`` —
- ``HTTPSchema.scala`` (358 LoC): HTTPRequestData/HTTPResponseData as Spark
  StructTypes via SparkBindings;
- ``Clients.scala:12,48``: sync + async clients, bounded concurrency;
- ``HTTPClients.scala:74-156``: ``sendWithRetries`` + advanced throttling;
- ``HTTPTransformer.scala:111`` / ``SimpleHTTPTransformer.scala:64``.

Here requests ride as dataclass cells in object columns (``Binding`` codec);
the async client is a bounded thread pool (Python's analogue of the
reference's Future pool) with exponential-backoff retries honoring
Retry-After.

Resilience (utils/resilience.py): clients optionally share a
``CircuitBreaker`` (open circuit -> synthetic 503 without touching the
network), and every retry loop is clipped to the ambient ``Deadline`` so a
caller's budget bounds the whole fan-out, not just a single attempt.  The
raw exchange is an injectable ``transport`` so the chaos harness
(testing/chaos.py) injects latency/errors/storms deterministically.
"""
from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import (Binding, DataFrame, HasInputCol, HasOutputCol, Param,
                    Transformer)
from ..core.schema import ColumnType
from ..observability.tracing import (TRACE_HEADER, TRACEPARENT_HEADER,
                                     current_span, current_trace_id,
                                     format_traceparent)
from ..stages.minibatch import FixedMiniBatchTransformer, FlattenBatch
from ..utils.resilience import CircuitBreaker, Deadline, current_deadline


@dataclasses.dataclass
class HTTPRequestData:
    """Reference HTTPSchema request struct."""
    url: str
    method: str = "GET"
    headers: Optional[Dict[str, str]] = None
    entity: Optional[bytes] = None

    @staticmethod
    def post_json(url: str, payload: Any, headers: Optional[Dict[str, str]] = None):
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        return HTTPRequestData(url=url, method="POST", headers=h,
                               entity=json.dumps(payload).encode())


@dataclasses.dataclass
class HTTPResponseData:
    """Reference HTTPSchema response struct."""
    status_code: int
    reason: str = ""
    headers: Optional[Dict[str, str]] = None
    entity: Optional[bytes] = None

    def json(self) -> Any:
        return json.loads(self.entity.decode()) if self.entity else None


REQUEST_BINDING = Binding(HTTPRequestData)
RESPONSE_BINDING = Binding(HTTPResponseData)


def _urllib_transport(req: HTTPRequestData, timeout_s: float) -> HTTPResponseData:
    """One raw exchange.  HTTP error statuses come back as responses (not
    exceptions); transport-level failures (refused, reset, DNS) raise."""
    try:
        r = urllib.request.Request(req.url, data=req.entity, method=req.method,
                                   headers=dict(req.headers or {}))
        with urllib.request.urlopen(r, timeout=timeout_s) as resp:
            return HTTPResponseData(
                status_code=resp.status, reason=getattr(resp, "reason", ""),
                headers=dict(resp.headers), entity=resp.read())
    except urllib.error.HTTPError as e:
        body = e.read() if hasattr(e, "read") else b""
        return HTTPResponseData(status_code=e.code, reason=str(e.reason),
                                headers=dict(e.headers or {}), entity=body)


def _with_trace_header(req: HTTPRequestData,
                       trace_id: Optional[str] = None) -> HTTPRequestData:
    """Copy-on-write trace-id injection: the ambient span's trace id (or an
    explicit one — thread pools don't inherit the contextvar) rides
    ``X-MMLSpark-Trace-Id`` AND a W3C ``traceparent`` (PR 4 follow-up: an
    external frontend that only speaks Trace Context still joins the trace)
    so worker-side spans join the caller's trace.  An explicit header
    already on the request wins; the caller's request object is never
    mutated."""
    if req.headers and TRACE_HEADER in req.headers:
        # explicit legacy header wins for the trace id, but the W3C pair
        # must still ride next to it (a W3C-only downstream would start a
        # disconnected trace otherwise)
        if TRACEPARENT_HEADER in req.headers:
            return req
        headers = dict(req.headers)
        span = current_span()
        headers[TRACEPARENT_HEADER] = format_traceparent(
            headers[TRACE_HEADER], span.span_id if span is not None else None)
        return dataclasses.replace(req, headers=headers)
    tid = trace_id or current_trace_id()
    if tid is None:
        return req
    headers = dict(req.headers or {})
    headers[TRACE_HEADER] = tid
    if TRACEPARENT_HEADER not in headers:
        span = current_span()
        headers[TRACEPARENT_HEADER] = format_traceparent(
            tid, span.span_id if span is not None else None)
    return dataclasses.replace(req, headers=headers)


def circuit_open_response(retry_after_s: float) -> HTTPResponseData:
    """Synthetic 503 emitted when a breaker rejects without a network call."""
    return HTTPResponseData(
        status_code=503, reason="circuit open",
        headers={"Retry-After": str(max(0, int(retry_after_s)) or 1),
                 "X-Circuit-Open": "1"})


class HTTPClient:
    """Single-threaded client with retries (reference SingleThreadedHTTPClient
    + HandlingUtils.sendWithRetries).

    ``breaker`` (shared CircuitBreaker): 5xx/transport failures feed it; an
    open circuit short-circuits to a synthetic 503.  The ambient
    ``deadline_scope`` (or an explicit ``deadline=``) clips every attempt
    timeout and backoff sleep to the caller's remaining budget — retries
    never overshoot it.  ``transport``/``clock``/``sleep`` are injectable
    for the deterministic chaos harness.
    """

    def __init__(self, retries: int = 3, backoff_ms: Optional[List[int]] = None,
                 timeout_s: float = 60.0,
                 breaker: Optional[CircuitBreaker] = None,
                 transport: Optional[Callable[[HTTPRequestData, float],
                                              HTTPResponseData]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.retries = retries
        self.backoffs = backoff_ms or [100, 500, 1000]
        self.timeout_s = timeout_s
        self.breaker = breaker
        self.transport = transport or _urllib_transport
        self.clock = clock
        self.sleep = sleep

    def _sleep_budgeted(self, seconds: float, deadline: Optional[Deadline]) -> bool:
        """Sleep, clipped to the remaining budget.  False if the budget is
        already gone (caller should stop retrying)."""
        if deadline is not None:
            remaining = deadline.remaining()
            if remaining <= 0:
                return False
            seconds = min(seconds, remaining)
        self.sleep(seconds)
        return True

    def send(self, req: HTTPRequestData,
             deadline: Optional[Deadline] = None,
             trace_id: Optional[str] = None) -> HTTPResponseData:
        deadline = deadline or current_deadline()
        req = _with_trace_header(req, trace_id)
        last_err: Optional[HTTPResponseData] = None
        for attempt in range(self.retries + 1):
            # deadline check MUST precede breaker admission: allow() may
            # consume a half-open probe slot, and an early return here would
            # leak it (the breaker would stay half-open forever)
            timeout_s = self.timeout_s
            if deadline is not None:
                if deadline.expired():
                    return last_err or HTTPResponseData(
                        status_code=0, reason="deadline exceeded before attempt")
                timeout_s = deadline.clip(self.timeout_s)
            if self.breaker is not None and not self.breaker.allow():
                return last_err or circuit_open_response(
                    self.breaker.retry_after_s())
            try:
                resp = self.transport(req, timeout_s)
            except Exception as e:  # noqa: BLE001 — network errors retried
                last_err = HTTPResponseData(status_code=0, reason=str(e))
                if self.breaker is not None:
                    self.breaker.record_failure()
            else:
                last_err = resp
                code = resp.status_code
                # 429 is the dependency throttling us, not failing — it
                # retries but never trips the breaker
                if self.breaker is not None:
                    if code == 0 or code >= 500:
                        self.breaker.record_failure()
                    else:
                        self.breaker.record_success()
                if 0 < code < 500 and code != 429:
                    return resp  # 2xx/3xx/4xx: done
                # throttling/outage: honor Retry-After (reference advanced
                # handler), else fall through to exponential backoff
                retry_after = (resp.headers or {}).get("Retry-After")
                if retry_after and attempt < self.retries:
                    try:  # RFC 7231 also allows an HTTP-date here
                        wait_s = min(float(retry_after), 30.0)
                    except ValueError:
                        wait_s = None
                    if wait_s is not None:
                        if not self._sleep_budgeted(wait_s, deadline):
                            return last_err
                        continue
            if attempt < self.retries:
                if not self._sleep_budgeted(
                        self.backoffs[min(attempt, len(self.backoffs) - 1)] / 1000.0,
                        deadline):
                    return last_err
        return last_err

    def send_json(self, url: str, payload: Any,
                  headers: Optional[Dict[str, str]] = None,
                  deadline: Optional[Deadline] = None,
                  trace_id: Optional[str] = None) -> HTTPResponseData:
        """POST ``payload`` as JSON through the full resilient path
        (breaker, deadline clipping, retries).  The one-call shape internal
        clients want — the observability span exporter POSTs OTLP batches
        through here so graft-lint RES coverage holds by construction."""
        return self.send(HTTPRequestData.post_json(url, payload, headers),
                         deadline=deadline, trace_id=trace_id)


class AsyncHTTPClient(HTTPClient):
    """Bounded-concurrency async client (reference AsyncClient, Clients.scala:48).
    The ambient deadline is captured on the submitting thread and handed to
    every pooled ``send`` (contextvars don't cross thread-pool boundaries)."""

    def __init__(self, concurrency: int = 8, **kw):
        super().__init__(**kw)
        self.concurrency = concurrency

    def send_all(self, reqs: List[Optional[HTTPRequestData]]) -> List[Optional[HTTPResponseData]]:
        deadline = current_deadline()
        trace_id = current_trace_id()  # contextvars don't cross the pool
        out: List[Optional[HTTPResponseData]] = [None] * len(reqs)
        with concurrent.futures.ThreadPoolExecutor(self.concurrency) as ex:
            futs = {ex.submit(self.send, r, deadline, trace_id): i
                    for i, r in enumerate(reqs) if r is not None}
            for f in concurrent.futures.as_completed(futs):
                out[futs[f]] = f.result()
        return out


class HTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """Column of HTTPRequestData -> column of HTTPResponseData
    (reference HTTPTransformer.transform:111)."""

    concurrency = Param("concurrency", "max in-flight requests per partition", "int", default=8)
    concurrent_timeout = Param("concurrent_timeout", "request timeout seconds", "float", default=60.0)
    handler = Param("handler", "custom (client, request)->response handler", "object")
    breaker = Param("breaker", "shared CircuitBreaker guarding the endpoint",
                    "object", default=None)

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    def _client(self) -> AsyncHTTPClient:
        return AsyncHTTPClient(concurrency=self.get("concurrency"),
                               timeout_s=self.get("concurrent_timeout"),
                               breaker=self.get("breaker"))

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col, out_col = self.get_or_fail("input_col"), self.get_or_fail("output_col")
        handler = self.get("handler")

        def per_part(p):
            client = self._client()
            reqs = []
            for v in p[in_col]:
                if v is None:
                    reqs.append(None)
                elif isinstance(v, HTTPRequestData):
                    reqs.append(v)
                else:
                    reqs.append(REQUEST_BINDING._decode(HTTPRequestData, v))
            if handler is not None:
                resps = [None if r is None else handler(client, r) for r in reqs]
            else:
                resps = client.send_all(reqs)
            out = np.empty(len(reqs), dtype=object)
            for i, r in enumerate(resps):
                out[i] = None if r is None else dataclasses.asdict(r)
            return {**p, out_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("input_col"))
        return schema.add(self.get_or_fail("output_col"), ColumnType.STRUCT)


class SimpleHTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """JSON-in/JSON-out convenience (reference SimpleHTTPTransformer.scala:64):
    input column -> request (via input_parser), response -> parsed output
    column, optional error column and minibatching."""

    url = Param("url", "endpoint for the default JSON POST parser", "string")
    input_parser = Param("input_parser", "fn(cell) -> HTTPRequestData", "object")
    output_parser = Param("output_parser", "fn(HTTPResponseData) -> cell", "object")
    error_col = Param("error_col", "column for failed-request info", "string", default="errors")
    max_batch_size = Param("max_batch_size", "minibatch rows per request (0=off)", "int", default=0)
    concurrency = Param("concurrency", "max in-flight requests", "int", default=8)
    headers = Param("headers", "extra headers dict", "object", default=None)
    breaker = Param("breaker", "shared CircuitBreaker guarding the endpoint",
                    "object", default=None)

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")
        err_col = self.get("error_col")
        url = self.get("url")
        in_parser = self.get("input_parser") or \
            (lambda cell: HTTPRequestData.post_json(url, cell, self.get("headers")))
        out_parser = self.get("output_parser") or (lambda resp: resp.json())
        batching = self.get("max_batch_size") or 0

        work = df
        if batching > 1:
            work = FixedMiniBatchTransformer().set("batch_size", batching).transform(work)

        def per_part(p):
            client = AsyncHTTPClient(concurrency=self.get("concurrency"),
                                     breaker=self.get("breaker"))
            cells = p[in_col]
            if batching > 1:
                reqs = [in_parser(list(c)) for c in cells]
            else:
                reqs = [None if c is None else in_parser(c) for c in cells]
            resps = client.send_all(reqs)
            out = np.empty(len(cells), dtype=object)
            errs = np.empty(len(cells), dtype=object)
            for i, r in enumerate(resps):
                if r is None:
                    out[i], errs[i] = None, None
                elif 200 <= r.status_code < 300:
                    try:
                        out[i], errs[i] = out_parser(r), None
                    except Exception as e:  # noqa: BLE001
                        out[i], errs[i] = None, f"parse error: {e}"
                else:
                    out[i] = None
                    errs[i] = {"status_code": r.status_code, "reason": r.reason}
                if batching > 1:
                    # cells must be per-row sequences so FlattenBatch can
                    # explode them alongside the original batched columns
                    m = len(cells[i])
                    if not isinstance(out[i], (list, np.ndarray)):
                        out[i] = [out[i]] * m
                    errs[i] = [errs[i]] * m
            res = {**p, out_col: out}
            if err_col:
                res[err_col] = errs
            return res

        result = DataFrame(
            [per_part(pp) for pp in work.partitions])
        if batching > 1:
            result = FlattenBatch().transform(result)
        return result

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("input_col"))
        s = schema.add(self.get_or_fail("output_col"), ColumnType.STRUCT)
        if self.get("error_col"):
            s = s.add(self.get("error_col"), ColumnType.STRUCT)
        return s
