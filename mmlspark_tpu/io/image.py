"""Image IO — decode image files/bytes into array columns.

Reference: ``PatchedImageFileFormat`` (Spark image source) + ``ImageUtils``
(``io/image/ImageUtils.scala``).  Decode stays host-side (PIL); the decoded
NHWC arrays feed ``ops.image`` / ``dl.ImageFeaturizer`` on device.
"""
from __future__ import annotations

import io as _io
from typing import Optional

import numpy as np

from ..core import DataFrame
from .binary import read_binary_files


def decode_image(data: bytes, channels: int = 3) -> Optional[np.ndarray]:
    try:
        from PIL import Image
        img = Image.open(_io.BytesIO(data))
        img = img.convert("RGB" if channels == 3 else "L")
        return np.asarray(img, dtype=np.uint8)
    except Exception:  # noqa: BLE001 — unreadable images become None
        return None


def read_images(path: str, pattern: str = "*", recursive: bool = True,
                num_partitions: int = 1, drop_invalid: bool = True) -> DataFrame:
    """Directory -> frame with (path, image) columns; image is HWC uint8."""
    df = read_binary_files(path, pattern, recursive, num_partitions)
    def per_part(p):
        imgs = np.empty(len(p["path"]), dtype=object)
        for i, b in enumerate(p["bytes"]):
            imgs[i] = decode_image(b)
        return {"path": p["path"], "image": imgs}
    out = df.map_partitions(per_part)
    if drop_invalid:
        out = out.filter(lambda p: np.asarray([v is not None for v in p["image"]]))
    return out


def images_to_bytes_column(df: DataFrame, image_col: str = "image",
                           fmt: str = "PNG", out_col: str = "bytes") -> DataFrame:
    from PIL import Image

    def per_part(p):
        out = np.empty(len(p[image_col]), dtype=object)
        for i, arr in enumerate(p[image_col]):
            buf = _io.BytesIO()
            Image.fromarray(np.asarray(arr, np.uint8)).save(buf, fmt)
            out[i] = buf.getvalue()
        return {**p, out_col: out}

    return df.map_partitions(per_part)
