"""Out-of-core chunked datasets — host-RAM shards streamed into device tiles.

The ROADMAP's billion-row item names the shape (Snap ML, arxiv 1803.06333):
a hierarchy of out-of-core host RAM -> device HBM *tiles* with asynchronous
prefetch that overlaps the host->device transfer of tile ``k+1`` with the
compute on tile ``k`` — classic double buffering, lifted from the kernel
level (where the Pallas guide applies it to VMEM) to the host/HBM seam.

Two pieces:

- :class:`ChunkedDataset` — row-range geometry over host arrays with a
  STATIC tile shape (every tile ships ``(tile_rows, ...)``, the last one
  zero-padded), so every per-tile jitted program compiles ONCE and the
  whole stream replays through a single executable signature.  The tile
  size resolves from an explicit ``tile_rows``, a ``memory_budget_bytes``
  device budget (two tiles must fit — one training, one in flight), or the
  ``MMLSPARK_TPU_TILE_ROWS`` env override.
- :class:`TilePrefetcher` — ONE background worker thread runs ``load_fn``
  (typically :func:`mmlspark_tpu.observability.compute.device_put`, so the
  transfer counters see every byte) one tile AHEAD of the consumer; a
  token semaphore caps the pipeline at exactly two live tiles (double
  buffering, not unbounded readahead).  The seam is instrumented:
  ``mmlspark_prefetch_wait_seconds`` books the time the consumer BLOCKED
  waiting for a tile (transfer the compute could not hide) and
  ``mmlspark_tile_compute_seconds`` books the consumer's per-tile compute
  time, so overlap efficiency is a first-class /metrics observation
  instead of a guess.
"""
from __future__ import annotations

import os
import queue
import threading
import time
import warnings
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from ..observability.metrics import MetricsRegistry, get_registry
from ..utils.resilience import current_deadline, is_transient_io

__all__ = ["ChunkedDataset", "TilePrefetcher", "resolve_tile_rows",
           "pad_tile", "TILE_ROWS_ENV"]

#: env override for the tile row count (beats tile_rows/memory budget)
TILE_ROWS_ENV = "MMLSPARK_TPU_TILE_ROWS"

#: floor on resolved tile sizes: tiles below this waste every dispatch on
#: fixed per-call overhead (and XLA padding) for no memory relief
MIN_TILE_ROWS = 256


def resolve_tile_rows(n_rows: int, bytes_per_row: int,
                      tile_rows: Optional[int] = None,
                      memory_budget_bytes: Optional[int] = None,
                      min_tile_rows: int = MIN_TILE_ROWS) -> int:
    """Static tile row count for an ``n_rows`` dataset.

    Priority: ``MMLSPARK_TPU_TILE_ROWS`` env > explicit ``tile_rows`` >
    ``memory_budget_bytes`` (TWO tiles must fit the budget — the training
    tile plus the one in flight behind it) > the whole dataset (one tile,
    the in-memory degenerate case).
    """
    env = os.environ.get(TILE_ROWS_ENV, "").strip()
    if env:
        return max(1, min(int(env), n_rows))
    if tile_rows is not None:
        if tile_rows < 1:
            raise ValueError(f"tile_rows must be >= 1, got {tile_rows}")
        return min(int(tile_rows), n_rows)
    if memory_budget_bytes is not None:
        per_tile = max(1, int(memory_budget_bytes) // 2)
        rows = per_tile // max(1, int(bytes_per_row))
        if rows < 1:
            raise ValueError(
                f"memory_budget_bytes={memory_budget_bytes} cannot hold two "
                f"tiles of even one {bytes_per_row}-byte row")
        if rows < min_tile_rows:
            # the floor wins (tiles below it waste every dispatch), but the
            # caller asked for a budget the floored tiles EXCEED — say so
            # instead of silently setting up the OOM the knob exists to
            # prevent
            warnings.warn(
                f"memory_budget_bytes={memory_budget_bytes} resolves to "
                f"{rows} rows/tile, below the {min_tile_rows}-row floor; "
                f"clamping to the floor makes the two live tiles hold "
                f"~{2 * min_tile_rows * bytes_per_row} bytes, exceeding the "
                "budget", RuntimeWarning, stacklevel=2)
        return min(max(rows, min_tile_rows), n_rows)
    return n_rows


def pad_tile(arr: np.ndarray, lo: int, hi: int, tile_rows: int,
             fill=0) -> np.ndarray:
    """Static-shape tile view of ``arr[lo:hi]``: rows past ``hi`` are
    ``fill`` so every tile ships the same ``(tile_rows, ...)`` shape (one
    jit signature for the whole stream).  Full tiles return the raw slice
    (no copy)."""
    view = arr[lo:hi]
    if hi - lo == tile_rows:
        return view
    out = np.full((tile_rows,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: hi - lo] = view
    return out


class ChunkedDataset:
    """Row-shard geometry + host storage for out-of-core streaming.

    Holds host-resident row-aligned arrays (``X`` and any extras added via
    :meth:`add_column`) and exposes static-shape padded tiles.  The arrays
    stay contiguous host memory (the closest a CPU process gets to pinned
    buffers); nothing here touches the device — :meth:`prefetch` hands
    per-tile host pytrees to a :class:`TilePrefetcher` whose ``load_fn``
    performs the instrumented ``device_put``.
    """

    def __init__(self, X: np.ndarray, y: Optional[np.ndarray] = None,
                 sample_weight: Optional[np.ndarray] = None, *,
                 tile_rows: Optional[int] = None,
                 memory_budget_bytes: Optional[int] = None,
                 bytes_per_row: Optional[int] = None):
        X = np.ascontiguousarray(X)
        self.n_rows, self.num_features = X.shape[0], int(np.prod(X.shape[1:]))
        self.columns: Dict[str, np.ndarray] = {"X": X}
        if y is not None:
            self.add_column("y", y)
        if sample_weight is not None:
            self.add_column("w", sample_weight)
        if bytes_per_row is None:
            # the budget covers what a training tile actually holds on
            # device: the feature tile plus f32 grad/hess/label/weight rows
            bytes_per_row = X.dtype.itemsize * self.num_features + 16
        self.bytes_per_row = int(bytes_per_row)
        self.tile_rows = resolve_tile_rows(
            self.n_rows, self.bytes_per_row, tile_rows, memory_budget_bytes)
        self.memory_budget_bytes = memory_budget_bytes

    # ------------------------------------------------------------- geometry
    @property
    def X(self) -> np.ndarray:
        return self.columns["X"]

    @property
    def num_tiles(self) -> int:
        return -(-self.n_rows // self.tile_rows)

    def add_column(self, name: str, arr: np.ndarray) -> "ChunkedDataset":
        arr = np.ascontiguousarray(arr)
        if arr.shape[0] != self.n_rows:
            raise ValueError(f"column {name!r} has {arr.shape[0]} rows, "
                             f"dataset has {self.n_rows}")
        self.columns[name] = arr
        return self

    def tile_slice(self, i: int) -> Tuple[int, int]:
        if not 0 <= i < self.num_tiles:
            raise IndexError(f"tile {i} out of range [0, {self.num_tiles})")
        lo = i * self.tile_rows
        return lo, min(lo + self.tile_rows, self.n_rows)

    def tile_valid_rows(self, i: int) -> int:
        lo, hi = self.tile_slice(i)
        return hi - lo

    def tile(self, i: int, names: Sequence[str],
             fill: Dict[str, Any] = ()) -> Dict[str, np.ndarray]:
        """Padded static-shape host tile of the named columns."""
        lo, hi = self.tile_slice(i)
        fill = dict(fill or {})
        return {nm: pad_tile(self.columns[nm], lo, hi, self.tile_rows,
                             fill.get(nm, 0)) for nm in names}

    # ------------------------------------------------------------- streaming
    def prefetch(self, make_tile: Callable[[int, int, int], Any],
                 load_fn: Callable[[Any], Any], *,
                 site: str = "io.chunked",
                 clock: Optional[Callable[[], float]] = None,
                 registry: Optional[MetricsRegistry] = None
                 ) -> "TilePrefetcher":
        """Double-buffered tile stream: ``make_tile(i, lo, hi)`` builds the
        host payload and ``load_fn`` places it (both run on the worker
        thread, overlapped with the consumer's compute)."""
        def _load(i: int):
            lo, hi = self.tile_slice(i)
            return load_fn(make_tile(i, lo, hi))

        return TilePrefetcher(range(self.num_tiles), _load, site=site,
                              clock=clock, registry=registry)


class TilePrefetcher:
    """Background loader streaming ``load_fn(item)`` one step ahead.

    Exactly double-buffered: a token semaphore lets the worker start
    loading tile ``k+1`` only once the consumer has TAKEN tile ``k`` —
    at most two tiles are ever materialized on the device (one training,
    one in flight), which is the memory contract the tile-size budget is
    computed against.

    Instrumentation (both labelled by ``site``):

    - ``mmlspark_prefetch_wait_seconds`` — consumer time blocked waiting
      for the next tile.  Zero when compute fully hides the transfer; any
      positive observation is transfer the pipeline failed to overlap.
    - ``mmlspark_tile_compute_seconds`` — consumer time between taking a
      tile and asking for the next (the compute the transfer hides under).

    ``overlap_stats()`` folds both into a prefetch-overlap percentage.
    ``clock`` is injectable (``utils.resilience.FakeClock``) for
    deterministic tests; :attr:`waiting` is a test seam set while the
    consumer is blocked on an empty pipeline.

    Transient ``load_fn`` failures (flaky storage, a wedged device relay)
    retry up to ``retries`` times with exponential backoff
    (``retry_backoff_s`` × ``retry_backoff_mult``^k, clipped to the
    ambient :class:`~mmlspark_tpu.utils.resilience.Deadline`), classified
    transient-vs-fatal by ``is_transient`` (default
    ``utils.resilience.is_transient_io``); each retried attempt books
    ``mmlspark_prefetch_retries_total{site}``.  Retries happen before the
    tile enters the queue, so delivery stays exactly-once and in order.

    Both histograms book HOST-VISIBLE time: on an async-dispatch backend a
    consumer that only enqueues device work attributes the dispatch gap to
    compute, so device-side serialization shows up in end-to-end
    throughput (the bench ``ooc`` A/B gate), not here — the numbers are
    re-anchored by whatever syncs the consumer's loop performs (the
    streamed growers sync once per histogram pass, the trainer every
    ``device_time_every`` steps).  Treat ``overlap_pct`` as "host stall
    share", exact under FakeClock and honest wherever the consumer blocks.
    """

    def __init__(self, items: Iterable[Any], load_fn: Callable[[Any], Any],
                 *, site: str = "unlabeled",
                 clock: Optional[Callable[[], float]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 retries: int = 3, retry_backoff_s: float = 0.05,
                 retry_backoff_mult: float = 2.0,
                 is_transient: Optional[Callable[[BaseException], bool]] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        self._items = items
        self._load = load_fn
        self._clock = clock if clock is not None else time.perf_counter
        self.site = site
        # transient-failure retry (ISSUE 10): a flaky tile load must not
        # kill an hours-long stream.  Bounded exponential backoff, clipped
        # to the consumer's ambient Deadline (captured HERE — contextvars
        # do not cross into the worker thread), transient-vs-fatal
        # classified by utils.resilience.is_transient_io unless overridden.
        # The retry happens strictly BEFORE the tile enters the queue, so
        # exactly-once delivery and ordering are untouched.
        self._retries = max(0, int(retries))
        self._retry_backoff_s = float(retry_backoff_s)
        self._retry_backoff_mult = float(retry_backoff_mult)
        self._is_transient = is_transient if is_transient is not None \
            else is_transient_io
        self._sleep = sleep if sleep is not None else time.sleep
        self._deadline = current_deadline()
        self.retries_total = 0
        reg = registry if registry is not None else get_registry()
        self._c_retry = reg.counter(
            "mmlspark_prefetch_retries_total",
            "transient tile-load failures retried by the prefetch worker "
            "(each inc is one failed attempt that was retried, not a "
            "killed stream)", labels=("site",)).labels(site=site)
        self._h_wait = reg.histogram(
            "mmlspark_prefetch_wait_seconds",
            "host->device prefetch stall: consumer time blocked waiting for "
            "the next tile (transfer the compute did not hide)",
            labels=("site",)).labels(site=site)
        self._h_tile = reg.histogram(
            "mmlspark_tile_compute_seconds",
            "per-tile consumer compute time between tile takes (the window "
            "the next tile's transfer overlaps with)",
            labels=("site",)).labels(site=site)
        self.wait_s = 0.0
        self.compute_s = 0.0
        self.tiles_served = 0
        #: test seam: set while the consumer blocks on an empty pipeline
        self.waiting = threading.Event()
        self._tokens = threading.Semaphore(1)   # depth-1 readahead
        # live TILES are bounded by the token semaphore (a tile put needs a
        # token; the consumer returns it on take), never by the queue bound.
        # The slack slot exists for the terminal _DONE sentinel: it is put
        # WITHOUT a token, and with maxsize=1 it could block behind a
        # still-untaken last tile — a consumer that then exits early would
        # strand the worker in put() where the cancel/token release cannot
        # reach it, leaking the thread and pinning the tile on device.
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._cancel = threading.Event()
        self._consumed = False
        self._thread = threading.Thread(
            target=self._run, name=f"tile-prefetch:{site}", daemon=True)
        # the pipeline fill (tile 0's transfer) starts NOW, at construction:
        # callers can build the prefetcher, do setup work, and find the
        # first tile already resident when they start iterating
        self._thread.start()

    _DONE = object()

    # --------------------------------------------------------------- worker
    def _run(self) -> None:
        try:
            for item in self._items:
                self._tokens.acquire()
                if self._cancel.is_set():
                    return
                self._q.put((self._load_with_retry(item), None))
            self._q.put((self._DONE, None))
        except BaseException as exc:  # noqa: BLE001 — propagated to consumer
            self._q.put((self._DONE, exc))

    def _load_with_retry(self, item):
        """``load_fn`` under bounded deadline-clipped backoff: transient
        failures retry up to ``retries`` times with exponential backoff
        (never sleeping past the ambient deadline's remaining budget);
        fatal failures and exhausted budgets propagate to the consumer as
        before.  Runs on the worker thread, so retry sleeps overlap the
        consumer's compute exactly like the load itself does."""
        delay = self._retry_backoff_s
        attempt = 0
        while True:
            try:
                return self._load(item)
            except BaseException as exc:  # noqa: BLE001 — classified below
                if attempt >= self._retries or not self._is_transient(exc) \
                        or self._cancel.is_set():
                    raise
                if self._deadline is not None and self._deadline.expired():
                    raise
                attempt += 1
                self.retries_total += 1
                self._c_retry.inc()
                sleep_s = delay if self._deadline is None else \
                    min(delay, max(0.0, self._deadline.remaining()))
                self._sleep(sleep_s)
                delay *= self._retry_backoff_mult

    # -------------------------------------------------------------- consumer
    def __iter__(self):
        if self._consumed:
            raise RuntimeError("TilePrefetcher is single-pass: build a new "
                               "one per stream")
        self._consumed = True
        t_prev = None
        try:
            while True:
                t0 = self._clock()
                if t_prev is not None:
                    self.compute_s += t0 - t_prev
                    self._h_tile.observe(t0 - t_prev)
                if self._q.empty():
                    self.waiting.set()
                tile, exc = self._q.get()
                self.waiting.clear()
                wait = self._clock() - t0
                if exc is not None:
                    raise exc
                if tile is self._DONE:
                    return
                # the tile is in the consumer's hands: the worker may start
                # the NEXT transfer (double-buffer token back)
                self._tokens.release()
                self.wait_s += wait
                self._h_wait.observe(wait)
                self.tiles_served += 1
                t_prev = self._clock()
                yield tile
        finally:
            # early exit (break / exception): unblock and retire the worker
            self._cancel.set()
            self._tokens.release()

    # ----------------------------------------------------------------- stats
    def snapshot(self) -> Dict[str, object]:
        """Live, lock-free view for monitors (``/progress``, flight
        dumps): all fields are GIL-atomic reads, safe while the stream is
        mid-flight.  ``waiting=True`` with ``tiles_served`` frozen is the
        signature of a hung tile load."""
        return {"site": self.site,
                "tiles_served": int(self.tiles_served),
                "wait_s": round(self.wait_s, 6),
                "compute_s": round(self.compute_s, 6),
                "waiting": bool(self.waiting.is_set())}

    def overlap_stats(self) -> Dict[str, float]:
        """Overlap summary: ``overlap_pct`` is the share of stream wall
        time spent computing rather than stalled on transfer — 100 means
        every transfer was fully hidden behind compute."""
        busy = self.wait_s + self.compute_s
        return {"wait_s": self.wait_s, "compute_s": self.compute_s,
                "tiles": float(self.tiles_served),
                "overlap_pct": 100.0 * (self.compute_s / busy)
                if busy > 0 else 100.0}
