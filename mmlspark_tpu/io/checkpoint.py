"""Atomic training checkpoints — crash-safe snapshots with async writes.

Production TPU training treats preemption as a routine event (PAPERS:
Gemma-on-Cloud-TPU fine-tuning; Snap ML's restartable out-of-core
streaming): a multi-hour boosting or DNN run must survive a killed worker
by checkpoint/resume instead of restarting from row zero.  This module is
the one copy of the durability mechanics every training path rides:

- :func:`atomic_write` — the sanctioned writer for ANYTHING under a
  checkpoint directory: content lands in a same-directory temp file and is
  published with ``os.replace``, so a crash mid-write can never tear the
  only copy.  graft-lint RES003 bans direct ``open(..., "w"/"wb")`` in the
  checkpoint modules precisely so this contract cannot erode.
- :class:`CheckpointManager` — step-numbered single-file ``.npz``
  snapshots (arrays + one JSON meta blob) with keep-last-K retention, a
  background writer thread (serialization and disk I/O happen OFF the
  training thread — device work never waits on disk), and torn-snapshot
  fallback on load: resume tries the newest snapshot, and anything that
  fails to parse is skipped (with a booked ``torn_skipped`` resume) in
  favour of the previous one.

Instrumentation (all labelled by ``site``): ``mmlspark_checkpoint_
{save_seconds,bytes,saves_total,resumes_total}`` plus the
``mmlspark_checkpoint_last_success_age_seconds`` gauge — a climbing age on
a run that is supposed to checkpoint every N iterations IS the alert.
Resume and save-failure ring events ride ``core.logging.log_event``.
"""
from __future__ import annotations

import io
import json
import os
import queue
import re
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ..utils.concurrency import make_lock

__all__ = ["atomic_write", "CheckpointManager", "checkpoint_instruments",
           "book_resume", "check_resume_arg", "snapshot_steps",
           "SNAPSHOT_RE", "topology_stanza", "topology_delta",
           "book_reshard", "RESUME_REQUIRED", "resume_required_error"]

#: step-numbered snapshot filename shape: ``ckpt_0000000042.npz`` — the
#: extension is pinned to ``.npz`` exactly: an operator-copied
#: ``ckpt_0000000042.npz.bak`` must read as a FOREIGN file, never as a
#: snapshot whose open would then surface as a confusing torn_skipped
SNAPSHOT_RE = re.compile(r"^(?P<prefix>.+)_(?P<step>\d{10})\.npz$")


@contextmanager
def atomic_write(path: str, mode: str = "wb"):
    """Write-then-publish: yields a file object on ``<path>.tmp-<pid>``;
    on clean exit the temp file is fsync'd and ``os.replace``d over
    ``path`` (atomic on POSIX — readers see the old bytes or the new
    bytes, never a torn mix).  On error the temp file is removed and the
    prior ``path`` content, if any, is untouched.  The single sanctioned
    writer for checkpoint artifacts (graft-lint RES003)."""
    if mode not in ("w", "wb"):
        raise ValueError(f"atomic_write mode must be 'w' or 'wb', got {mode!r}")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    f = open(tmp, mode)  # graft-lint: disable=RES003 — this IS the writer
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
    except BaseException:
        f.close()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


#: sentinel distinguishing "caller did not hand us the directory" from a
#: genuinely absent one — check_resume_arg must not guess either way
_DIR_UNCHECKED = object()


def check_resume_arg(resume: str,
                     checkpoint_dir: Any = _DIR_UNCHECKED) -> None:
    """Shared knob validation for every checkpointing driver: a typo'd
    resume value silently restarting from iteration zero is the exact
    loss this layer exists to prevent — reject it loudly.  ``'must'``
    (ISSUE 14) is ``'auto'`` that additionally REQUIRES a usable snapshot:
    a preemption-restart script passes it so a wiped disk raises instead
    of silently retraining from zero.

    Drivers pass ``checkpoint_dir=`` so the 'must'-with-nowhere-to-resume
    contract lives HERE, once: ``'must'`` with no directory is the
    silent-retrain trap in its sneakiest form (a checkpoint-dir env var
    that didn't propagate to the restart) and raises
    :func:`resume_required_error` instead of quietly training from zero."""
    if resume not in ("auto", "never", "must"):
        raise ValueError(
            f"resume must be 'auto', 'never' or 'must', got {resume!r} "
            "(docs/RESILIENCE.md: training fault tolerance)")
    if checkpoint_dir is not _DIR_UNCHECKED and resume == "must" \
            and not checkpoint_dir:
        raise resume_required_error(checkpoint_dir)


#: shared raise for ``resume='must'`` with nothing to restore — one
#: message so all three drivers fail identically
RESUME_REQUIRED = (
    "resume='must' but no usable snapshot exists in {directory!r} — the "
    "checkpoint directory is empty, wiped, or every snapshot is torn.  A "
    "preemption-restart script must not silently retrain from zero; point "
    "at the surviving checkpoint_dir or pass resume='auto' to accept a "
    "fresh start (docs/RESILIENCE.md: elastic resume)")


def resume_required_error(directory: Optional[str]) -> FileNotFoundError:
    return FileNotFoundError(RESUME_REQUIRED.format(
        directory=directory or "<no checkpoint_dir>"))


def checkpoint_instruments(registry=None) -> Dict[str, Any]:
    """Register (idempotently) and return the checkpoint metric families.
    One shared booking surface so the booster manager here and the trainer
    checkpointer in ``parallel/checkpoint.py`` report into the SAME
    families, distinguished only by their ``site`` label."""
    from ..observability.metrics import get_registry
    reg = registry if registry is not None else get_registry()
    return {
        "save_seconds": reg.histogram(
            "mmlspark_checkpoint_save_seconds",
            "wall time to serialize+publish one snapshot (background "
            "writer thread; the training loop never waits on this)",
            labels=("site",)),
        "bytes": reg.histogram(
            "mmlspark_checkpoint_bytes",
            "published snapshot size in bytes", labels=("site",)),
        "saves": reg.counter(
            "mmlspark_checkpoint_saves_total",
            "snapshot save attempts by outcome", labels=("site", "result")),
        "resumes": reg.counter(
            "mmlspark_checkpoint_resumes_total",
            "resume loads by outcome (ok / torn_skipped / none)",
            labels=("site", "result")),
        "last_age": reg.gauge(
            "mmlspark_checkpoint_last_success_age_seconds",
            "seconds since the last successful snapshot publish (inf "
            "until the first save) — a climbing age on a checkpointing "
            "run is the page", labels=("site",)),
        "reshard": reg.counter(
            "mmlspark_reshard_total",
            "resumes that re-sharded state onto a changed topology "
            "(elastic resume), by driver and direction "
            "(shrink / grow / reshape)", labels=("driver", "direction")),
    }


def book_resume(site: str, result: str, step: Optional[int] = None,
                registry=None, path: str = "", **fields) -> None:
    """Book one resume outcome (counter + ring event) — the ONE booking
    path for the ``checkpoint_resume`` family.  Extra keyword fields ride
    the ring event (e.g. ``files=`` for ``foreign_skipped``)."""
    checkpoint_instruments(registry)["resumes"].inc(site=site, result=result)
    from ..core.logging import log_event
    log_event({"event": "checkpoint_resume", "site": site, "result": result,
               "step": step, "path": path, **fields})


def snapshot_steps(directory: str, prefix: str = "ckpt",
                   foreign: Optional[List[str]] = None) -> List[int]:
    """Sorted (ascending) step numbers of published snapshots in
    ``directory``.  Anything that does not parse as
    ``<prefix>_<10 digits>.npz`` — temp files, operator-copied backups,
    editor artifacts — is a FOREIGN name: ignored, and appended to
    ``foreign`` when the caller wants to book the skip (ISSUE 14: a
    stray file beside the snapshots must never fail the resume path)."""
    steps = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = SNAPSHOT_RE.match(name)
        if m and m.group("prefix") == prefix:
            steps.append(int(m.group("step")))
        elif foreign is not None and not name.startswith(".") \
                and ".tmp-" not in name:
            # our own in-flight temp files are not "foreign" — they are
            # the atomic writer mid-publish (or crash debris it tolerates)
            foreign.append(name)
    return sorted(steps)


# ---------------------------------------------------------------------------
# topology stanza (elastic resume, ISSUE 14) — recorded, allowed to differ
# ---------------------------------------------------------------------------

def topology_stanza(mesh=None, **extra) -> Dict[str, Any]:
    """The topology half of a snapshot's identity: device count, mesh
    shape, shard count — RECORDED so a resume knows what it left, but
    never part of the must-match fingerprint, because the fleet a
    preempted run restarts on is rarely the fleet it lost.  ``mesh``
    fills the device/mesh fields from a ``jax.sharding.Mesh``; drivers
    add their own geometry (``shard_count``, ``num_tiles``, ...) via
    ``extra``."""
    stanza: Dict[str, Any] = {}
    if mesh is not None:
        stanza["device_count"] = int(mesh.devices.size)
        stanza["mesh_axes"] = {str(a): int(s) for a, s in
                               zip(mesh.axis_names, mesh.devices.shape)}
    stanza.update({k: v for k, v in extra.items() if v is not None})
    return stanza


#: width keys in precedence order: the first one present on both sides,
#: numeric, and DIFFERENT decides shrink-vs-grow; everything else is a
#: "reshape".  ``tile_rows`` (not num_tiles) is the streamed width: a
#: smaller tile is a smaller host budget — a shrink — even though the
#: tile COUNT grows.
_WIDTH_KEYS = ("shard_count", "tile_rows", "device_count")


def topology_delta(saved: Optional[Dict[str, Any]],
                   current: Dict[str, Any]) -> Dict[str, Any]:
    """Compare a snapshot's recorded topology to the resuming run's.
    Returns ``{"changed": bool, "direction": shrink|grow|reshape|same,
    "fields": {key: [old, new]}}`` — the delta drivers book (and return
    in extras) so an operator can see a resume re-sharded, in which
    direction, and by how much.  ``saved=None`` means the snapshot
    predates topology recording: that is UNKNOWN, not a change — booking
    a spurious reshard on every pre-upgrade same-mesh resume would cry
    wolf on the very signal this exists for."""
    if saved is None:
        return {"changed": False, "direction": "same", "fields": {}}
    fields = {}
    for key in sorted(set(saved) | set(current)):
        old, new = saved.get(key), current.get(key)
        if old != new:
            fields[key] = [old, new]
    direction = "same"
    if fields:
        direction = "reshape"
        for key in _WIDTH_KEYS:
            old, new = saved.get(key), current.get(key)
            if isinstance(old, (int, float)) and isinstance(new, (int, float)) \
                    and old != new:
                direction = "shrink" if new < old else "grow"
                break
    return {"changed": bool(fields), "direction": direction,
            "fields": fields}


def book_reshard(driver: str, delta: Dict[str, Any],
                 registry=None) -> None:
    """Book one topology-changing resume: the ``mmlspark_reshard_total``
    counter plus a ``resume_topology_delta`` ring event carrying the
    full field-by-field delta."""
    checkpoint_instruments(registry)["reshard"].inc(
        driver=driver, direction=delta.get("direction", "reshape"))
    from ..core.logging import log_event
    log_event({"event": "resume_topology_delta", "driver": driver,
               "direction": delta.get("direction"),
               "fields": delta.get("fields", {})})


class CheckpointManager:
    """Step-numbered atomic ``.npz`` snapshots with async publication.

    ``save(step, arrays, meta)`` enqueues one snapshot: ``arrays`` is a
    dict of array-likes (device arrays welcome — ``np.asarray`` runs on
    the writer thread, so the device-to-host fetch itself happens off the
    training thread) or a zero-arg callable returning one (materialization
    fully deferred); ``meta`` is any JSON-serializable dict.  The writer
    thread serializes to ``<prefix>_<step>.npz`` via :func:`atomic_write`
    and prunes snapshots beyond ``keep_last``.

    Failure containment: a failed save books ``result="error"`` + a ring
    event and the run continues — durability is best-effort per snapshot,
    and the previous snapshot is still intact because publication is
    atomic.  ``load_latest`` walks newest-to-oldest, skipping (and
    booking) torn snapshots.

    NOT safe for two concurrent writers on one directory (the retention
    pass would prune each other's files) — one training run owns one
    checkpoint dir, the same contract every production checkpoint layout
    assumes.
    """

    _META_KEY = "__meta__"

    def __init__(self, directory: str, *, site: str = "checkpoint",
                 keep_last: int = 3, prefix: str = "ckpt",
                 registry=None,
                 clock: Callable[[], float] = time.monotonic):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.directory = str(directory)
        self.site = site
        self.keep_last = int(keep_last)
        self.prefix = prefix
        self._clock = clock
        self._registry = registry
        self._m = checkpoint_instruments(registry)
        self._last_success_at: Optional[float] = None
        self._m["last_age"].set_function(self._age, site=site)
        self.saves_ok = 0
        self.saves_failed = 0
        self.saves_coalesced = 0
        self.last_error: Optional[BaseException] = None
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = make_lock("CheckpointManager._lock")
        os.makedirs(self.directory, exist_ok=True)

    # ---------------------------------------------------------------- save
    def _age(self) -> float:
        with self._lock:
            t = self._last_success_at
        return float("inf") if t is None else max(0.0, self._clock() - t)

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory,
                            f"{self.prefix}_{int(step):010d}.npz")

    def save(self, step: int,
             arrays: Union[Dict[str, Any], Callable[[], Dict[str, Any]]],
             meta: Optional[Dict[str, Any]] = None, *,
             block: bool = False) -> None:
        """Enqueue one snapshot for background publication.  ``block=True``
        waits for THIS snapshot (and everything queued before it) to land
        — the final pre-exit checkpoint wants that; periodic saves do not.

        Backpressure by coalescing: when the writer is slower than the
        save cadence, only the NEWEST still-pending periodic snapshot is
        kept — older pending ones are dropped (booked ``coalesced``)
        before this one enqueues.  Host memory is then bounded at ~two
        payloads (one in flight + one pending) instead of growing without
        limit on slow storage — the exact storage this layer targets.
        Blocking saves drain everything first, so nothing a caller waited
        on is ever dropped."""
        self._ensure_thread()
        if not block:
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
                self._q.task_done()
                self.saves_coalesced += 1
                self._m["saves"].inc(site=self.site, result="coalesced")
        self._q.put((int(step), arrays, dict(meta or {})))
        if block:
            self.wait()

    def wait(self) -> None:
        """Drain every queued save (including any in flight)."""
        self._q.join()

    def close(self) -> None:
        """Drain pending saves, retire the writer thread, and unhook the
        last-success-age gauge — a FINISHED run's age must not keep
        climbing in the shared registry (the gauge is the "checkpoints
        stopped landing" page, and a closed manager is not an outage), and
        the callback closure must not pin the manager alive.  A later save
        restarts the worker and re-registers the gauge."""
        self.wait()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            self._q.put(None)
            t.join()
        self._m["last_age"].remove(site=self.site)

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                # re-opening after close(): the age gauge comes back too
                self._m["last_age"].set_function(self._age, site=self.site)
                self._thread = threading.Thread(
                    target=self._writer, name=f"ckpt-writer:{self.site}",
                    daemon=True)
                self._thread.start()

    def _writer(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            step, arrays, meta = item
            try:
                self._write_one(step, arrays, meta)
            except BaseException as exc:  # noqa: BLE001 — best-effort save
                self.saves_failed += 1
                self.last_error = exc
                self._m["saves"].inc(site=self.site, result="error")
                from ..core.logging import log_event
                log_event({"event": "checkpoint_save_failed",
                           "site": self.site, "step": step,
                           "error": repr(exc)})
            finally:
                self._q.task_done()

    def _write_one(self, step: int, arrays, meta: Dict[str, Any]) -> None:
        t0 = self._clock()
        if callable(arrays):
            arrays = arrays()
        payload = {k: np.asarray(v) for k, v in arrays.items()}
        if self._META_KEY in payload:
            raise ValueError(f"array key {self._META_KEY!r} is reserved")
        meta_bytes = json.dumps(meta, default=float).encode()
        payload[self._META_KEY] = np.frombuffer(meta_bytes, dtype=np.uint8)
        path = self.path_for(step)
        with atomic_write(path, "wb") as f:
            np.savez(f, **payload)
        nbytes = os.path.getsize(path)
        self._prune()
        dt = self._clock() - t0
        with self._lock:
            self._last_success_at = self._clock()
        self.saves_ok += 1
        self._m["save_seconds"].observe(dt, site=self.site)
        self._m["bytes"].observe(float(nbytes), site=self.site)
        self._m["saves"].inc(site=self.site, result="ok")

    def _prune(self) -> None:
        steps = snapshot_steps(self.directory, self.prefix)
        for step in steps[:-self.keep_last]:
            try:
                os.unlink(self.path_for(step))
            except OSError:
                pass  # already gone — retention is best-effort

    # ---------------------------------------------------------------- load
    def steps(self) -> List[int]:
        return snapshot_steps(self.directory, self.prefix)

    def load(self, step: int) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        """Load one snapshot; raises on a torn/unreadable file."""
        with open(self.path_for(step), "rb") as f:
            data = f.read()
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files if k != self._META_KEY}
            meta_raw = z[self._META_KEY].tobytes() if self._META_KEY in z.files \
                else b"{}"
        meta = json.loads(meta_raw.decode())
        if not isinstance(meta, dict):
            raise ValueError("snapshot meta is not a JSON object")
        return arrays, meta

    def load_latest(self, current_topology: Optional[Dict[str, Any]] = None
                    ) -> Optional[
            Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]]:
        """Newest valid snapshot, or None.  A torn newest snapshot (crash
        artifact, truncated copy) is skipped — booked + ring-evented — and
        the previous one restores instead: durability degrades one step,
        never to zero.

        Foreign filenames beside the snapshots (operator copies, editor
        backups, unparseable names) are skipped with ONE booked
        ``foreign_skipped`` + ring event instead of failing the resume
        path (ISSUE 14).  A snapshot evicted by keep-last-K retention
        between the directory listing and the open falls back to the
        next-oldest — and if the stale listing exhausted itself that way
        while a newer snapshot was landing, the walk re-lists once
        (booked ``evicted_skipped`` per vanished file).

        With ``current_topology`` given, the returned ``meta`` carries
        ``meta["topology_delta"]`` — :func:`topology_delta` of the
        snapshot's recorded topology stanza against the resuming run's —
        so drivers know they are re-sharding before they rebuild state.
        """
        skipped_booked: set = set()   # steps already booked torn/evicted —
        for relist in range(2):       # the re-list walk must not re-count
            foreign: List[str] = []   # the same artifact
            steps = snapshot_steps(self.directory, self.prefix,
                                   foreign=foreign)
            if foreign and relist == 0:
                book_resume(self.site, "foreign_skipped",
                            registry=self._registry,
                            files=sorted(foreign)[:16])
            evicted_midwalk = False
            for step in reversed(steps):
                try:
                    arrays, meta = self.load(step)
                except FileNotFoundError:
                    # keep-last-K retention raced the walk: the listed
                    # file is gone, the next-oldest (or a re-list) serves
                    if step not in skipped_booked:
                        skipped_booked.add(step)
                        book_resume(self.site, "evicted_skipped", step,
                                    registry=self._registry,
                                    path=self.path_for(step))
                    evicted_midwalk = True
                    continue
                except Exception:  # noqa: BLE001 — torn snapshot: fall back
                    if step not in skipped_booked:
                        skipped_booked.add(step)
                        book_resume(self.site, "torn_skipped", step,
                                    registry=self._registry,
                                    path=self.path_for(step))
                    continue
                if current_topology is not None:
                    meta = dict(meta, topology_delta=topology_delta(
                        meta.get("topology"), current_topology))
                book_resume(self.site, "ok", step, registry=self._registry,
                            path=self.path_for(step))
                return step, arrays, meta
            if not evicted_midwalk:
                break
            # every listed snapshot vanished mid-walk — retention only
            # evicts when a NEWER snapshot landed, so a fresh listing
            # has something to serve; retry exactly once
        book_resume(self.site, "none", registry=self._registry)
        return None
