"""Audio streams + features — the pull-stream layer under streaming speech.

Reference: ``cognitive/.../AudioStreams.scala`` — ``WavStream`` (:16) and
``CompressedStream`` (:84) implement the Speech SDK's PullAudioInputStream
(chunked ``read(buf)`` over wav/compressed bytes), and
``BlockingQueueIterator`` (SpeechToTextSDK.scala:42) bridges the SDK's
callback-push world into Spark's iterator-pull world.

TPU-native: the same three pieces, dependency-free — pull streams over
bytes/files, a blocking queue bridge, and the acoustic front end (framed
log-mel filterbanks, numpy) that turns PCM chunks into the (T, n_mels)
feature matrices the streaming encoder consumes on device.
"""
from __future__ import annotations

import io
import queue
import struct
import threading
from typing import Iterator, Optional

import numpy as np


class PullAudioStream:
    """Chunked pull over mono float32 PCM in [-1, 1]."""

    def __init__(self, samples: np.ndarray, sample_rate: int):
        self.samples = np.asarray(samples, np.float32).reshape(-1)
        self.sample_rate = sample_rate
        self._pos = 0

    def read(self, n: int) -> np.ndarray:
        """Next <=n samples; empty array at end of stream."""
        chunk = self.samples[self._pos:self._pos + n]
        self._pos += len(chunk)
        return chunk

    def chunks(self, chunk_samples: int) -> Iterator[np.ndarray]:
        while True:
            c = self.read(chunk_samples)
            if len(c) == 0:
                return
            yield c


def parse_wav(data: bytes) -> PullAudioStream:
    """Minimal RIFF/WAVE PCM parser (``WavStream`` analogue): 16-bit or
    32-bit-float PCM, any channel count (downmixed to mono)."""
    buf = io.BytesIO(data)
    if buf.read(4) != b"RIFF":
        raise ValueError("not a RIFF file")
    buf.read(4)
    if buf.read(4) != b"WAVE":
        raise ValueError("not a WAVE file")
    fmt = None
    while True:
        hdr = buf.read(8)
        if len(hdr) < 8:
            raise ValueError("no data chunk in wav")
        cid, size = hdr[:4], struct.unpack("<I", hdr[4:])[0]
        if cid == b"fmt ":
            fmt = buf.read(size)
        elif cid == b"data":
            raw = buf.read(size)
            break
        else:
            buf.read(size + (size & 1))
    if fmt is None:
        raise ValueError("no fmt chunk in wav")
    audio_fmt, channels, rate = struct.unpack("<HHI", fmt[:8])
    bits = struct.unpack("<H", fmt[14:16])[0]
    if audio_fmt == 1 and bits == 16:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif audio_fmt == 3 and bits == 32:
        x = np.frombuffer(raw, "<f4").astype(np.float32)
    else:
        raise ValueError(f"unsupported wav encoding fmt={audio_fmt} bits={bits}")
    if channels > 1:
        x = x.reshape(-1, channels).mean(axis=1)
    return PullAudioStream(x, rate)


def write_wav(samples: np.ndarray, sample_rate: int) -> bytes:
    """16-bit PCM wav bytes (tests/mocks)."""
    pcm = np.round(np.clip(np.asarray(samples, np.float32), -1, 1)
                   * 32767).astype("<i2")
    data = pcm.tobytes()
    fmt = struct.pack("<HHIIHH", 1, 1, sample_rate, sample_rate * 2, 2, 16)
    out = b"RIFF" + struct.pack("<I", 4 + 8 + len(fmt) + 8 + len(data)) + b"WAVE"
    out += b"fmt " + struct.pack("<I", len(fmt)) + fmt
    out += b"data" + struct.pack("<I", len(data)) + data
    return out


def resample(x: np.ndarray, sr_in: int, sr_out: int) -> np.ndarray:
    """Linear-interpolation resample (adequate for speech front ends)."""
    if sr_in == sr_out:
        return np.asarray(x, np.float32)
    n_out = int(round(len(x) * sr_out / sr_in))
    pos = np.arange(n_out) * (len(x) - 1) / max(n_out - 1, 1)
    return np.interp(pos, np.arange(len(x)), x).astype(np.float32)


def audio_stream(payload, sample_rate: int = 16000,
                 audio_format: str = "wav") -> PullAudioStream:
    """Column cell -> PullAudioStream: wav bytes, raw float arrays, or an
    existing stream."""
    if isinstance(payload, PullAudioStream):
        return payload
    if audio_format == "wav" and isinstance(payload, (bytes, bytearray)):
        return parse_wav(bytes(payload))
    return PullAudioStream(np.asarray(payload, np.float32), sample_rate)


class BlockingQueueIterator:
    """Push-to-pull bridge (reference ``SpeechToTextSDK.scala:42``): a
    producer (recognition callback) ``put``s results, the consumer iterates;
    ``close()`` ends iteration after the queue drains.  Producer errors
    pushed via ``put_error`` re-raise in the consumer."""

    _DONE = object()

    class _Error:
        __slots__ = ("exc",)

        def __init__(self, exc):
            self.exc = exc

    def __init__(self, maxsize: int = 0):
        self._q: "queue.Queue" = queue.Queue(maxsize)
        self._closed = threading.Event()

    def put(self, item) -> None:
        if self._closed.is_set():
            raise RuntimeError("put() after close()")
        self._q.put(item)

    def put_error(self, exc: BaseException) -> None:
        if not self._closed.is_set():
            self._q.put(self._Error(exc))

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, self._Error):
            raise item.exc
        return item


# --------------------------------------------------------------------------
# acoustic front end
# --------------------------------------------------------------------------

def mel_filterbank(sr: int, n_fft: int, n_mels: int,
                   fmin: float = 0.0, fmax: Optional[float] = None) -> np.ndarray:
    """(n_mels, n_fft//2+1) triangular mel filter matrix."""
    fmax = fmax or sr / 2
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)
    def mel_to_hz(m):
        return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)
    pts = mel_to_hz(np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), n_mels + 2))
    bins = np.floor((n_fft + 1) * pts / sr).astype(int)
    fb = np.zeros((n_mels, n_fft // 2 + 1), np.float32)
    for i in range(n_mels):
        l, c, r = bins[i], bins[i + 1], bins[i + 2]
        for b in range(l, c):
            if c > l:
                fb[i, b] = (b - l) / (c - l)
        for b in range(c, r):
            if r > c:
                fb[i, b] = (r - b) / (r - c)
    return fb


def log_mel(signal: np.ndarray, sr: int = 16000, n_mels: int = 40,
            frame_ms: float = 25.0, hop_ms: float = 10.0) -> np.ndarray:
    """(T, n_mels) log-mel features — framed hann-windowed power spectra
    through a mel filterbank.  Pure numpy; chunk-sized inputs stay cheap on
    host while the encoder runs on device."""
    frame = int(sr * frame_ms / 1000)
    hop = int(sr * hop_ms / 1000)
    x = np.asarray(signal, np.float32).reshape(-1)
    if len(x) < frame:
        x = np.pad(x, (0, frame - len(x)))
    n_frames = 1 + (len(x) - frame) // hop
    idx = np.arange(frame)[None, :] + hop * np.arange(n_frames)[:, None]
    frames = x[idx] * np.hanning(frame).astype(np.float32)
    n_fft = int(2 ** np.ceil(np.log2(frame)))
    spec = np.abs(np.fft.rfft(frames, n=n_fft, axis=1)) ** 2
    fb = mel_filterbank(sr, n_fft, n_mels)
    return np.log(spec @ fb.T + 1e-6).astype(np.float32)
