"""Binary file IO — directories of arbitrary files as frames.

Reference: ``core/.../io/binary/BinaryFileFormat.scala`` (Spark DataSource
over binary files with recursive parallel listing, batch AND streaming) and
``BinaryFileReader``.  Columns: path (string), bytes (binary).
"""
from __future__ import annotations

import fnmatch
import os
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ..core import DataFrame


def list_files(path: str, pattern: Optional[str] = None,
               recursive: bool = True) -> List[str]:
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, dirs, files in os.walk(path):
        for f in sorted(files):
            if pattern is None or fnmatch.fnmatch(f, pattern):
                out.append(os.path.join(root, f))
        if not recursive:
            break
    return sorted(out)


def read_binary_files(path: str, pattern: Optional[str] = None,
                      recursive: bool = True, num_partitions: int = 1,
                      with_bytes: bool = True) -> DataFrame:
    files = list_files(path, pattern, recursive)
    paths = np.empty(len(files), dtype=object)
    blobs = np.empty(len(files), dtype=object)
    for i, f in enumerate(files):
        paths[i] = f
        if with_bytes:
            with open(f, "rb") as fh:
                blobs[i] = fh.read()
    cols = {"path": paths}
    if with_bytes:
        cols["bytes"] = blobs
    return DataFrame.from_dict(cols, num_partitions=max(1, min(num_partitions, len(files) or 1)))


class BinaryFileStream:
    """Streaming variant: files appearing under ``path`` become micro-batch
    frames (the reference's binary DataSource streams new files the same
    way; ``IOImplicits.readStream.binary``).  Poll-based; offsets are the
    set of already-seen paths, so each file is delivered exactly once."""

    def __init__(self, path: str, pattern: Optional[str] = None,
                 recursive: bool = True, poll_interval_s: float = 0.5,
                 settle_s: float = 0.0):
        self.path = path
        self.pattern = pattern
        self.recursive = recursive
        self.poll_interval_s = poll_interval_s
        # files are delivered once their mtime is at least settle_s old, so
        # a file mid-write isn't emitted truncated.  The default 0 assumes
        # the Spark-file-source convention: producers write to a temp name
        # and rename into the watched directory (rename is atomic).
        self.settle_s = settle_s
        self._seen = set()

    def get_batch(self) -> Optional[DataFrame]:
        """Frame of files not yet delivered, or None when nothing is new."""
        now = time.time()
        files = []
        for f in list_files(self.path, self.pattern, self.recursive):
            if f in self._seen:
                continue
            try:
                if self.settle_s and now - os.path.getmtime(f) < self.settle_s:
                    continue  # still settling; picked up on a later poll
            except OSError:
                continue  # vanished between list and stat
            files.append(f)
        if not files:
            return None
        paths, blobs = [], []
        for f in files:
            try:
                with open(f, "rb") as fh:
                    data = fh.read()
            except OSError:
                continue  # vanished between stat and open: not marked seen
            self._seen.add(f)
            paths.append(f)
            blobs.append(data)
        if not paths:
            return None
        p_col = np.empty(len(paths), dtype=object)
        b_col = np.empty(len(paths), dtype=object)
        for i, (p, b) in enumerate(zip(paths, blobs)):
            p_col[i], b_col[i] = p, b
        return DataFrame.from_dict({"path": p_col, "bytes": b_col})

    def for_each_batch(self, fn: Callable[[DataFrame], None]):
        """Background trigger loop (``writeStream.foreachBatch`` analogue);
        returns a handle with ``stop()`` and ``last_error``.  Per-batch
        errors (user fn or IO) are recorded on the handle and the stream
        keeps polling — one bad batch must not silently end the stream."""
        stop = threading.Event()

        class _Handle:
            last_error: Optional[str] = None

            def stop(self, timeout: float = 10.0):
                stop.set()
                t.join(timeout)

        handle = _Handle()

        def loop():
            while not stop.is_set():
                try:
                    batch = self.get_batch()
                    if batch is not None:
                        fn(batch)
                        continue
                except Exception as e:  # noqa: BLE001 — record and keep going
                    handle.last_error = str(e)
                time.sleep(self.poll_interval_s)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return handle
