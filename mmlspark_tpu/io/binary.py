"""Binary file IO — directories of arbitrary files as frames.

Reference: ``core/.../io/binary/BinaryFileFormat.scala`` (Spark DataSource
over binary files with recursive parallel listing) and ``BinaryFileReader``.
Columns: path (string), bytes (binary).
"""
from __future__ import annotations

import fnmatch
import os
from typing import List, Optional

import numpy as np

from ..core import DataFrame


def list_files(path: str, pattern: Optional[str] = None,
               recursive: bool = True) -> List[str]:
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, dirs, files in os.walk(path):
        for f in sorted(files):
            if pattern is None or fnmatch.fnmatch(f, pattern):
                out.append(os.path.join(root, f))
        if not recursive:
            break
    return sorted(out)


def read_binary_files(path: str, pattern: Optional[str] = None,
                      recursive: bool = True, num_partitions: int = 1,
                      with_bytes: bool = True) -> DataFrame:
    files = list_files(path, pattern, recursive)
    paths = np.empty(len(files), dtype=object)
    blobs = np.empty(len(files), dtype=object)
    for i, f in enumerate(files):
        paths[i] = f
        if with_bytes:
            with open(f, "rb") as fh:
                blobs[i] = fh.read()
    cols = {"path": paths}
    if with_bytes:
        cols["bytes"] = blobs
    return DataFrame.from_dict(cols, num_partitions=max(1, min(num_partitions, len(files) or 1)))
