"""CSV ingest — native fast path with numpy fallback.

Reference ingest hot loop: rows are streamed into native chunked arrays
(``DatasetAggregator.scala:87-95``).  Here whole numeric CSVs parse in C++
(``native/mmlspark_native.cpp``) straight into a columnar float32 matrix;
mixed-type files fall back to a python reader that keeps string columns.
"""
from __future__ import annotations

import csv as _csv
import io as _io
from typing import List, Optional

import numpy as np

from ..core import DataFrame
from ..core.dataframe import _as_column


def read_csv(path: str, num_partitions: int = 1, header: bool = True,
             numeric_only: bool = False) -> DataFrame:
    with open(path, "rb") as f:
        raw = f.read()
    names: Optional[List[str]] = None
    if header:
        first_line = raw.split(b"\n", 1)[0].decode("utf-8").strip("\r")
        names = next(_csv.reader([first_line]))
    if numeric_only:
        from ..utils.native_loader import csv_to_matrix_native
        mat = csv_to_matrix_native(raw, skip_header=header)
        if mat is not None:
            cols = names or [f"c{i}" for i in range(mat.shape[1])]
            return DataFrame.from_dict(
                {c: mat[:, i].astype(np.float64) for i, c in enumerate(cols)},
                num_partitions)
    # general path: python csv module, per-column type inference
    text = raw.decode("utf-8", "replace")
    reader = _csv.reader(_io.StringIO(text))
    rows = [r for r in reader if r]
    if header:
        names = rows[0]
        rows = rows[1:]
    if not rows:
        return DataFrame([{}])
    ncols = len(rows[0])
    names = names or [f"c{i}" for i in range(ncols)]
    cols = {}
    for i, name in enumerate(names):
        vals = [r[i] if i < len(r) else "" for r in rows]
        try:
            cols[name] = np.asarray([float(v) if v != "" else np.nan for v in vals])
        except ValueError:
            cols[name] = _as_column(vals)
    return DataFrame.from_dict(cols, num_partitions)
