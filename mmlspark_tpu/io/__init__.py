from .http import (HTTPRequestData, HTTPResponseData, HTTPClient,
                   AsyncHTTPClient, HTTPTransformer, SimpleHTTPTransformer,
                   REQUEST_BINDING, RESPONSE_BINDING)
from .binary import read_binary_files, list_files, BinaryFileStream
from .image import read_images, decode_image, images_to_bytes_column
from . import powerbi

__all__ = ["HTTPRequestData", "HTTPResponseData", "HTTPClient",
           "AsyncHTTPClient", "HTTPTransformer", "SimpleHTTPTransformer",
           "REQUEST_BINDING", "RESPONSE_BINDING", "read_binary_files",
           "BinaryFileStream",
           "list_files", "read_images", "decode_image",
           "images_to_bytes_column", "powerbi"]
