from .http import (HTTPRequestData, HTTPResponseData, HTTPClient,
                   AsyncHTTPClient, HTTPTransformer, SimpleHTTPTransformer,
                   REQUEST_BINDING, RESPONSE_BINDING)
from .binary import read_binary_files, list_files, BinaryFileStream
from .chunked import (ChunkedDataset, TilePrefetcher, resolve_tile_rows,
                      pad_tile)
from .image import read_images, decode_image, images_to_bytes_column
from . import powerbi

__all__ = ["HTTPRequestData", "HTTPResponseData", "HTTPClient",
           "AsyncHTTPClient", "HTTPTransformer", "SimpleHTTPTransformer",
           "REQUEST_BINDING", "RESPONSE_BINDING", "read_binary_files",
           "BinaryFileStream", "ChunkedDataset", "TilePrefetcher",
           "resolve_tile_rows", "pad_tile",
           "list_files", "read_images", "decode_image",
           "images_to_bytes_column", "powerbi"]
