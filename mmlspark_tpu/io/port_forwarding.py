"""SSH reverse port forwarding for serving behind NAT.

Reference: ``io/http/PortForwarding.scala:16-69`` (jsch ``ssh -R`` tunnels so
an executor-local serving port is reachable from a public host).  Here the
tunnel is the system ``ssh`` client run as a managed subprocess.
"""
from __future__ import annotations

import shutil
import subprocess
from typing import Dict, Optional


class PortForwarding:
    _sessions: Dict[str, subprocess.Popen] = {}

    @staticmethod
    def forward_port_to_remote(username: str, host: str, remote_port: int,
                               local_port: int, key_file: Optional[str] = None,
                               ssh_port: int = 22, extra_args=()) -> str:
        """Open ssh -R remote_port:localhost:local_port; returns session id."""
        if shutil.which("ssh") is None:
            raise RuntimeError("no ssh client available for port forwarding")
        cmd = ["ssh", "-N", "-o", "StrictHostKeyChecking=no",
               "-o", "ExitOnForwardFailure=yes",
               "-R", f"{remote_port}:localhost:{local_port}",
               "-p", str(ssh_port)]
        if key_file:
            cmd += ["-i", key_file]
        cmd += list(extra_args)
        cmd.append(f"{username}@{host}")
        proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        session_id = f"{username}@{host}:{remote_port}->{local_port}"
        PortForwarding._sessions[session_id] = proc
        return session_id

    @staticmethod
    def stop(session_id: str) -> None:
        proc = PortForwarding._sessions.pop(session_id, None)
        if proc is not None:
            proc.terminate()

    @staticmethod
    def stop_all() -> None:
        for sid in list(PortForwarding._sessions):
            PortForwarding.stop(sid)
