from .server import (PipelineServer, DistributedPipelineServer, ServingStats)
from .distributed import (MembershipWatcher, RoutingClient, TopologyService,
                          WorkerServer)
from .streaming import HTTPStreamSource, StreamingQuery, read_stream
from .loadgen import check_gates, sustained_load, mixed_load

__all__ = ["PipelineServer", "DistributedPipelineServer", "ServingStats",
           "TopologyService", "WorkerServer", "RoutingClient",
           "MembershipWatcher",
           "HTTPStreamSource", "StreamingQuery", "read_stream",
           "sustained_load", "mixed_load", "check_gates"]
