from .server import (PipelineServer, DistributedPipelineServer, ServingStats)

__all__ = ["PipelineServer", "DistributedPipelineServer", "ServingStats"]
