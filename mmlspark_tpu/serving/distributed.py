"""Distributed serving topology — driver registry + worker servers + routing.

Reference: Spark Serving v2's driver-side routing service
(``continuous/HTTPSourceV2.scala:190-196`` ``DriverServiceUtils
.createDriverService`` announces which executor hosts which partition's
server so a load balancer can route) and its server/client registries
(``HTTPSourceStateHolder`` ``:337-371``); the v1 distributed variant shards
buffered requests across partitions (``DistributedHTTPSource.scala:27-88``
``MultiChannelMap``).

TPU-native mapping: one ``WorkerServer`` per executor host (each wrapping an
already-jitted pipeline on that host's chip), a ``TopologyService`` on the
driver holding the ``server_id -> host:port`` routing table plus aggregated
stats, and a ``RoutingClient`` that routes by partition key (hash) or round
robin — the ``MultiChannelMap`` analogue, client-side where the reference
put it behind an LB.  Workers reply directly on their own sockets
(continuous-mode semantics: no reply forwarding hop, ``HTTPSinkV2``).
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .server import PipelineServer


def _http_json(url: str, payload: Optional[dict] = None, timeout: float = 10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode() or "null")


class TopologyService:
    """Driver-side registry: workers announce ``server_id -> host:port``;
    clients fetch the routing table; ``/stats`` aggregates every worker's
    counters (reference: driver service ``HTTPSourceV2.scala:190`` +
    state-holder registries ``:337-371``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._lock = threading.Lock()
        self._workers: Dict[str, Dict] = {}
        self._flags: Dict[str, str] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------------ http
    def _make_handler(self):
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, status, obj):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length).decode() or "{}")
                if self.path == "/register":
                    with svc._lock:
                        svc._workers[payload["server_id"]] = payload
                    self._json(200, {"ok": True,
                                     "num_workers": len(svc._workers)})
                elif self.path == "/deregister":
                    with svc._lock:
                        svc._workers.pop(payload.get("server_id"), None)
                    self._json(200, {"ok": True})
                elif self.path == "/flag":
                    with svc._lock:
                        svc._flags[payload["key"]] = payload["value"]
                    self._json(200, {"ok": True})
                else:
                    self._json(404, {"error": "not found"})

            def do_GET(self):
                if self.path == "/routing":
                    with svc._lock:
                        table = dict(svc._workers)
                    self._json(200, table)
                elif self.path.startswith("/flag/"):
                    with svc._lock:
                        self._json(200, {"value": svc._flags.get(self.path[6:])})
                elif self.path == "/stats":
                    self._json(200, svc.aggregate_stats())
                elif self.path == "/health":
                    self._json(200, {"ok": True})
                else:
                    self._json(404, {"error": "not found"})

        return Handler

    # ------------------------------------------------------------------ api
    def start(self) -> "TopologyService":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_port
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def routing_table(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._workers)

    def aggregate_stats(self) -> Dict:
        """Pull and sum every registered worker's counters."""
        with self._lock:
            workers = list(self._workers.values())
        total = {"received": 0, "replied": 0, "errors": 0, "workers": {}}
        lat_sum = 0.0
        for w in workers:
            try:
                s = _http_json(f"http://{w['host']}:{w['port']}/stats")
            except Exception as e:  # noqa: BLE001 — a dead worker is a stat
                total["workers"][w["server_id"]] = {"error": str(e)}
                continue
            total["workers"][w["server_id"]] = s
            total["received"] += s.get("received", 0)
            total["replied"] += s.get("replied", 0)
            total["errors"] += s.get("errors", 0)
            lat_sum += s.get("mean_latency_ms", 0.0) * s.get("replied", 0)
        if total["replied"]:
            total["mean_latency_ms"] = lat_sum / total["replied"]
        return total


class WorkerServer:
    """Executor-side server: a ``PipelineServer`` that registers its
    ``host:port`` (and owned partition ids) with the driver's topology
    service at start and deregisters at stop — the worker half of
    ``HTTPSourceStateHolder`` registration."""

    def __init__(self, model, server_id: str, driver_address: str,
                 partition_ids: Optional[List[int]] = None, **kw):
        self.server_id = server_id
        self.driver_address = driver_address.rstrip("/")
        self.partition_ids = partition_ids or []
        self.server = PipelineServer(model, **kw)

    def start(self) -> "WorkerServer":
        self.server.start()
        _http_json(f"{self.driver_address}/register",
                   {"server_id": self.server_id, "host": self.server.host,
                    "port": self.server.port,
                    "api_path": self.server.api_path,
                    "partition_ids": self.partition_ids})
        return self

    def stop(self) -> None:
        try:
            _http_json(f"{self.driver_address}/deregister",
                       {"server_id": self.server_id})
        except Exception:  # noqa: BLE001 — driver may already be gone
            pass
        self.server.stop()

    @property
    def address(self) -> str:
        return self.server.address


class RoutingClient:
    """Client-side router over the driver's table: round robin by default,
    or deterministic key-hash routing (``MultiChannelMap.nextList``'s
    request sharding, client-side).  Refreshes the table on demand."""

    def __init__(self, driver_address: str, refresh_s: float = 5.0):
        self.driver_address = driver_address.rstrip("/")
        self.refresh_s = refresh_s
        self._table: List[Dict] = []
        self._fetched = 0.0
        self._rr = 0
        self._lock = threading.Lock()

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if force or not self._table or now - self._fetched > self.refresh_s:
            table = _http_json(f"{self.driver_address}/routing")
            with self._lock:
                self._table = sorted(table.values(),
                                     key=lambda w: w["server_id"])
                self._fetched = now

    def _pick(self, key: Optional[str]) -> Dict:
        self._refresh()
        with self._lock:
            if not self._table:
                raise RuntimeError("no serving workers registered")
            if key is not None:
                # stable across processes/restarts (builtin hash is salted),
                # so partition affinity survives like MultiChannelMap's
                import zlib
                return self._table[zlib.crc32(key.encode()) % len(self._table)]
            w = self._table[self._rr % len(self._table)]
            self._rr += 1
            return w

    def request(self, payload, key: Optional[str] = None,
                timeout: float = 30.0, retries: int = 2):
        """POST to the routed worker; on connection failure, refresh the
        table and fail over to the next worker (the LB behavior the
        reference delegates to Azure LB, ``docs/mmlspark-serving.md:87``)."""
        last = None
        for _ in range(retries + 1):
            w = self._pick(key)
            url = f"http://{w['host']}:{w['port']}{w.get('api_path', '/score')}"
            try:
                return _http_json(url, payload, timeout=timeout)
            except Exception as e:  # noqa: BLE001 — fail over
                last = e
                try:  # a briefly-unreachable driver must not abort the
                    self._refresh(force=True)  # retry; stale table still works
                except Exception:  # noqa: BLE001
                    pass
                key = None  # reroute away from the dead worker
        raise RuntimeError(f"all serving workers failed: {last}")

    def stats(self) -> Dict:
        return _http_json(f"{self.driver_address}/stats")
