"""Distributed serving topology — driver registry + worker servers + routing.

Reference: Spark Serving v2's driver-side routing service
(``continuous/HTTPSourceV2.scala:190-196`` ``DriverServiceUtils
.createDriverService`` announces which executor hosts which partition's
server so a load balancer can route) and its server/client registries
(``HTTPSourceStateHolder`` ``:337-371``); the v1 distributed variant shards
buffered requests across partitions (``DistributedHTTPSource.scala:27-88``
``MultiChannelMap``).

TPU-native mapping: one ``WorkerServer`` per executor host (each wrapping an
already-jitted pipeline on that host's chip), a ``TopologyService`` on the
driver holding the ``server_id -> host:port`` routing table plus aggregated
stats, and a ``RoutingClient`` that routes by partition key (hash) or round
robin — the ``MultiChannelMap`` analogue, client-side where the reference
put it behind an LB.  Workers reply directly on their own sockets
(continuous-mode semantics: no reply forwarding hop, ``HTTPSinkV2``).
"""
from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
import urllib.parse
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .server import PipelineServer
from ..observability import get_registry, instrument_breaker
from ..observability.attribution import CapacityModel, attribution_instruments
from ..observability.autoscale import AutoscaleAdvisor
from ..observability.federation import MetricsFederator
from ..observability.instruments import uninstrument_breaker
from ..observability.slo import SLOEngine
from ..observability.tracing import (TRACE_HEADER, TRACEPARENT_HEADER,
                                     current_span, current_trace_id,
                                     format_traceparent)
from ..utils.concurrency import make_lock
from ..utils.resilience import (CircuitBreaker, Deadline, RetryBudget,
                                current_deadline)


def _http_json(url: str, payload: Optional[dict] = None, timeout: float = 10.0,
               deadline: Optional[Deadline] = None):
    deadline = deadline or current_deadline()
    if deadline is not None:
        if deadline.expired():
            raise TimeoutError("deadline exceeded before request")
        timeout = deadline.clip(timeout)
    data = json.dumps(payload).encode() if payload is not None else None
    headers = {"Content-Type": "application/json"}
    if deadline is not None:
        # forward the remaining budget so the server admits/sheds/scores
        # under the caller's deadline, not its own default
        headers[Deadline.HEADER] = deadline.to_header()
    trace_id = current_trace_id()
    if trace_id is not None:
        # the ambient span's trace id rides the wire so worker-side spans
        # join the caller's trace — legacy header plus W3C traceparent
        headers[TRACE_HEADER] = trace_id
        span = current_span()
        headers[TRACEPARENT_HEADER] = format_traceparent(
            trace_id, span.span_id if span is not None else None)
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode() or "null")


#: every HTTP endpoint TopologyService serves, by verb — the telemetry
#: coverage sweep (tests/test_telemetry_coverage.py) diffs this table
#: against the handler source, so a new endpoint cannot land unlisted
#: (and therefore undocumented/unswept).  ``/flag/<key>`` is the
#: prefix-matched flag read.
TOPOLOGY_ENDPOINTS = {
    "GET": ("/routing", "/flag/<key>", "/stats", "/fleet/slow",
            "/fleet/metrics", "/fleet/slo", "/fleet/autoscale",
            "/fleet/capacity", "/fleet/trace/<id>",
            "/fleet/membership", "/fleet/dump", "/health"),
    "POST": ("/register", "/deregister", "/flag"),
}

#: per-process instance counter for the membership-epoch gauge label: a
#: registry shared by several services (tests, embedded drivers) must not
#: have one service's epoch stomp another's series, and port 0 is not
#: known until start() so host:port cannot label at construction
_SERVICE_IDS = itertools.count()


def _nonneg_int(raw: str) -> int:
    v = int(raw)
    if v < 0:
        raise ValueError("must be >= 0")
    return v


def _pos_float(raw: str) -> float:
    v = float(raw)
    if not v > 0:
        raise ValueError("must be > 0")
    return v


def _flag01(raw: str) -> bool:
    if raw in ("1", "true"):
        return True
    if raw in ("", "0", "false"):
        return False
    raise ValueError("expected 0|1")


def _parse_query(query: str, spec: Dict[str, Callable[[str], object]]):
    """Validate a query string against ``spec`` (param name -> parser
    raising ValueError).  Returns ``(params, None)`` or ``(None, error)``
    — the shared validation for every fleet endpoint: a malformed value
    is a 400 verdict on the REQUEST, never a silent default and never an
    unhandled exception turning into a 500 (ISSUE 11 bugfix).  Unknown
    params are ignored (forward compatibility); percent-encoding is
    decoded by the stdlib parser; a repeated param's LAST value wins."""
    params: Dict[str, object] = {}
    if not query:
        return params, None
    for key, values in urllib.parse.parse_qs(
            query, keep_blank_values=True).items():
        parser = spec.get(key)
        if parser is None:
            continue
        raw = values[-1]
        try:
            params[key] = parser(raw)
        except ValueError as e:
            return None, f"bad query param {key}={raw!r}: {e}"
    return params, None


def _default_prober(worker: Dict, timeout: float) -> bool:
    """One /health probe against a worker's own socket (PipelineServer and
    TopologyService both serve GET /health)."""
    try:
        url = f"http://{worker['host']}:{worker['port']}/health"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status == 200
    except Exception:  # noqa: BLE001 — any failure is "unhealthy"
        return False


class TopologyService:
    """Driver-side registry: workers announce ``server_id -> host:port``;
    clients fetch the routing table; ``/stats`` aggregates every worker's
    counters (reference: driver service ``HTTPSourceV2.scala:190`` +
    state-holder registries ``:337-371``).

    Health-checked failover: the driver actively probes each worker's
    ``/health`` every ``probe_interval_s``; ``evict_after`` consecutive
    probe failures evict the worker from the routing table (it reappears
    if it re-registers).  ``probe_once()`` runs a single sweep — tests
    drive eviction deterministically through it instead of sleeping.
    ``prober`` is injectable for the chaos harness.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 probe_interval_s: Optional[float] = 5.0,
                 probe_timeout_s: float = 2.0, evict_after: int = 3,
                 prober: Optional[Callable[[Dict, float], bool]] = None,
                 registry=None, fleet_slow_deadline_s: float = 2.0,
                 fleet_slow_k: int = 10,
                 fleet_breaker_factory: Optional[
                     Callable[[str], CircuitBreaker]] = None,
                 slos=(), federation_poll_s: Optional[float] = None,
                 federation_timeout_s: float = 2.0,
                 federation_deadline_s: float = 3.0,
                 telemetry_clock: Callable[[], float] = time.monotonic,
                 federator: Optional[MetricsFederator] = None,
                 slo_engine: Optional[SLOEngine] = None,
                 autoscaler: Optional[AutoscaleAdvisor] = None):
        self.host, self.port = host, port
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.evict_after = max(1, evict_after)
        self.prober = prober or _default_prober
        self.registry = registry if registry is not None else get_registry()
        # /fleet/slow fan-out: overall budget, default depth, per-worker
        # breakers (a dead worker costs one probe per cooldown, never the
        # whole fan-out's latency on every query)
        self.fleet_slow_deadline_s = float(fleet_slow_deadline_s)
        self.fleet_slow_k = int(fleet_slow_k)
        self.fleet_breaker_factory = fleet_breaker_factory or (
            lambda sid: CircuitBreaker(failure_threshold=3, window_s=30.0,
                                       cooldown_s=10.0,
                                       name=f"fleet-slow:{sid}"))
        self._fleet_breakers: Dict[str, CircuitBreaker] = {}
        self._m_probes = self.registry.counter(
            "mmlspark_topology_probes_total",
            "health probes by worker and outcome",
            labels=("worker", "result"))
        self._m_evictions = self.registry.counter(
            "mmlspark_topology_evictions_total",
            "workers evicted after consecutive probe failures",
            labels=("worker",))
        # training-fleet membership plane (ISSUE 14): a monotonically
        # increasing epoch that bumps EXACTLY once per join / evict /
        # leave — the signal an elastic training loop (MembershipWatcher)
        # observes to checkpoint-and-exit instead of riding a dead
        # collective.  Registered at construction (coverage-gated).
        self._membership_epoch = 0
        _sid = next(_SERVICE_IDS)
        self._membership_label = f"topology-{_sid}"
        # served in /fleet/membership as "instance": a restarted driver
        # is a DIFFERENT membership plane even when its fresh epoch has
        # already caught up past a watcher's last-seen value — pid makes
        # it unique across processes, the counter within one
        self._boot_id = f"{os.getpid():x}-{_sid}"
        self._m_membership = self.registry.gauge(
            "mmlspark_fleet_membership_epoch",
            "monotonic fleet-membership epoch (bumps once per worker "
            "join/evict/leave)", labels=("service",))
        self._m_membership.set(0.0, service=self._membership_label)
        self._m_membership_changes = self.registry.counter(
            "mmlspark_fleet_membership_changes_total",
            "membership transitions by kind", labels=("change",))
        # postmortem plane (ISSUE 15): recorder families on the driver's
        # registry (fleet_dump books per-worker outcomes into them), the
        # driver's own recorder with crash/preemption hooks, and roster
        # enrolment so any recorder dumping THIS registry captures the
        # membership epoch
        from ..observability.flightrecorder import (_roster,
                                                    flightrecorder_instruments,
                                                    get_flight_recorder)
        self._m_fr = flightrecorder_instruments(self.registry)
        get_flight_recorder(self.registry)
        _roster(self.registry, "_topology_services").add(self)
        self._lock = make_lock("TopologyService._lock")
        self._workers: Dict[str, Dict] = {}
        self._fail_counts: Dict[str, int] = {}
        self._evicted: Dict[str, Dict] = {}
        self._flags: Dict[str, str] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._httpd_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # fleet telemetry plane (ISSUE 11): federated /metrics, SLO
        # burn-rate verdicts, autoscale recommendations — all driven off
        # ONE injectable clock so the deterministic suites step windows
        # and cooldowns with FakeClock.  Components are injectable whole
        # for custom thresholds; the defaults share this service's
        # registry so every instrument lands in one scrape.
        self.federation_poll_s = federation_poll_s
        self.federator = federator if federator is not None else \
            MetricsFederator(workers_fn=self.routing_table,
                             registry=self.registry,
                             timeout_s=federation_timeout_s,
                             deadline_s=federation_deadline_s,
                             clock=telemetry_clock)
        self.slo_engine = slo_engine if slo_engine is not None else \
            SLOEngine(slos, registry=self.registry, clock=telemetry_clock)
        self.autoscaler = autoscaler if autoscaler is not None else \
            AutoscaleAdvisor(registry=self.registry, clock=telemetry_clock)
        # fleet capacity model (ISSUE 17): folds the federated cost
        # ledgers into goodput% + per-class device-seconds/1k-tokens and
        # headroom — fed once per federation tick, served at
        # GET /fleet/capacity
        self.capacity = CapacityModel(clock=telemetry_clock)
        self._fleet_lock = make_lock("TopologyService._fleet_lock")
        self._last_view = None
        self._last_slo: Optional[Dict] = None
        self._last_autoscale: Optional[Dict] = None
        self._last_capacity: Optional[Dict] = None
        self._federation_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ http
    def _make_handler(self):
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, status, obj):
                self._raw(status, json.dumps(obj).encode(),
                          "application/json")

            def _raw(self, status, body, ctype):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length).decode() or "{}")
                if self.path == "/register":
                    payload.setdefault("role", "worker")
                    payload.setdefault("generation", 0)
                    bump = None
                    with svc._lock:
                        sid = payload["server_id"]
                        prev = svc._workers.get(sid)
                        # a JOIN is a sid the table does not route to, or
                        # a returning worker announcing a NEW generation
                        # (a crashed box back before the prober noticed);
                        # a same-generation re-register is a heartbeat
                        # and must NOT bump the epoch
                        if prev is None or \
                                prev.get("generation") != payload["generation"]:
                            bump = svc._bump_epoch_locked("joined", sid,
                                                          payload)
                        svc._workers[sid] = payload
                        # (re-)registration wipes any stale health verdict
                        svc._fail_counts.pop(sid, None)
                        svc._evicted.pop(sid, None)
                        num, epoch = len(svc._workers), svc._membership_epoch
                    if bump is not None:
                        svc._book_membership(*bump)
                    self._json(200, {"ok": True, "num_workers": num,
                                     "membership_epoch": epoch})
                elif self.path == "/deregister":
                    bump = None
                    with svc._lock:
                        sid = payload.get("server_id")
                        gone = svc._workers.pop(sid, None)
                        if gone is not None:
                            bump = svc._bump_epoch_locked("left", sid, gone)
                    if bump is not None:
                        svc._book_membership(*bump)
                    self._json(200, {"ok": True})
                elif self.path == "/flag":
                    with svc._lock:
                        svc._flags[payload["key"]] = payload["value"]
                    self._json(200, {"ok": True})
                else:
                    self._json(404, {"error": "not found"})

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/routing":
                    # trainers (ISSUE 19) register so /fleet/metrics
                    # federates their telemetry, but they serve /progress,
                    # not scores — keep them out of the routing table so
                    # RoutingClient never hashes score traffic onto one
                    with svc._lock:
                        table = {sid: w for sid, w in svc._workers.items()
                                 if w.get("role") != "trainer"}
                    self._json(200, table)
                elif path.startswith("/flag/"):
                    with svc._lock:
                        self._json(200, {"value": svc._flags.get(path[6:])})
                elif path == "/stats":
                    self._json(200, svc.aggregate_stats())
                elif path == "/fleet/slow":
                    # shared validation (ISSUE 11 bugfix): a malformed or
                    # negative ?k= is a 400 verdict on the request — it
                    # used to be swallowed into the default (or blow up in
                    # the handler), both of which hide the caller's bug
                    params, err = _parse_query(query, {
                        "k": _nonneg_int, "deadline_ms": _pos_float})
                    if err is not None:
                        self._json(400, {"error": err})
                        return
                    dl = params.get("deadline_ms")
                    self._json(200, svc.fleet_slow(
                        k=params.get("k"),
                        deadline_s=dl / 1000.0 if dl is not None else None))
                elif path == "/fleet/metrics":
                    params, err = _parse_query(query, {
                        "refresh": _flag01, "deadline_ms": _pos_float})
                    if err is not None:
                        self._json(400, {"error": err})
                        return
                    dl = params.get("deadline_ms")
                    view, _slo, _auto = svc._fleet_state(
                        refresh=params.get("refresh"),
                        deadline_s=dl / 1000.0 if dl is not None else None)
                    body = view.to_prometheus(extra_registry=svc.registry)
                    self._raw(200, body.encode(),
                              "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/fleet/slo":
                    params, err = _parse_query(query, {"refresh": _flag01})
                    if err is not None:
                        self._json(400, {"error": err})
                        return
                    view, verdicts, _auto = svc._fleet_state(
                        refresh=params.get("refresh"))
                    self._json(200, {**verdicts, "workers": view.to_dict()["workers"]})
                elif path == "/fleet/autoscale":
                    params, err = _parse_query(query, {"refresh": _flag01})
                    if err is not None:
                        self._json(400, {"error": err})
                        return
                    view, _slo, recs = svc._fleet_state(
                        refresh=params.get("refresh"))
                    self._json(200, {"classes": recs,
                                     "workers": view.to_dict()["workers"],
                                     "evaluated_at": view.scraped_at})
                elif path == "/fleet/capacity":
                    params, err = _parse_query(query, {
                        "refresh": _flag01, "deadline_ms": _pos_float})
                    if err is not None:
                        self._json(400, {"error": err})
                        return
                    dl = params.get("deadline_ms")
                    self._json(200, svc.fleet_capacity(
                        refresh=params.get("refresh"),
                        deadline_s=dl / 1000.0 if dl is not None else None))
                elif path.startswith("/fleet/trace/"):
                    params, err = _parse_query(query,
                                               {"deadline_ms": _pos_float})
                    if err is not None:
                        self._json(400, {"error": err})
                        return
                    dl = params.get("deadline_ms")
                    body = svc.fleet_trace(
                        path[len("/fleet/trace/"):],
                        deadline_s=dl / 1000.0 if dl is not None else None)
                    # 404 ONLY when no worker (and not the driver) holds
                    # the id — a partial assembly past dead workers is 200
                    self._json(200 if body["found"] else 404, body)
                elif path == "/fleet/dump":
                    params, err = _parse_query(query,
                                               {"deadline_ms": _pos_float})
                    if err is not None:
                        self._json(400, {"error": err})
                        return
                    dl = params.get("deadline_ms")
                    self._json(200, svc.fleet_dump(
                        deadline_s=dl / 1000.0 if dl is not None else None))
                elif path == "/fleet/membership":
                    self._json(200, svc.membership())
                elif path == "/health":
                    self._json(200, {"ok": True})
                else:
                    self._json(404, {"error": "not found"})

        return Handler

    # ---------------------------------------------------------------- health
    def _bump_epoch_locked(self, change: str, sid: str,
                           worker: Optional[Dict]) -> tuple:
        """Advance the membership epoch — caller MUST hold ``self._lock``
        — and return the transition tuple for :meth:`_book_membership`
        (booked OUTSIDE the lock: the ring event does I/O).  Every
        mutation site rides this one helper so the exactly-once
        bump-per-change contract is structural, not copy-pasted."""
        self._membership_epoch += 1
        return (self._membership_epoch, change, sid, worker)

    def _book_membership(self, epoch: int, change: str, sid: str,
                         worker: Optional[Dict]) -> None:
        """Book one membership transition: epoch gauge, per-kind counter,
        and the ``fleet_membership_changed`` ring event a training loop's
        watcher (or an operator tailing events) observes.  The gauge is
        written from the CURRENT epoch while holding the lock, not this
        transition's value outside it: two transitions booking out of
        lock order must never regress a gauge documented as monotonic —
        the ring event keeps the per-transition epoch."""
        with self._lock:
            # set while HOLDING the lock: a re-read-then-set outside it
            # still lets an older transition's write land last
            self._m_membership.set(float(self._membership_epoch),
                                   service=self._membership_label)
        self._m_membership_changes.inc(change=change)
        from ..core.logging import log_event
        log_event({"event": "fleet_membership_changed", "epoch": int(epoch),
                   "change": change, "worker": sid,
                   "role": (worker or {}).get("role"),
                   "generation": (worker or {}).get("generation")})

    def membership(self) -> Dict:
        """The ``GET /fleet/membership`` body: current epoch plus every
        live worker's role/generation/address — what an elastic training
        loop polls to notice the fleet changed under it."""
        with self._lock:
            workers = {sid: {"role": w.get("role", "worker"),
                             "generation": int(w.get("generation", 0)),
                             "host": w.get("host"), "port": w.get("port"),
                             "request_class": w.get("request_class"),
                             # "up" | "draining" — a draining worker is
                             # still a member (its in-flight slots are
                             # finishing) but routing excludes it at pick
                             # time; published by a same-generation
                             # re-register so it never bumps the epoch
                             "state": w.get("state", "up")}
                       for sid, w in self._workers.items()}
            return {"epoch": int(self._membership_epoch), "workers": workers,
                    "evicted": sorted(self._evicted),
                    "instance": self._boot_id}

    def probe_once(self) -> List[str]:
        """One health sweep over the registered workers; returns the ids
        evicted by this sweep.  Also the unit the background prober loops."""
        with self._lock:
            snapshot = list(self._workers.items())
        evicted: List[str] = []
        bumps = []
        for sid, w in snapshot:
            healthy = self.prober(w, self.probe_timeout_s)
            self._m_probes.inc(worker=sid,
                               result="ok" if healthy else "fail")
            with self._lock:
                if sid not in self._workers:
                    continue  # deregistered mid-sweep
                if healthy:
                    self._fail_counts.pop(sid, None)
                    continue
                fails = self._fail_counts.get(sid, 0) + 1
                self._fail_counts[sid] = fails
                if fails >= self.evict_after:
                    gone = self._workers.pop(sid)
                    self._evicted[sid] = gone
                    self._fail_counts.pop(sid, None)
                    evicted.append(sid)
                    bumps.append(self._bump_epoch_locked("evicted", sid,
                                                         gone))
        for sid in evicted:
            self._m_evictions.inc(worker=sid)
        for bump in bumps:
            self._book_membership(*bump)
        return evicted

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — prober must never die
                pass

    # ------------------------------------------------------ fleet telemetry
    def workers_by_class(self) -> Dict[str, List[Dict]]:
        """Live workers grouped by the ``request_class`` they registered
        under (``"default"`` when unset) — the autoscale signal's unit."""
        out: Dict[str, List[Dict]] = {}
        for w in self.routing_table().values():
            out.setdefault(str(w.get("request_class") or "default"),
                           []).append(w)
        return out

    def federation_tick(self, deadline_s: Optional[float] = None) -> Dict:
        """One federation poll: scrape every live worker's ``/metrics``,
        evaluate the SLOs against the merged view, recompute the autoscale
        recommendation — the unit the background poll loops and the
        on-demand fleet endpoints call.  Always completes with whatever
        partial view the scrape produced: a dead worker is a failure row,
        never a blind endpoint."""
        view = self.federator.scrape_once(deadline_s=deadline_s)
        verdicts = self.slo_engine.evaluate(view)
        by_class = self.workers_by_class()
        recs = self.autoscaler.recommend(view, by_class)
        capacity = self.capacity.report(view, by_class)
        with self._fleet_lock:
            self._last_view = view
            self._last_slo = verdicts
            self._last_autoscale = recs
            self._last_capacity = capacity
        return {"view": view, "slo": verdicts, "autoscale": recs,
                "capacity": capacity}

    def _fleet_state(self, refresh: Optional[bool] = None,
                     deadline_s: Optional[float] = None):
        """(view, slo_verdicts, autoscale_recs) for the fleet endpoints.
        With a background poll running the cached poll result serves
        (``?refresh=1`` forces a sweep); without one every GET scrapes on
        demand — the ISSUE 11 "poll interval or on demand" contract."""
        if refresh is None:
            refresh = self.federation_poll_s is None
        with self._fleet_lock:
            have = self._last_view is not None
        if refresh or not have:
            self.federation_tick(deadline_s=deadline_s)
        with self._fleet_lock:
            return self._last_view, self._last_slo, self._last_autoscale

    def _federation_loop(self) -> None:
        while not self._stop.wait(self.federation_poll_s):
            try:
                self.federation_tick()
            except Exception:  # noqa: BLE001 — the poll must never die
                pass

    # ------------------------------------------------------------------ api
    def start(self) -> "TopologyService":
        # a restart after stop() must re-arm the loops: the stop event
        # left set would kill the fresh probe/federation threads on entry
        self._stop.clear()
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_port
        self._httpd_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._httpd_thread.start()
        if self.probe_interval_s is not None:
            self._probe_thread = threading.Thread(target=self._probe_loop,
                                                  daemon=True)
            self._probe_thread.start()
        # restore the staleness series after a previous stop() (no-op on
        # first start — construction already registered it)
        self.federator.reopen()
        if self.federation_poll_s is not None:
            self._federation_thread = threading.Thread(
                target=self._federation_loop, daemon=True,
                name="mmlspark-federation-poll")
            self._federation_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # join the loops before returning: start() clears the stop event,
        # and an old loop still mid-probe when it is cleared would revive
        # and run ALONGSIDE the restart's fresh threads (double-counted
        # probes evict healthy workers at half the intended threshold)
        for t in (self._probe_thread, self._federation_thread,
                  self._httpd_thread):
            if t is not None and t.is_alive():
                t.join(timeout=10.0)
        self._probe_thread = self._federation_thread = None
        self._httpd_thread = None
        # the federator's stale-workers callback gauge closes over this
        # service's routing table — a stopped driver must not scrape on
        self.federator.close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def routing_table(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._workers)

    def aggregate_stats(self) -> Dict:
        """Pull and sum every registered worker's counters."""
        with self._lock:
            workers = list(self._workers.values())
            evicted = sorted(self._evicted)
        total = {"received": 0, "replied": 0, "errors": 0, "shed": 0,
                 "workers": {}, "evicted": evicted}
        lat_sum_ms, lat_count = 0.0, 0
        ckpt_ages: Dict[str, float] = {}
        for w in workers:
            try:
                s = _http_json(f"http://{w['host']}:{w['port']}/stats")
            except Exception as e:  # noqa: BLE001 — a dead worker is a stat
                total["workers"][w["server_id"]] = {"error": str(e)}
                continue
            total["workers"][w["server_id"]] = s
            total["received"] += s.get("received", 0)
            total["replied"] += s.get("replied", 0)
            total["errors"] += s.get("errors", 0)
            total["shed"] += s.get("shed", 0)
            # checkpointing workers report their last-success age (ISSUE
            # 11): "checkpoints stopped landing" is a FLEET page, so the
            # worst age surfaces here, not just per box
            age = s.get("checkpoint_last_success_age_seconds")
            if isinstance(age, (int, float)) and age == age:  # NaN out
                ckpt_ages[w["server_id"]] = age
            # (sum, count)-paired latency when the worker reports it; the
            # pre-pairing fallback weights by replied
            n = s.get("latency_count", s.get("replied", 0))
            lat_count += n
            lat_sum_ms += s.get("mean_latency_ms", 0.0) * n
        if lat_count:
            total["latency_count"] = lat_count
            total["latency_avg_ms"] = lat_sum_ms / lat_count
            total["mean_latency_ms"] = total["latency_avg_ms"]
        if ckpt_ages:
            total["checkpoint_last_success_age_seconds"] = ckpt_ages
            total["checkpoint_max_last_success_age_seconds"] = \
                max(ckpt_ages.values())
        return total

    # ------------------------------------------------------------ /fleet/slow
    def _fleet_breaker(self, sid: str) -> CircuitBreaker:
        with self._lock:
            b = self._fleet_breakers.get(sid)
        if b is None:
            fresh = self.fleet_breaker_factory(sid)
            with self._lock:
                b = self._fleet_breakers.setdefault(sid, fresh)
            if b is fresh:
                # only the setdefault WINNER is instrumented (outside our
                # lock — it registers gauges): instrumenting a losing
                # duplicate would rebind the shared gauge callbacks and
                # listener record to a breaker nobody uses
                instrument_breaker(b, self.registry)
        return b

    def _prune_fleet_breakers(self, live_ids) -> None:
        """A worker gone from the routing table takes its fan-out breaker
        and gauge series with it (same hygiene as RoutingClient's
        per-worker breakers — fresh-id churn must not grow state)."""
        with self._lock:
            dead = [(sid, self._fleet_breakers.pop(sid))
                    for sid in list(self._fleet_breakers)
                    if sid not in live_ids]
        for _sid, breaker in dead:
            uninstrument_breaker(breaker, self.registry)

    def _fanout_debug(self, path: str, deadline: Deadline,
                      not_found_ok: bool = False) -> Tuple[Dict, Dict]:
        """Concurrent deadline-bounded GET of ``path`` against every live
        worker with the per-worker breaker discipline (ISSUE 15 factored
        this out of :meth:`fleet_slow` so ``/fleet/dump`` shares it
        verbatim).  Returns ``(per_worker, payloads)``: a verdict row per
        worker (``{"ok": True}`` / ``{"skipped": ...}`` / ``{"error":
        ...}``) and the successful workers' JSON payloads.

        Rules carried over: an open breaker costs one skip, not a timeout;
        a client-side deadline expiry mid-exchange is NEVER fed to the
        breaker (PR 2 rule); partial results always serve.

        ``not_found_ok`` (ISSUE 17, ``/fleet/trace/<id>``): a worker's 404
        is a healthy "I don't hold it" verdict — ``{"not_found": True}``
        row, no payload, and NO breaker feed (a trace fanned out across a
        fleet misses on most workers by design; charging their breakers
        would open every breaker under normal trace lookups)."""
        with self._lock:
            workers = list(self._workers.items())
        self._prune_fleet_breakers({sid for sid, _ in workers})
        per_worker: Dict[str, Dict] = {}
        results: Dict[str, tuple] = {}
        results_lock = make_lock("TopologyService._stats_results_lock")

        def fetch(sid: str, w: Dict, breaker: CircuitBreaker) -> None:
            try:
                got = _http_json(
                    f"http://{w['host']}:{w['port']}{path}",
                    timeout=self.probe_timeout_s, deadline=deadline)
            except Exception as e:  # noqa: BLE001 — a dead worker is a row
                if not_found_ok and isinstance(e, urllib.error.HTTPError) \
                        and e.code == 404:
                    breaker.record_success()
                    with results_lock:
                        results[sid] = ({"not_found": True}, None)
                    return
                if deadline.expired():
                    # the budget ran out mid-exchange — that is the
                    # caller's deadline, not the worker's health: no
                    # breaker feed (PR 2 rule: client-side expiry must
                    # never trip a healthy worker's breaker)
                    with results_lock:
                        results[sid] = (
                            {"skipped": "deadline_exhausted"}, None)
                    return
                breaker.record_failure()
                with results_lock:
                    results[sid] = ({"error": str(e)}, None)
                return
            breaker.record_success()
            with results_lock:
                results[sid] = ({"ok": True}, got)

        # genuinely concurrent fan-out: one slow worker costs the query its
        # OWN latency, never every later worker's slice of the budget (the
        # sequential version starved the tail of the worker list)
        threads = []
        for sid, w in workers:
            breaker = self._fleet_breaker(sid)
            if not breaker.allow():
                per_worker[sid] = {"skipped": "circuit_open"}
                continue
            if deadline.expired():
                per_worker[sid] = {"skipped": "deadline_exhausted"}
                continue
            t = threading.Thread(target=fetch, args=(sid, w, breaker),
                                 daemon=True, name=f"fleet-debug-{sid}")
            t.start()
            threads.append((sid, t))
        for sid, t in threads:
            t.join(timeout=max(0.0, deadline.remaining()))
        with results_lock:
            done = dict(results)
        payloads: Dict[str, Dict] = {}
        for sid, _t in threads:
            outcome = done.get(sid)
            if outcome is None:
                # still in flight when the budget ran out; its thread will
                # finish the breaker bookkeeping in the background
                per_worker[sid] = {"skipped": "deadline_exhausted"}
                continue
            verdict, payload = outcome
            per_worker[sid] = verdict
            if payload is not None:
                payloads[sid] = payload
        return per_worker, payloads

    def fleet_slow(self, k: Optional[int] = None,
                   deadline_s: Optional[float] = None) -> Dict:
        """Fleet-wide slowest requests (``GET /fleet/slow?k=N``, PR 4
        follow-up): fan out to every live worker's ``/debug/slow`` under one
        overall deadline, merge to a global top-K with worker attribution.

        Per-worker circuit breakers isolate dead workers: a worker that
        keeps failing costs one probe per cooldown instead of a timeout per
        query, and partial results are always served — one dead worker must
        never blind the fleet view.  Skipped/failed workers are reported in
        ``workers`` so a partial merge is visibly partial."""
        k = self.fleet_slow_k if k is None else max(0, int(k))
        deadline = Deadline.after(deadline_s if deadline_s is not None
                                  else self.fleet_slow_deadline_s)
        per_worker, payloads = self._fanout_debug(f"/debug/slow?k={k}",
                                                  deadline)
        merged: List[Dict] = []
        for sid, got in payloads.items():
            rows = got.get("slowest", []) if isinstance(got, dict) else []
            for row in rows:
                row["worker"] = sid
            per_worker[sid] = {"count": len(rows)}
            merged.extend(rows)
        merged.sort(key=lambda r: r.get("durationS", 0.0), reverse=True)
        return {"k": k, "workers": per_worker, "slowest": merged[:k]}

    def fleet_dump(self, deadline_s: Optional[float] = None) -> Dict:
        """Fleet-wide flight-recorder snapshots (``GET /fleet/dump``,
        ISSUE 15): fan out to every live worker's ``/debug/dump`` under
        one overall deadline with the :meth:`fleet_slow` breaker
        discipline, and serve PARTIAL results — a dead worker is exactly
        when an operator pulls the fleet's black boxes, so one dead worker
        blinding the endpoint would defeat it.  Per-worker outcomes book
        ``mmlspark_flightrecorder_dumps_total{trigger="fleet"}`` on the
        driver's registry."""
        deadline = Deadline.after(deadline_s if deadline_s is not None
                                  else self.fleet_slow_deadline_s)
        per_worker, payloads = self._fanout_debug("/debug/dump", deadline)
        dumps_c = self._m_fr["dumps"]
        for sid, verdict in per_worker.items():
            result = "ok" if sid in payloads else (
                "skipped" if "skipped" in verdict else "error")
            dumps_c.inc(trigger="fleet", result=result)
        return {"workers": per_worker, "dumps": payloads}

    def fleet_trace(self, trace_id: str,
                    deadline_s: Optional[float] = None) -> Dict:
        """Assemble ONE trace's span trees across the driver and every
        live worker (``GET /fleet/trace/<id>``, the PR 4 cross-worker
        follow-up): fan ``/trace/<id>`` out under one overall deadline
        with the breaker discipline, treating a worker's 404 as a healthy
        "not here" verdict.  Partial results serve past dead workers;
        ``found`` is False only when NO reachable holder (driver
        included) had the id — the endpoint's 404 signal."""
        deadline = Deadline.after(deadline_s if deadline_s is not None
                                  else self.fleet_slow_deadline_s)
        per_worker, payloads = self._fanout_debug(
            f"/trace/{urllib.parse.quote(trace_id, safe='')}", deadline,
            not_found_ok=True)
        trees = dict(payloads)
        from ..observability.collector import get_collector
        own = get_collector(self.registry).trace_tree(trace_id)
        if own is not None:
            trees["driver"] = own
        return {"trace_id": trace_id, "found": bool(trees),
                "workers": per_worker, "trees": trees}

    def fleet_capacity(self, refresh: Optional[bool] = None,
                       deadline_s: Optional[float] = None) -> Dict:
        """Per-class capacity/headroom report (``GET /fleet/capacity``,
        ISSUE 17): goodput%, measured device-seconds per 1k decode tokens,
        arrival rate vs the class's device-seconds budget.  Rides the
        federation cache exactly like the other fleet endpoints —
        ``?refresh=1`` forces a sweep; the background poll keeps the
        windowed rate history warm in between."""
        self._fleet_state(refresh=refresh, deadline_s=deadline_s)
        with self._fleet_lock:
            return dict(self._last_capacity or {})


class WorkerServer:
    """Executor-side server: a ``PipelineServer`` that registers its
    ``host:port`` (and owned partition ids) with the driver's topology
    service at start and deregisters at stop — the worker half of
    ``HTTPSourceStateHolder`` registration."""

    def __init__(self, model, server_id: str, driver_address: str,
                 partition_ids: Optional[List[int]] = None,
                 request_class: str = "default", role: str = "serving",
                 generation: int = 0, **kw):
        self.server_id = server_id
        self.driver_address = driver_address.rstrip("/")
        self.partition_ids = partition_ids or []
        # the traffic class this replica serves (e.g. "score" / "decode"):
        # the autoscale signal groups workers by it (ISSUE 11)
        self.request_class = request_class
        # membership plane (ISSUE 14): the role this worker plays in the
        # fleet ("serving" / "trainer" / ...) and its restart generation —
        # a returning worker announces generation+1 so the driver books a
        # join even if the prober never noticed the crash
        self.role = role
        self.generation = int(generation)
        # the class rides into the wrapped server too (ISSUE 17): its
        # request records and per-class cost rollups must agree with what
        # this worker registered as — an explicit kw still wins
        kw.setdefault("request_class", request_class)
        self.server = PipelineServer(model, **kw)

    def _registration(self, state: Optional[str] = None) -> Dict:
        body = {"server_id": self.server_id, "host": self.server.host,
                "port": self.server.port,
                "api_path": self.server.api_path,
                "partition_ids": self.partition_ids,
                "request_class": self.request_class,
                "role": self.role, "generation": self.generation}
        if state is not None:
            body["state"] = state
        return body

    def start(self) -> "WorkerServer":
        self.server.start()
        _http_json(f"{self.driver_address}/register", self._registration())
        return self

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Zero-drop rolling-restart unit (ISSUE 16): publish the
        ``draining`` membership state FIRST (a same-generation re-register
        — a heartbeat row replacement, so ``RoutingClient`` stops picking
        this worker without a membership-epoch bump), then drain the
        wrapped :class:`PipelineServer` (shed new admissions, let
        in-flight work finish, stop), then deregister.  Stragglers that
        raced the state publication are shed with ``Retry-After`` and fail
        over client-side.  Returns the server drain's verdict."""
        try:
            _http_json(f"{self.driver_address}/register",
                       self._registration(state="draining"))
        except Exception:  # noqa: BLE001 — a blind driver must not block
            pass           # the drain; probes will evict us anyway
        ok = self.server.drain(timeout_s=timeout_s)
        try:
            _http_json(f"{self.driver_address}/deregister",
                       {"server_id": self.server_id})
        except Exception:  # noqa: BLE001 — driver may already be gone
            pass
        return ok

    def stop(self) -> None:
        try:
            _http_json(f"{self.driver_address}/deregister",
                       {"server_id": self.server_id})
        except Exception:  # noqa: BLE001 — driver may already be gone
            pass
        self.server.stop()

    @property
    def address(self) -> str:
        return self.server.address


class MembershipWatcher:
    """Watches ``GET /fleet/membership`` for a fleet SHRINK (ISSUE 14).

    The elastic-training half of the membership plane: a training loop
    hands this to :func:`utils.resilience.preemption_scope` (``watcher=``)
    — or starts it standalone around the whole run — and when the epoch
    advances with FEWER workers than before, the watcher requests
    preemption, so the loop writes its final checkpoint and exits instead
    of riding a collective whose peer just died.  Growth (a join) is
    observed but never preempts: new capacity joins at the next restart's
    re-shard, it does not invalidate the running step.

    ``poll_once()`` is the deterministic unit tests drive; ``start()``
    loops it on a daemon thread every ``poll_s``.  A dead or slow driver
    is swallowed — losing the membership view must degrade to signal-only
    preemption, never kill the training it protects."""

    def __init__(self, driver_address: str, poll_s: float = 2.0,
                 timeout_s: float = 2.0,
                 on_shrink: Optional[Callable[[Dict], None]] = None,
                 roles: Optional[Iterable[str]] = None):
        self.driver_address = driver_address.rstrip("/")
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.on_shrink = on_shrink
        # on a TopologyService shared with serving replicas, a scaled-down
        # or evicted SERVING worker must not preempt training — pass
        # roles={"trainer"} to watch only the collective's own peers.
        # None keeps every worker in view (single-purpose fleets).
        self.roles = None if roles is None else frozenset(roles)
        self.last_epoch: Optional[int] = None
        self.last_workers: Optional[Dict[str, int]] = None  # sid -> generation
        self.last_instance: Optional[str] = None
        self.shrinks = 0
        # guards the view compare-and-update: poll_once runs on the
        # watcher thread AND as a public probe (tests, manual ticks) — an
        # unlocked interleaving can diff against a half-updated view and
        # preempt a healthy collective (CCY002)
        self._state_lock = make_lock("MembershipWatcher._state_lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> Optional[Dict]:
        """One membership read; returns the shrink info dict when this
        poll observed a shrink (and fired the preemption), else None."""
        try:
            m = _http_json(f"{self.driver_address}/fleet/membership",
                           timeout=self.timeout_s)
        except Exception:  # noqa: BLE001 — a blind watcher must not kill
            return None    # the training loop it guards
        epoch = int(m.get("epoch", 0))
        workers = {sid: int((w or {}).get("generation", 0))
                   for sid, w in dict(m.get("workers", {})).items()
                   if self.roles is None
                   or (w or {}).get("role") in self.roles}
        inst = m.get("instance")
        # compare-and-update under the state lock (the HTTP fetch above
        # and the on_shrink callback below stay outside it); the callback
        # fires AFTER the view commits, so a reentrant poll_once from
        # inside on_shrink diffs against the new baseline, not a torn one
        with self._state_lock:
            first = self.last_epoch is None
            restarted = not first and (
                (inst is not None and self.last_instance is not None
                 and inst != self.last_instance)
                or epoch < self.last_epoch)
            if restarted:
                # a NEW instance token (or, pre-upgrade, an epoch that went
                # backwards): a restarted (fresh, in-memory) membership
                # plane, not a transition — the old view is incomparable.
                # The token matters because a restart whose re-registrations
                # already pushed the fresh epoch PAST our last-seen value
                # looks like a plain advance.  Rebaseline instead of diffing
                # across service instances: a restarted driver's half-empty
                # registry would read as "every peer lost" and preempt a
                # healthy collective, and a lost membership view must
                # degrade to signal-only preemption, never kill the run it
                # guards.
                self.last_epoch, self.last_workers = epoch, workers
                self.last_instance = inst
                return None
            # a shrink is a worker the last view HAD that this one lost —
            # keyed by id AND generation, not a count compare: an eviction
            # masked by an unrelated join keeps the count flat, and a crash
            # whose supervisor re-registers the same id with generation+1
            # inside one poll interval keeps even the ID SET flat — in both
            # cases the collective's original peer process is dead
            lost = set() if first else {
                sid for sid, gen in self.last_workers.items()
                if workers.get(sid, -1) != gen}
            shrunk = not first and epoch > self.last_epoch and bool(lost)
            self.last_epoch, self.last_workers = epoch, workers
            self.last_instance = inst
            if not shrunk:
                return None
            self.shrinks += 1
        info = {"epoch": epoch, "workers": len(workers),
                "lost": sorted(lost)}
        if self.on_shrink is not None:
            self.on_shrink(info)
        else:
            from ..utils.resilience import request_preemption
            request_preemption("fleet_membership_shrink")
        return info

    def _loop(self, stop: threading.Event) -> None:
        # the event is captured per thread: a loop orphaned by a
        # timed-out stop() keeps its own SET event and dies at the next
        # wake even after start() arms a fresh one — never two pollers
        while not stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watcher must never
                # die: a malformed /fleet/membership body (proxy error
                # page behind a 200) or a user on_shrink callback that
                # raises would otherwise silently kill the thread, and
                # every later shrink would go unobserved — the exact
                # dead-collective hang this watcher exists to prevent
                pass

    def start(self) -> "MembershipWatcher":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(self._stop,), daemon=True,
            name="mmlspark-membership-watcher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=self.poll_s + self.timeout_s + 1.0)
        self._thread = None


class RoutingClient:
    """Client-side router over the driver's table: round robin by default,
    or deterministic key-hash routing (``MultiChannelMap.nextList``'s
    request sharding, client-side).  Refreshes the table on demand.

    Failover: a failed exchange refreshes the table and retries ONCE per
    remaining healthy worker candidate (``failover_retries``, default 1 —
    exactly one failover hop), always excluding workers that already failed
    this request so a retry can never land back on the dead socket.

    Per-worker circuit breakers (ROADMAP follow-up): every routed exchange
    feeds that worker's breaker; a worker whose breaker is OPEN is skipped
    at pick time — repeated failures stop costing a failed primary attempt
    per request during the eviction window.  If every candidate's breaker
    is open the pick falls back to ignoring breaker state (shedding 100% of
    traffic client-side is worse than probing).  ``breaker_factory=None``
    keeps the default breaker; pass a factory for custom thresholds, or
    ``per_worker_breakers=False`` to disable.  Request/failover counters
    land per worker in the registry.

    Tail tolerance (ISSUE 16):

    - **Retry budget** — failover retries (and hedges) draw from a shared
      token-bucket :class:`RetryBudget` that deposits per first-try
      request: under a full outage, attempted exchanges stay within
      ``(1 + ratio) x`` offered load instead of amplifying into a retry
      storm.  ``retry_budget_ratio=None`` disables the budget; pass
      ``retry_budget=`` to inject one (e.g. ``initial=0.0`` for the exact
      asymptotic bound).  Bookings:
      ``mmlspark_retry_budget_{granted,denied}_total``.
    - **Hedged requests** (``hedge=True``, off by default: a hedge is a
      deliberate traffic duplicate) — once the first exchange outlives
      the rolling-p95 hedge delay (over the last ``hedge_window``
      successful exchange latencies; no hedging until
      ``hedge_min_samples`` exist), ONE speculative duplicate goes to a
      *different* worker and the first response wins.  Bookings:
      ``mmlspark_hedges_total{outcome}``.
    - **Retry-After cooldown** — a 503 shed carrying ``Retry-After`` puts
      that worker on a pick-time cooldown instead of charging its breaker
      (a shed is backpressure by design, not a fault) — the very next
      request routes elsewhere instead of re-picking the shedding worker.
    - **Draining exclusion** — workers whose membership row carries
      ``state="draining"`` are skipped at pick time (falling back to them
      only when nobody else is left).
    """

    def __init__(self, driver_address: str, refresh_s: float = 5.0,
                 failover_retries: int = 1, registry=None,
                 per_worker_breakers: bool = True,
                 breaker_factory: Optional[Callable[[str], CircuitBreaker]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 retry_budget: Optional[RetryBudget] = None,
                 retry_budget_ratio: Optional[float] = 0.1,
                 hedge: bool = False, hedge_window: int = 64,
                 hedge_min_samples: int = 8,
                 hedge_min_delay_s: float = 0.05):
        self.driver_address = driver_address.rstrip("/")
        self.refresh_s = refresh_s
        self.failover_retries = max(0, failover_retries)
        self.clock = clock
        self.registry = registry if registry is not None else get_registry()
        self.per_worker_breakers = per_worker_breakers
        self.breaker_factory = breaker_factory or (
            lambda sid: CircuitBreaker(failure_threshold=5, window_s=30.0,
                                       cooldown_s=5.0, clock=self.clock,
                                       name=f"worker:{sid}"))
        self.breakers: Dict[str, CircuitBreaker] = {}
        if retry_budget is not None:
            self.retry_budget: Optional[RetryBudget] = retry_budget
        elif retry_budget_ratio is not None:
            self.retry_budget = RetryBudget(ratio=retry_budget_ratio)
        else:
            self.retry_budget = None
        self.hedge = hedge
        self.hedge_min_samples = max(1, int(hedge_min_samples))
        self.hedge_min_delay_s = float(hedge_min_delay_s)
        self._lat_window: "deque" = deque(maxlen=max(1, int(hedge_window)))
        # per-worker Retry-After cooldown: sid -> clock() time the shed
        # verdict expires (consulted at pick time, like breakers)
        self._cooldown: Dict[str, float] = {}
        self._m_requests = self.registry.counter(
            "mmlspark_routing_requests_total",
            "routed exchanges by worker and outcome",
            labels=("worker", "result"))
        self._m_failovers = self.registry.counter(
            "mmlspark_routing_failovers_total",
            "failover hops away from a failed worker", labels=("worker",))
        self._m_hedges = self.registry.counter(
            "mmlspark_hedges_total",
            "speculative duplicate exchanges by outcome",
            labels=("outcome",))
        self._m_budget_granted = self.registry.counter(
            "mmlspark_retry_budget_granted_total",
            "retry/hedge attempts the token-bucket budget allowed")
        self._m_budget_denied = self.registry.counter(
            "mmlspark_retry_budget_denied_total",
            "retry/hedge attempts suppressed by an exhausted budget")
        # attribution (ISSUE 17): a hedge leg that completes 200 after the
        # race was lost produced a whole reply the caller discards — its
        # decode tokens book as hedge_loser waste, client-side (only the
        # client knows which leg lost)
        self._c_tok_outcome = attribution_instruments(self.registry)["tokens"]
        self._table: List[Dict] = []
        self._fetched = 0.0
        self._rr = 0
        self._lock = make_lock("RoutingClient._lock")

    def _breaker_for(self, sid: str) -> Optional[CircuitBreaker]:
        if not self.per_worker_breakers:
            return None
        with self._lock:
            b = self.breakers.get(sid)
            if b is None:
                b = self.breakers[sid] = instrument_breaker(
                    self.breaker_factory(sid), self.registry)
            return b

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if force or not self._table or now - self._fetched > self.refresh_s:
            table = _http_json(f"{self.driver_address}/routing")
            with self._lock:
                self._table = sorted(table.values(),
                                     key=lambda w: w["server_id"])
                self._fetched = now
                # a worker id the topology no longer routes to (evicted or
                # deregistered) takes its breaker with it: the breaker dict
                # entry AND its state/failure-rate gauge series would
                # otherwise grow without bound under fresh-id churn and
                # scrape frozen values forever (ROADMAP PR 2 follow-up).
                # A re-registered id simply gets a fresh breaker.
                live = {w["server_id"] for w in self._table}
                dead = [(sid, self.breakers.pop(sid))
                        for sid in list(self.breakers) if sid not in live]
                # cooldown hygiene rides the same sweep: a departed
                # worker's Retry-After verdict must not outlive its row
                for sid in list(self._cooldown):
                    if sid not in live:
                        del self._cooldown[sid]
            for _sid, breaker in dead:  # registry ops outside our lock
                uninstrument_breaker(breaker, self.registry)

    def _pick(self, key: Optional[str], exclude=()) -> Dict:
        self._refresh()
        with self._lock:
            candidates = [w for w in self._table
                          if w["server_id"] not in exclude]
            if not candidates:
                raise RuntimeError(
                    "no serving workers registered" if not self._table
                    else "no healthy serving workers left to fail over to")
            # a draining worker sheds everything it is sent: skip it at
            # pick time, falling back only when nobody else is left (its
            # fast 503 still beats "no workers" for the caller)
            up = [w for w in candidates if w.get("state") != "draining"]
            if up:
                candidates = up
            # Retry-After cooldown: a worker that shed with an explicit
            # back-off verdict is skipped until it expires — same
            # last-resort fall-back as breakers
            now = self.clock()
            cool = [w for w in candidates
                    if self._cooldown.get(w["server_id"], 0.0) <= now]
            if cool:
                candidates = cool
            if self.per_worker_breakers:
                # skip workers whose breaker is open; keep them as a last
                # resort when every candidate is open
                closed = [w for w in candidates
                          if (b := self.breakers.get(w["server_id"])) is None
                          or b.state != "open"]
                if closed:
                    candidates = closed
            if key is not None:
                # stable across processes/restarts (builtin hash is salted),
                # so partition affinity survives like MultiChannelMap's
                import zlib
                return candidates[zlib.crc32(key.encode()) % len(candidates)]
            w = candidates[self._rr % len(candidates)]
            self._rr += 1
            return w

    @staticmethod
    def _shed_retry_after(e) -> Optional[float]:
        """The cooldown a 503 shed's ``Retry-After`` header asks for, or
        None when ``e`` is not a shed (or carries no parseable header)."""
        if not (isinstance(e, urllib.error.HTTPError) and e.code == 503):
            return None
        try:
            ra = e.headers.get("Retry-After") if e.headers is not None \
                else None
            return float(ra) if ra is not None else None
        except (TypeError, ValueError):
            return None

    def _attempt(self, w: Dict, payload, timeout: float,
                 deadline: Optional[Deadline]):
        """One exchange against one worker with ALL per-worker bookkeeping
        — breaker feed, Retry-After shed cooldown, request counter, hedge
        latency window.  Never raises; returns a verdict pair:

        - ``("ok", out)`` — success;
        - ``("raise", e)`` — 4xx: a verdict on the REQUEST, not the worker
          (the caller re-raises; retrying elsewhere wastes a hop and five
          bad payloads must never trip a healthy worker's breaker);
        - ``("deadline", e)`` — the budget ran out mid-exchange: ambiguous
          evidence, so nothing is booked against the worker (PR 2 rule);
        - ``("err", e)`` — a failure the caller may fail over from.
        """
        sid = w["server_id"]
        url = f"http://{w['host']}:{w['port']}{w.get('api_path', '/score')}"
        breaker = self._breaker_for(sid)
        t0 = self.clock()
        try:
            out = _http_json(url, payload, timeout=timeout,
                             deadline=deadline)
        except Exception as e:  # noqa: BLE001 — verdict, not propagation
            if isinstance(e, urllib.error.HTTPError) and e.code < 500:
                return ("raise", e)
            if deadline is not None and deadline.expired():
                return ("deadline", e)
            cooldown_s = self._shed_retry_after(e)
            if cooldown_s is not None:
                # a shed is backpressure by design, not a fault: honor the
                # worker's Retry-After with a pick-time cooldown instead
                # of charging its breaker — and stop re-picking it on the
                # very next request
                with self._lock:
                    self._cooldown[sid] = self.clock() + cooldown_s
                self._m_requests.inc(worker=sid, result="shed")
            else:
                if breaker is not None:
                    breaker.record_failure()
                self._m_requests.inc(worker=sid, result="fail")
            return ("err", e)
        if breaker is not None:
            if breaker.state == "half_open":
                # the routing path filters on state at pick time rather
                # than calling allow() (probe-slot leaks on the bail-out
                # paths would pin the breaker), so a successful exchange
                # against a half-open worker is accounted as the probe it
                # de-facto was: take a slot, then record — the success
                # closes it
                breaker.allow()
            breaker.record_success()
        self._m_requests.inc(worker=sid, result="ok")
        with self._lock:
            # successful exchange latencies drive the rolling-p95 hedge
            # delay; failures stay out (a hung worker must not teach the
            # hedger that "slow is normal")
            self._lat_window.append(max(0.0, self.clock() - t0))
        return ("ok", out)

    def _hedge_delay_s(self) -> Optional[float]:
        """Rolling p95 of recent successful exchange latencies (floored at
        ``hedge_min_delay_s``), or None while the window is too thin to
        trust — no hedging during cold start."""
        with self._lock:
            n = len(self._lat_window)
            if n < self.hedge_min_samples:
                return None
            lats = sorted(self._lat_window)
        return max(self.hedge_min_delay_s, lats[min(n - 1, int(0.95 * n))])

    def _hedged_exchange(self, w: Dict, payload, key: Optional[str],
                         timeout: float, deadline: Optional[Deadline],
                         tried: set):
        """The first attempt with latency hedging: run the primary
        exchange; once it outlives the hedge delay, issue ONE speculative
        duplicate to a *different* worker and return whichever response
        lands first.  The losing leg finishes its own (per-worker)
        bookkeeping on its daemon thread.  Failed legs land in ``tried``
        so a later failover never re-picks them."""
        delay = self._hedge_delay_s()
        if delay is None:
            return self._attempt(w, payload, timeout, deadline)
        results: "queue.Queue" = queue.Queue()
        race = {"winner": None}
        race_lock = make_lock("RoutingClient._race_lock")

        def leg(name: str, wk: Dict) -> None:
            res = self._attempt(wk, payload, timeout, deadline)
            lost = False
            with race_lock:
                if res[0] == "ok":
                    if race["winner"] is None:
                        race["winner"] = name
                    else:
                        lost = True
            if lost:
                # the race already had a winner when this 200 landed: the
                # whole reply is discarded device work (ISSUE 17)
                self._book_hedge_loser(res[1])
            results.put((name, wk["server_id"], res))

        threading.Thread(target=leg, args=("primary", w), daemon=True,
                         name="mmlspark-hedge-primary").start()
        try:
            _name, _sid, res = results.get(timeout=delay)
            return res  # primary beat the hedge delay: no duplicate issued
        except queue.Empty:
            pass
        # the primary outlived the p95 delay — speculate, to a different
        # worker; a hedge is a retry in disguise, so it draws from the
        # same budget (a storm of hedges is still a retry storm)
        hw = None
        try:
            hw = self._pick(key, exclude=tried | {w["server_id"]})
        except RuntimeError:
            pass
        if hw is None:
            self._m_hedges.inc(outcome="no_candidate")
        elif self.retry_budget is not None \
                and not self.retry_budget.try_withdraw():
            self._m_budget_denied.inc()
            self._m_hedges.inc(outcome="budget_denied")
            hw = None
        else:
            if self.retry_budget is not None:
                self._m_budget_granted.inc()
            threading.Thread(target=leg, args=("hedge", hw), daemon=True,
                             name="mmlspark-hedge-dup").start()
        # collect until the first success (or every launched leg failed);
        # each leg's exchange is bounded by `timeout`, so the collection
        # loop is too — no unbounded wait
        legs = 1 if hw is None else 2
        t_end = time.monotonic() + timeout + 1.0
        raise_res = deadline_res = err_res = None
        for _ in range(legs):
            try:
                name, sid, res = results.get(
                    timeout=max(0.05, t_end - time.monotonic()))
            except queue.Empty:
                break
            if res[0] == "ok":
                if hw is not None:
                    self._m_hedges.inc(
                        outcome="hedge_won" if name == "hedge"
                        else "primary_won")
                return res
            tried.add(sid)
            if res[0] == "raise":
                raise_res = res
            elif res[0] == "deadline":
                deadline_res = deadline_res or res
            else:
                err_res = err_res or res
        if hw is not None:
            self._m_hedges.inc(outcome="both_failed")
        return raise_res or err_res or deadline_res or \
            ("err", TimeoutError("hedged exchange produced no result"))

    def _book_hedge_loser(self, reply) -> None:
        """Book a discarded-but-completed hedge reply's decode tokens as
        ``hedge_loser`` waste.  The reply shape is the decode scorer's: a
        token list, possibly wrapped in ``{"tokens": ...}`` (report_ttft)
        and possibly one-row nested; an unparseable reply books nothing —
        attribution must never fail a request path."""
        body = reply.get("tokens") if isinstance(reply, dict) else reply
        if isinstance(body, (list, tuple)):
            if len(body) == 1 and isinstance(body[0], (list, tuple)):
                body = body[0]
            n = len(body)
        else:
            n = 0
        if n > 0:
            self._c_tok_outcome.inc(n, outcome="hedge_loser")

    def request(self, payload, key: Optional[str] = None,
                timeout: float = 30.0, retries: Optional[int] = None,
                deadline: Optional[Deadline] = None):
        """POST to the routed worker; on failure, refresh the table and fail
        over to the next healthy worker — exactly once per extra attempt
        (the LB behavior the reference delegates to Azure LB,
        ``docs/mmlspark-serving.md:87``).  The ambient/explicit deadline
        clips every attempt's timeout.  Failover retries draw from the
        retry budget; with ``hedge=True`` the first attempt may issue one
        speculative duplicate (see the class docstring)."""
        deadline = deadline or current_deadline()
        failovers = self.failover_retries if retries is None else max(0, retries)
        if self.retry_budget is not None:
            # one deposit per OFFERED request: first tries fund retries
            self.retry_budget.deposit()
        tried: set = set()
        last = None
        failed_over_from: Optional[str] = None
        first_attempt = True
        for _ in range(failovers + 1):
            if deadline is not None and deadline.expired():
                # the CALLER's budget is gone — a client-side condition, not
                # a worker failure: raise without feeding any breaker or
                # failover counter (five tight-deadline requests must never
                # trip a healthy worker's breaker)
                raise last or TimeoutError("deadline exceeded before request")
            try:
                w = self._pick(key, exclude=tried)
            except RuntimeError:
                if last is None:
                    raise  # empty table and nothing attempted yet
                break  # nobody left to fail over to
            sid = w["server_id"]
            if not first_attempt and self.retry_budget is not None:
                # a failover retry spends a token; an exhausted budget ends
                # the request instead of amplifying the outage
                if not self.retry_budget.try_withdraw():
                    self._m_budget_denied.inc()
                    break
                self._m_budget_granted.inc()
            if failed_over_from is not None:
                # a HOP is real only once a next candidate is attempted —
                # a terminal failure with nobody left must not count one
                self._m_failovers.inc(worker=failed_over_from)
                failed_over_from = None
            if first_attempt and self.hedge:
                verdict, out = self._hedged_exchange(
                    w, payload, key, timeout, deadline, tried)
            else:
                verdict, out = self._attempt(w, payload, timeout, deadline)
            first_attempt = False
            if verdict == "ok":
                return out
            if verdict == "raise":
                raise out
            if verdict == "deadline":
                raise last or out
            last = out
            tried.add(sid)
            failed_over_from = sid
            try:  # a briefly-unreachable driver must not abort the
                self._refresh(force=True)  # retry; stale table still works
            except Exception:  # noqa: BLE001
                pass
            key = None  # reroute away from the dead worker
        raise RuntimeError(f"all serving workers failed: {last}")

    def stats(self) -> Dict:
        return _http_json(f"{self.driver_address}/stats")
