"""Concurrent HTTP load generator for serving benchmarks and CI gates.

One implementation shared by ``bench.py``'s sustained-load phase and
``tests/test_serving_latency.py`` so the reported metric and the CI gate
can never drift apart.  Reference context: the reference's serving claims
are about SUSTAINED throughput (``docs/mmlspark-serving.md:10-11``), not
single-connection latency.

``mixed_load`` (ISSUE 9) drives several request classes — e.g. vector
scoring AND generative decode — through one shared measurement window, the
traffic shape the multi-model serving-fleet ROADMAP item needs a generator
for: per-class latency percentiles under combined load, not per-class runs
that never contend.

Per-class gates (ISSUE 11): a workload may carry ``"gates": {"p99_ms":
..., "p50_ms": ..., "max_error_rate": ..., "min_rps": ...}`` and its
result gains a ``"gates"`` verdict — pass/fail per class with every
limit/actual pair, the ROADMAP's "per-class p99 gates" hook reused by the
fleet E2E suite and bench.

TTFT gates (ISSUE 13): decode classes care about FIRST-token latency, not
just whole-response p99 — a ticked drain that batches arrivals serves a
fine p99 at low load while every request waits out the flush tick before
its first token.  A workload carrying ``"ttft_key": "ttft_ms"`` has each
2xx reply body parsed as JSON and that field collected (the continuous
decode scorer reports it in-band via ``report_ttft=True``: engine-measured
admission→first-token; the ticked scorer reports its honest value — the
full latency, since no token is client-visible before the batch resolves).
The class's stats gain ``ttft_p50_ms``/``ttft_p99_ms``/``ttft_count`` and
the gate spec accepts ``ttft_p99_ms``/``ttft_p50_ms`` upper bounds.

Goodput accounting (ISSUE 17): a workload carrying ``"tokens_key":
"tokens"`` has each 2xx reply body parsed and that field counted as
decode tokens (a list counts ``len``, a number its value) — the class's
stats gain ``decode_tokens``/``decode_tokens_per_sec`` so mixed-class
runs report per-class token throughput, the denominator the fleet
capacity model is judged against.  ``check_gates`` accepts
``min_goodput_pct``, a lower bound on the ``goodput_pct`` the caller
folds into ``stats`` (from ``GET /fleet/capacity``); it fails on zero
``goodput_samples`` — never vacuous, the PR 11/13 gate discipline.

Template-sharing traffic (ISSUE 20): a workload carrying
``"prompt_pool": {"prefixes": [...], "suffixes": [...]}`` builds each
request's body per-request — shared prefix (cycled from ``prefixes``) +
per-request suffix (cycled from ``suffixes``) — instead of a static
``body``, the traffic shape whose prefill the cross-request prefix cache
exists to skip.  ``check_gates`` accepts ``min_prefix_hit_pct``, a lower
bound on the ``prefix_hit_rate_pct`` the caller folds into ``stats``
(with ``prefix_lookups`` as its no-vacuous-pass sample count, e.g. from
the engine's ``debug_state()["prefix_cache"]``).
"""
from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence


def check_gates(gates: Dict[str, float],
                stats: Dict[str, float]) -> Dict[str, Any]:
    """Evaluate one class's gate spec against its measured stats.

    Known gates: ``p99_ms`` / ``p50_ms`` (upper bounds on the measured
    percentiles), ``max_error_rate`` (lost + non-2xx requests over the
    class's INTENDED request count when ``stats`` carries ``intended`` —
    a client thread dying mid-run loses every remaining request, not one
    "error" — else the legacy transport-errors/attempts ratio),
    ``max_failed`` (upper bound on the absolute COUNT of lost + non-2xx
    requests — the zero-drop drills gate on ``max_failed: 0``),
    ``min_rps`` (lower bound on completed-request throughput).  Unknown
    gate keys fail loudly — a typo'd gate that silently always passes is
    worse than no gate."""
    checks: Dict[str, Dict[str, float]] = {}
    failures: List[str] = []

    def book(name: str, actual: float, limit: float, ok: bool) -> None:
        checks[name] = {"limit": limit, "actual": actual, "ok": ok}
        if not ok:
            failures.append(f"{name}: {actual:.4g} vs limit {limit:.4g}")

    for name, limit in gates.items():
        limit = float(limit)
        if name in ("p99_ms", "p50_ms"):
            # a class that completed NOTHING reports 0.0 percentiles — a
            # vacuous pass there would wave a totally dead class through
            # its latency gate, the exact silent failure gates exist for
            ok = stats["completed"] > 0 and stats[name] <= limit
            book(name, stats[name], limit, ok)
        elif name in ("ttft_p99_ms", "ttft_p50_ms"):
            # same no-vacuous-pass rule, on the TTFT sample count: a class
            # whose replies never carried the ttft field (no ttft_key, or
            # a server that does not report it) must FAIL its ttft gate
            # rather than pass on a 0.0 placeholder
            actual = stats.get(name, 0.0)
            ok = stats.get("ttft_count", 0.0) > 0 and actual <= limit
            book(name, actual, limit, ok)
        elif name == "max_error_rate":
            intended = stats.get("intended", 0.0)
            if intended > 0:
                bad = max(0.0, intended - stats["completed"]) \
                    + stats.get("non_2xx", 0.0)
                rate = bad / intended
            else:
                attempts = stats["completed"] + stats["errors"]
                bad = stats["errors"] + stats.get("non_2xx", 0.0)
                rate = bad / attempts if attempts else 1.0
            book(name, rate, limit, rate <= limit)
        elif name == "max_failed":
            # absolute count of failed requests (lost + non-2xx), the
            # rolling-restart drill's gate: "zero dropped requests" is a
            # COUNT invariant — a rate gate would wave through one drop
            # per thousand, which is exactly the drop drains must not make
            intended = stats.get("intended", 0.0)
            bad = max(0.0, intended - stats["completed"]) \
                + stats.get("non_2xx", 0.0)
            book(name, bad, limit, bad <= limit)
        elif name == "min_rps":
            book(name, stats["rps"], limit, stats["rps"] >= limit)
        elif name == "min_goodput_pct":
            # lower bound on useful-token share (ISSUE 17).  The caller
            # folds the fleet ledger's goodput into stats as
            # goodput_pct/goodput_samples (e.g. from GET /fleet/capacity);
            # zero samples FAIL — a run whose ledger recorded no tokens
            # must not pass a goodput gate on a 0.0 placeholder
            actual = stats.get("goodput_pct", 0.0)
            ok = stats.get("goodput_samples", 0.0) > 0 and actual >= limit
            book(name, actual, limit, ok)
        elif name == "min_prefix_hit_pct":
            # lower bound on the prefix-cache hit rate (ISSUE 20).  The
            # caller folds the engine's index stats into stats as
            # prefix_hit_rate_pct/prefix_lookups (e.g. from the decoder's
            # debug_state()["prefix_cache"]: hits+misses = lookups); zero
            # lookups FAIL — a run that never consulted the index must
            # not pass a hit-rate gate on a 0.0 placeholder
            actual = stats.get("prefix_hit_rate_pct", 0.0)
            ok = stats.get("prefix_lookups", 0.0) > 0 and actual >= limit
            book(name, actual, limit, ok)
        else:
            raise ValueError(f"unknown gate {name!r}; expected one of "
                             "p99_ms/p50_ms/ttft_p99_ms/ttft_p50_ms/"
                             "max_error_rate/max_failed/min_rps/"
                             "min_goodput_pct/min_prefix_hit_pct")
    return {"passed": not failures, "failures": failures, "checks": checks}


def mixed_load(host: str, port: int,
               workloads: Sequence[Dict[str, Any]],
               warm: int = 10) -> Dict[str, Dict[str, Any]]:
    """Fire several request classes concurrently through one wall-clock
    window.

    Each workload is ``{"name", "path", "body", "headers", "n_clients",
    "per_client"}`` (``n_clients`` default 4, ``per_client`` default 100)
    plus an optional ``"prompt_pool"`` spec replacing the static ``body``
    with per-request bodies — ``{"prefixes": [token lists...],
    "suffixes": [token lists...]}``, each request JSON-encoding one
    cycled prefix + one cycled suffix (ISSUE 20's template-sharing
    shape), an optional ``"gates"`` spec (see :func:`check_gates`), an
    optional ``"ttft_key"`` naming the reply-body field carrying in-band
    first-token latency (adds ``ttft_p50_ms``/``ttft_p99_ms``/
    ``ttft_count`` to the class's stats; see the module docstring), and an
    optional ``"tokens_key"`` naming the reply-body field carrying the
    generated tokens (adds ``decode_tokens``/``decode_tokens_per_sec``).  Every
    client opens its own persistent connection, fires ``warm`` untimed
    requests, then waits on ONE barrier shared by every workload — the
    clock starts when the whole mixed fleet is warm, so the classes
    genuinely contend for the server for the entire window.  Worker
    exceptions are caught and counted; a dying connection deflates (never
    inflates) its class's numbers.

    Returns ``{workload_name: {"rps", "p50_ms", "p99_ms", "completed",
    "errors", "non_2xx"[, "gates"]}, "combined": {...}}`` — per-class RPS
    shares the combined wall window, so the numbers add up; ``non_2xx``
    counts completed exchanges whose status was not 2xx (sheds, timeouts)
    so overload is visible without changing the completed/latency
    semantics.  Raises AssertionError if no request of any class
    completed.
    """
    names = [w["name"] for w in workloads]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate workload names: {sorted(names)} — "
                         "per-class attribution would silently merge them")
    for w in workloads:
        spec = w.get("prompt_pool")
        if spec is not None and not spec.get("prefixes"):
            # validated HERE, not in the worker threads, where a raise
            # would be swallowed into the class's error count
            raise ValueError(f"workload {w['name']!r}: prompt_pool needs a "
                             "non-empty 'prefixes' list")
    lats: Dict[str, List[float]] = {w["name"]: [] for w in workloads}
    errors: Dict[str, List[str]] = {w["name"]: [] for w in workloads}
    non_2xx: Dict[str, int] = {w["name"]: 0 for w in workloads}
    ttfts: Dict[str, List[float]] = {w["name"]: [] for w in workloads}
    tokens: Dict[str, float] = {w["name"]: 0.0 for w in workloads}
    lock = threading.Lock()
    total_clients = sum(int(w.get("n_clients", 4)) for w in workloads)
    barrier = threading.Barrier(total_clients + 1)

    def fire(w: Dict[str, Any]):
        name = w["name"]
        headers = w.get("headers") or {}
        ttft_key = w.get("ttft_key")
        tokens_key = w.get("tokens_key")
        pool_spec = w.get("prompt_pool")
        if pool_spec is None:
            body = w["body"]

            def next_body() -> str:
                return body
        else:
            # template-sharing traffic (ISSUE 20): shared prefix × per-
            # request suffix, both cycled deterministically so repeated
            # runs replay the same prompt stream — every repeat of a
            # prefix is a prefix-cache hit opportunity
            prefixes = [list(p) for p in pool_spec["prefixes"]]
            suffixes = [list(s) for s in
                        (pool_spec.get("suffixes") or [[]])]
            seq = iter(range(10 ** 9))

            def next_body() -> str:
                i = next(seq)
                return json.dumps(prefixes[i % len(prefixes)]
                                  + suffixes[i % len(suffixes)])
        mine: List[float] = []
        mine_ttft: List[float] = []
        mine_bad = 0
        mine_tokens = 0.0
        try:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            for _ in range(warm):
                conn.request("POST", w["path"], next_body(), headers)
                conn.getresponse().read()
        except Exception as e:  # noqa: BLE001 - a dead warm-up is an error
            with lock:
                errors[name].append(f"warmup: {e!r}")
            try:
                barrier.wait(timeout=60)
            except Exception:  # noqa: BLE001
                pass
            return
        try:
            barrier.wait(timeout=60)
        except Exception:  # noqa: BLE001
            return
        try:
            for _ in range(int(w.get("per_client", 100))):
                t0 = time.perf_counter()
                conn.request("POST", w["path"], next_body(), headers)
                resp = conn.getresponse()
                data = resp.read()
                mine.append(time.perf_counter() - t0)
                if not 200 <= resp.status < 300:
                    mine_bad += 1
                elif ttft_key or tokens_key:
                    # in-band TTFT: the decode scorer reports first-token
                    # latency inside the reply body (see module docstring);
                    # a reply without the field just contributes no sample
                    # — the ttft gate fails on a zero sample count
                    try:
                        reply = json.loads(data.decode())
                    except (ValueError, AttributeError):
                        reply = None
                    if isinstance(reply, dict):
                        if ttft_key:
                            val = reply.get(ttft_key)
                            if val is not None:
                                mine_ttft.append(float(val))
                        if tokens_key:
                            # generated tokens: a (possibly row-nested)
                            # list counts its leaves, a bare number counts
                            # its value — only DELIVERED (2xx) tokens
                            # count, matching the ledger's "useful" lane
                            tok = reply.get(tokens_key)
                            if isinstance(tok, (list, tuple)):
                                mine_tokens += sum(
                                    len(r) if isinstance(r, (list, tuple))
                                    else 1 for r in tok)
                            elif isinstance(tok, (int, float)):
                                mine_tokens += float(tok)
        except Exception as e:  # noqa: BLE001 - count what completed
            with lock:
                errors[name].append(repr(e))
        finally:
            with lock:
                lats[name].extend(mine)
                non_2xx[name] += mine_bad
                ttfts[name].extend(mine_ttft)
                tokens[name] += mine_tokens

    threads = [threading.Thread(target=fire, args=(w,))
               for w in workloads for _ in range(int(w.get("n_clients", 4)))]
    for t in threads:
        t.start()
    barrier.wait(timeout=120)      # clock starts once the whole fleet is warm
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = max(time.perf_counter() - t0, 1e-9)

    def stats(vals: List[float], errs: List[str], bad: int
              ) -> Dict[str, float]:
        vals = sorted(vals)
        # the percentile keys are part of the return contract even for a
        # class that completed nothing (0.0, with completed==0 saying why)
        return {"rps": len(vals) / wall, "completed": float(len(vals)),
                "errors": float(len(errs)), "non_2xx": float(bad),
                "p50_ms": 1000 * vals[len(vals) // 2] if vals else 0.0,
                "p99_ms": 1000 * vals[int(len(vals) * 0.99)] if vals else 0.0}

    def ttft_stats(vals: List[float]) -> Dict[str, float]:
        vals = sorted(vals)
        return {"ttft_count": float(len(vals)),
                "ttft_p50_ms": vals[len(vals) // 2] if vals else 0.0,
                "ttft_p99_ms": vals[int(len(vals) * 0.99)] if vals else 0.0}

    all_lats = [v for vs in lats.values() for v in vs]
    all_errs = [e for es in errors.values() for e in es]
    assert all_lats, f"no request completed; errors={all_errs[:3]}"
    result: Dict[str, Dict[str, Any]] = {}
    intended_total = 0.0
    for w in workloads:
        name = w["name"]
        st = stats(lats[name], errors[name], non_2xx[name])
        if w.get("ttft_key"):
            st.update(ttft_stats(ttfts[name]))
        if w.get("tokens_key"):
            # per-class decode token throughput over the SHARED wall
            # window, so classes' tokens/sec add up like their rps does
            st["decode_tokens"] = tokens[name]
            st["decode_tokens_per_sec"] = tokens[name] / wall
        # the class's intended request count: the honest error-rate
        # denominator (a dead client loses all its remaining requests)
        st["intended"] = float(int(w.get("n_clients", 4))
                               * int(w.get("per_client", 100)))
        intended_total += st["intended"]
        if w.get("gates"):
            st["gates"] = check_gates(w["gates"], st)
        result[name] = st
    result["combined"] = stats(all_lats, all_errs, sum(non_2xx.values()))
    result["combined"]["intended"] = intended_total
    all_ttfts = [v for vs in ttfts.values() for v in vs]
    if all_ttfts:
        result["combined"].update(ttft_stats(all_ttfts))
    if any(w.get("tokens_key") for w in workloads):
        total_tokens = sum(tokens.values())
        result["combined"]["decode_tokens"] = total_tokens
        result["combined"]["decode_tokens_per_sec"] = total_tokens / wall
    return result


def sustained_load(host: str, port: int, path: str, body: str,
                   headers: Dict[str, str], n_clients: int = 8,
                   per_client: int = 250, warm: int = 10,
                   gates: Optional[Dict[str, float]] = None
                   ) -> Dict[str, float]:
    """Fire ``per_client`` requests from ``n_clients`` persistent
    connections concurrently — the single-workload special case of
    :func:`mixed_load` (one shared warm barrier, completed-request RPS
    numerator, caught-and-counted worker errors, optional ``gates``).

    Returns {"rps", "p50_ms", "p99_ms", "completed", "errors", "non_2xx"}.
    Raises AssertionError if no request completed.
    """
    res = mixed_load(host, port, [{
        "name": "default", "path": path, "body": body, "headers": headers,
        "n_clients": n_clients, "per_client": per_client, "gates": gates}],
        warm=warm)
    return res["default"]
