"""Concurrent HTTP load generator for serving benchmarks and CI gates.

One implementation shared by ``bench.py``'s sustained-load phase and
``tests/test_serving_latency.py`` so the reported metric and the CI gate
can never drift apart.  Reference context: the reference's serving claims
are about SUSTAINED throughput (``docs/mmlspark-serving.md:10-11``), not
single-connection latency.

``mixed_load`` (ISSUE 9) drives several request classes — e.g. vector
scoring AND generative decode — through one shared measurement window, the
traffic shape the multi-model serving-fleet ROADMAP item needs a generator
for: per-class latency percentiles under combined load, not per-class runs
that never contend.
"""
from __future__ import annotations

import http.client
import threading
import time
from typing import Any, Dict, List, Optional, Sequence


def mixed_load(host: str, port: int,
               workloads: Sequence[Dict[str, Any]],
               warm: int = 10) -> Dict[str, Dict[str, float]]:
    """Fire several request classes concurrently through one wall-clock
    window.

    Each workload is ``{"name", "path", "body", "headers", "n_clients",
    "per_client"}`` (``n_clients`` default 4, ``per_client`` default 100).
    Every client opens its own persistent connection, fires ``warm``
    untimed requests, then waits on ONE barrier shared by every workload —
    the clock starts when the whole mixed fleet is warm, so the classes
    genuinely contend for the server for the entire window.  Worker
    exceptions are caught and counted; a dying connection deflates (never
    inflates) its class's numbers.

    Returns ``{workload_name: {"rps", "p50_ms", "p99_ms", "completed",
    "errors"}, "combined": {...}}`` — per-class RPS shares the combined
    wall window, so the numbers add up.  Raises AssertionError if no
    request of any class completed.
    """
    names = [w["name"] for w in workloads]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate workload names: {sorted(names)} — "
                         "per-class attribution would silently merge them")
    lats: Dict[str, List[float]] = {w["name"]: [] for w in workloads}
    errors: Dict[str, List[str]] = {w["name"]: [] for w in workloads}
    lock = threading.Lock()
    total_clients = sum(int(w.get("n_clients", 4)) for w in workloads)
    barrier = threading.Barrier(total_clients + 1)

    def fire(w: Dict[str, Any]):
        name = w["name"]
        body, headers = w["body"], w.get("headers") or {}
        mine: List[float] = []
        try:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            for _ in range(warm):
                conn.request("POST", w["path"], body, headers)
                conn.getresponse().read()
        except Exception as e:  # noqa: BLE001 - a dead warm-up is an error
            with lock:
                errors[name].append(f"warmup: {e!r}")
            try:
                barrier.wait(timeout=60)
            except Exception:  # noqa: BLE001
                pass
            return
        try:
            barrier.wait(timeout=60)
        except Exception:  # noqa: BLE001
            return
        try:
            for _ in range(int(w.get("per_client", 100))):
                t0 = time.perf_counter()
                conn.request("POST", w["path"], body, headers)
                conn.getresponse().read()
                mine.append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - count what completed
            with lock:
                errors[name].append(repr(e))
        finally:
            with lock:
                lats[name].extend(mine)

    threads = [threading.Thread(target=fire, args=(w,))
               for w in workloads for _ in range(int(w.get("n_clients", 4)))]
    for t in threads:
        t.start()
    barrier.wait(timeout=120)      # clock starts once the whole fleet is warm
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = max(time.perf_counter() - t0, 1e-9)

    def stats(vals: List[float], errs: List[str]) -> Dict[str, float]:
        vals = sorted(vals)
        # the percentile keys are part of the return contract even for a
        # class that completed nothing (0.0, with completed==0 saying why)
        return {"rps": len(vals) / wall, "completed": float(len(vals)),
                "errors": float(len(errs)),
                "p50_ms": 1000 * vals[len(vals) // 2] if vals else 0.0,
                "p99_ms": 1000 * vals[int(len(vals) * 0.99)] if vals else 0.0}

    all_lats = [v for vs in lats.values() for v in vs]
    all_errs = [e for es in errors.values() for e in es]
    assert all_lats, f"no request completed; errors={all_errs[:3]}"
    result = {w["name"]: stats(lats[w["name"]], errors[w["name"]])
              for w in workloads}
    result["combined"] = stats(all_lats, all_errs)
    return result


def sustained_load(host: str, port: int, path: str, body: str,
                   headers: Dict[str, str], n_clients: int = 8,
                   per_client: int = 250, warm: int = 10) -> Dict[str, float]:
    """Fire ``per_client`` requests from ``n_clients`` persistent
    connections concurrently — the single-workload special case of
    :func:`mixed_load` (one shared warm barrier, completed-request RPS
    numerator, caught-and-counted worker errors).

    Returns {"rps", "p50_ms", "p99_ms", "completed", "errors"}.
    Raises AssertionError if no request completed.
    """
    res = mixed_load(host, port, [{
        "name": "default", "path": path, "body": body, "headers": headers,
        "n_clients": n_clients, "per_client": per_client}], warm=warm)
    return res["default"]
