"""Concurrent HTTP load generator for serving benchmarks and CI gates.

One implementation shared by ``bench.py``'s sustained-load phase and
``tests/test_serving_latency.py`` so the reported metric and the CI gate
can never drift apart.  Reference context: the reference's serving claims
are about SUSTAINED throughput (``docs/mmlspark-serving.md:10-11``), not
single-connection latency.
"""
from __future__ import annotations

import http.client
import threading
import time
from typing import Dict, List


def sustained_load(host: str, port: int, path: str, body: str,
                   headers: Dict[str, str], n_clients: int = 8,
                   per_client: int = 250, warm: int = 10) -> Dict[str, float]:
    """Fire ``per_client`` requests from ``n_clients`` persistent
    connections concurrently.

    Each worker opens its connection and fires ``warm`` untimed requests,
    then waits on a barrier; the wall clock starts when every worker is
    warm, so connection setup and warm-up never bias the window.  Worker
    exceptions are CAUGHT and counted — the RPS numerator is the number of
    requests that actually completed, so a dying connection deflates (never
    inflates) the result.

    Returns {"rps", "p50_ms", "p99_ms", "completed", "errors"}.
    Raises AssertionError if no request completed.
    """
    lats: List[float] = []
    errors: List[str] = []
    lock = threading.Lock()
    barrier = threading.Barrier(n_clients + 1)

    def fire():
        mine: List[float] = []
        try:
            conn = http.client.HTTPConnection(host, port, timeout=10)
            for _ in range(warm):
                conn.request("POST", path, body, headers)
                conn.getresponse().read()
        except Exception as e:  # noqa: BLE001 - a dead warm-up is an error
            with lock:
                errors.append(f"warmup: {e!r}")
            try:
                barrier.wait(timeout=30)
            except Exception:  # noqa: BLE001
                pass
            return
        try:
            barrier.wait(timeout=30)
        except Exception:  # noqa: BLE001
            return
        try:
            for _ in range(per_client):
                t0 = time.perf_counter()
                conn.request("POST", path, body, headers)
                conn.getresponse().read()
                mine.append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - count what completed
            with lock:
                errors.append(repr(e))
        finally:
            with lock:
                lats.extend(mine)

    threads = [threading.Thread(target=fire) for _ in range(n_clients)]
    for t in threads:
        t.start()
    barrier.wait(timeout=60)          # clock starts once every worker is warm
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    assert lats, f"no request completed; errors={errors[:3]}"
    lats.sort()
    return {
        "rps": len(lats) / max(wall, 1e-9),
        "p50_ms": 1000 * lats[len(lats) // 2],
        "p99_ms": 1000 * lats[int(len(lats) * 0.99)],
        "completed": float(len(lats)),
        "errors": float(len(errors)),
    }
