"""Serving — low-latency model web service over pipeline transforms.

Reference: Spark Serving (``core/src/main/scala/org/apache/spark/sql/
execution/streaming/``, SURVEY.md §2.7):
- v1 head-node ``HTTPSource``/``HTTPSink`` (requests buffered as micro-batch
  offsets, replies matched by uuid);
- ``DistributedHTTPSource`` (per-executor ``JVMSharedServer`` +
  ``MultiChannelMap`` request sharding);
- v2 continuous mode (sub-ms replies; worker servers reply directly via
  ``HTTPSourceStateHolder.replyTo``).

TPU-native: the server is host-side Python (threaded HTTP, as the reference's
is JVM HttpServer); scoring goes through an already-jitted pipeline so the
device sees steady pre-compiled batch shapes.  ``continuous`` mode drains
whatever is queued into one dynamic micro-batch per transform (the latency/
throughput trick the reference gets from continuous processing);
``micro_batch`` mode flushes on a trigger interval.
"""
from __future__ import annotations

import itertools
import json
import math
import queue
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import DataFrame, Transformer
from ..observability import get_registry
from ..observability.collector import get_collector
from ..observability.tracing import (Span, TRACE_HEADER, TRACEPARENT_HEADER,
                                     export_span, format_traceparent,
                                     new_trace_id, parse_traceparent,
                                     trace_span)
from ..utils.concurrency import make_lock
from ..utils.resilience import (Deadline, deadline_scope,
                                register_preemption_hook,
                                unregister_preemption_hook)

# entry ids need uniqueness within the process, not entropy: uuid4's
# per-call os.urandom syscall (~40 us on this kernel) sat inside the
# serialized admission path — same counter pattern as span ids in
# observability/tracing.py.  itertools.count.__next__ is atomic under
# the GIL, so handler threads share it without a lock.
_ENTRY_IDS = itertools.count()


@dataclass
class _Entry:
    uid: str
    payload: Any
    headers: Dict[str, str]
    done: threading.Event = field(default_factory=threading.Event)
    reply: Any = None
    status: int = 200
    # absolute expiry on the server clock; a plain float (not a Deadline
    # object) keeps the per-request hot path allocation-free
    t_deadline: float = float("inf")
    t_enq: float = 0.0
    retry_after_s: Optional[float] = None
    trace_id: str = ""
    # set when the request carried a W3C traceparent: the reply echoes one
    # back with the server-side request span's id
    echo_traceparent: bool = False
    span_id: str = ""  # serving.request span id, filled by the scorer
    # stable prompt identity (ISSUE 20): set at continuous admission when
    # the front accepts prompt_hash=; lands on the request record so
    # /debug/requests correlates hits with their prefill_cached lane
    prompt_hash: Optional[str] = None


class ServingStats:
    """Request counters (reference DistributedHTTPSource.scala:99-110).

    Each request is counted EXACTLY once by its handler thread:
    ``replied`` (200 written), ``errors`` (500/504/failed write), or
    ``shed`` (503 load shed).  At quiescence
    ``received == replied + errors + shed``; mid-flight, admitted-but-
    unresolved requests make up the difference.

    ``latency_sum`` is paired with ``latency_count`` (both fed only by 200s,
    under one lock) so consumers always compute a correct average — dividing
    by ``replied`` raced the reply-before-latency window and broke down once
    shed/error replies existed.
    """

    def __init__(self):
        self.lock = make_lock("ServingStats.lock")
        self.received = 0
        self.replied = 0
        self.errors = 0
        self.shed = 0
        self.latency_sum = 0.0
        self.latency_count = 0

    def as_dict(self):
        with self.lock:
            avg_ms = 1000.0 * self.latency_sum / max(1, self.latency_count)
            return {"received": self.received, "replied": self.replied,
                    "errors": self.errors, "shed": self.shed,
                    "latency_sum_s": self.latency_sum,
                    "latency_count": self.latency_count,
                    "latency_avg_ms": avg_ms,
                    # legacy name kept for aggregators; same correct value
                    "mean_latency_ms": avg_ms}


class PipelineServer:
    """Serve a fitted pipeline as a JSON web service.

    POST <api_path> with a JSON object (one row) -> JSON reply from
    ``reply_col``.  GET /stats -> counters; GET /health -> ok;
    GET /metrics -> Prometheus exposition (with exemplars);
    GET /trace/<id> -> assembled span tree for a recent trace;
    GET /debug/slow[?k=N] -> top-K slowest recent requests with phase
    breakdown and shed/deadline verdict (see docs/OBSERVABILITY.md,
    "Debugging a slow request");
    GET /debug/compile -> compute-plane compile state (per-function compile
    counts, abstract signatures, last cost analysis, recompile-storm trips);
    GET /debug/requests[?k=&class=&verdict=] -> newest-first canonical
    request records with per-request cost stanzas (ISSUE 17).

    Graceful degradation: admission is bounded — once ``max_queue_depth``
    requests are in flight, further POSTs are shed immediately with 503 +
    ``Retry-After`` instead of queueing toward certain timeout (the
    reference's LB would do this upstream; in-process we must).  Each
    request carries a deadline (``X-MMLSpark-Deadline-Ms`` header if the
    client sent one, else ``request_timeout_s``); the scorer drops entries
    whose budget expired in the queue (504) or whose queue age exceeds
    ``max_queue_age_s`` (503) without wasting device time on them.
    """

    def __init__(self, model: Transformer, input_col: str = "request",
                 reply_col: str = "reply", host: str = "127.0.0.1",
                 port: int = 8899, api_path: str = "/score",
                 mode: str = "continuous", max_batch: int = 64,
                 micro_batch_interval_ms: int = 10,
                 input_parser: Optional[Callable[[bytes], Any]] = None,
                 reply_encoder: Optional[Callable[[Any], Any]] = None,
                 request_timeout_s: float = 30.0,
                 max_queue_depth: int = 256,
                 max_queue_age_s: Optional[float] = None,
                 shed_retry_after_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None,
                 shed_queue_delay_ewma_s: Optional[float] = None,
                 ewma_alpha: float = 0.2,
                 micro_batch_deadline_margin_s: float = 0.0,
                 micro_batch_ewma_flush_s: Optional[float] = None,
                 slow_k: int = 10,
                 drain_timeout_s: Optional[float] = 30.0,
                 request_class: str = "default",
                 request_record_k: int = 256):
        if mode not in ("continuous", "micro_batch"):
            raise ValueError("mode must be continuous|micro_batch")
        self.model = model
        # continuous admission protocol (ISSUE 13): a model exposing
        # `continuous_submit(payload, resolve, queue_age_s=,
        # deadline_budget_s=)` (the runner's continuous decode scorer)
        # gets each drained entry
        # handed to it the moment the drain sees it — the entry resolves
        # per request from the model's own engine instead of with the
        # batch, so a finished sequence replies while the rest keep
        # decoding.  Duck-typed so serving never imports the models
        # package (a pure-python pipeline must not pay a jax import).
        self._continuous_submit = getattr(model, "continuous_submit", None)
        # `trace_id=` (ISSUE 15: the TTFT exemplar rides it to the engine
        # thread) is forwarded only to fronts that declare it — the PR 13
        # protocol is duck-typed, and an existing front must not start
        # throwing TypeError because the server learned a new kwarg
        self._submit_takes_trace = False
        # `prompt_hash=` (ISSUE 20: the prefix-cache admission seam — a
        # stable identity for the request's prompt, recorded on the
        # stream handle and the request record) rides the same duck-typed
        # introspection as trace_id
        self._submit_takes_hash = False
        if self._continuous_submit is not None:
            try:
                import inspect as _inspect
                params = _inspect.signature(
                    self._continuous_submit).parameters
                var_kw = any(p.kind is _inspect.Parameter.VAR_KEYWORD
                             for p in params.values())
                self._submit_takes_trace = "trace_id" in params or var_kw
                self._submit_takes_hash = "prompt_hash" in params or var_kw
            except (TypeError, ValueError):
                pass
        self.input_col, self.reply_col = input_col, reply_col
        self.host, self.port, self.api_path = host, port, api_path
        self.mode = mode
        self.max_batch = max_batch
        self.interval_ms = micro_batch_interval_ms
        self.input_parser = input_parser or (lambda b: json.loads(b.decode() or "null"))
        self.reply_encoder = reply_encoder or _default_encode
        self.request_timeout_s = request_timeout_s
        self.max_queue_depth = max_queue_depth
        self.max_queue_age_s = max_queue_age_s
        self.shed_retry_after_s = shed_retry_after_s
        self.clock = clock
        self.stats = ServingStats()
        self._pending = 0  # admitted, not yet resolved (guarded by stats.lock)
        # adaptive shedding signal: EWMA of per-entry queue delay, updated by
        # the scorer, read at admission (guarded by stats.lock).  Shedding on
        # it only engages while a backlog exists (_pending > 0), so a drained
        # server always admits again — no lockout after a latency spike.
        self.shed_queue_delay_ewma_s = shed_queue_delay_ewma_s
        self.ewma_alpha = float(ewma_alpha)
        self._queue_ewma = 0.0
        # micro-batch early flush: never wait out the trigger interval past
        # the point where the tightest drained entry's deadline (minus this
        # reserved scoring margin) would expire in the batch buffer
        self.micro_batch_deadline_margin_s = float(micro_batch_deadline_margin_s)
        # EWMA-predicted early flush (ROADMAP PR 2 follow-up): once the
        # scorer-maintained queue-delay EWMA says entries are already
        # paying this much delay, waiting out the rest of the trigger
        # interval costs more latency than the batch amortization gains —
        # take what is queued and flush now.  None = off.
        self.micro_batch_ewma_flush_s = micro_batch_ewma_flush_s
        # /debug/slow default depth
        self.slow_k = int(slow_k)
        # graceful drain (ISSUE 16): once draining, admission sheds with
        # 503 "draining" + Connection: close, the continuous engine stops
        # accepting joins while existing slots run to eos/budget, and the
        # server stops only after everything admitted resolved — a rolling
        # restart drops zero in-flight requests.  The SIGTERM/preemption
        # hook drains with this default budget.
        self.drain_timeout_s = drain_timeout_s
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._drain_lock = make_lock("PipelineServer._drain_lock")
        self._preemption_hook = None
        # metrics: families on the (shared, injectable) registry; children
        # are labelled per server instance once the port is resolved so many
        # servers coexist in one registry/process
        self.registry = registry if registry is not None else get_registry()
        self._server_label = f"{host}:{port}"
        reg = self.registry
        self._m_requests = reg.counter(
            "mmlspark_serving_requests_total",
            "requests by terminal status (received counts admissions+sheds)",
            labels=("server", "status"))
        self._m_latency = reg.histogram(
            "mmlspark_serving_request_latency_seconds",
            "end-to-end latency of 200 replies", labels=("server",))
        self._m_phase = reg.histogram(
            "mmlspark_serving_phase_seconds",
            "per-request time split: queue wait vs batch score",
            labels=("server", "phase"))
        self._m_queue_depth = reg.gauge(
            "mmlspark_serving_queue_depth",
            "admitted-but-unresolved requests", labels=("server",))
        self._m_queue_age = reg.gauge(
            "mmlspark_serving_queue_oldest_age_seconds",
            "age of the oldest queued entry (0 when empty)",
            labels=("server",))
        self._m_ewma = reg.gauge(
            "mmlspark_serving_queue_delay_ewma_seconds",
            "EWMA of per-entry queue delay (adaptive shed signal)",
            labels=("server",))
        self._m_drain = reg.histogram(
            "mmlspark_serving_drain_seconds",
            "graceful-drain duration: draining flag set -> server stopped",
            labels=("server",))
        # profiling + postmortem plane (ISSUE 15): families registered at
        # construction (coverage-gated), and the per-registry flight
        # recorder created with its crash/preemption hooks installed so
        # every serving process records — /debug/profile and /debug/dump
        # serve from these
        from ..observability.flightrecorder import get_flight_recorder
        from ..observability.profiling import profiler_instruments
        profiler_instruments(reg)
        self._recorder = get_flight_recorder(reg)
        # goodput & cost attribution (ISSUE 17): this server's request
        # class labels the fleet cost rollups, and every terminal request
        # emits one bounded canonical record (trace id, class, verdict,
        # cost stanza) into the ring behind GET /debug/requests — also the
        # flight recorder's `source.requests:<addr>` postmortem section
        from ..observability.attribution import (RequestRecordRing,
                                                 attribution_instruments)
        self.request_class = str(request_class)
        self._records = RequestRecordRing(request_record_k)
        _att = attribution_instruments(reg)
        self._c_class_tokens = _att["class_tokens"].labels(
            **{"class": self.request_class})
        self._c_class_device = _att["class_device"].labels(
            **{"class": self.request_class})
        self._record_source: Optional[str] = None
        # pre-start sinks: port=0 is unresolved, and registering children
        # under "host:0" would leave a ghost zero series in the (usually
        # shared) registry for every constructed-but-restarted server.
        # start() re-binds to real labelled children.
        self._c_status = {s: self._m_requests.detached_child()
                          for s in self._STATUSES}
        self._h_latency = self._m_latency.detached_child()
        self._h_phase_queue = self._m_phase.detached_child()
        self._h_phase_score = self._m_phase.detached_child()
        self._h_drain = self._m_drain.detached_child()
        self._q: "queue.Queue[_Entry]" = queue.Queue()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # continuous-mode fast path: an idle handler thread scores its own
        # request inline instead of paying two thread hand-offs through the
        # queue (reference continuous mode reaches ~1 ms,
        # docs/mmlspark-serving.md:10-11; the hand-off alone costs ~0.5 ms)
        self._inline_lock = make_lock("PipelineServer._inline_lock")

    _STATUSES = ("received", "replied", "shed", "error", "write_error")

    def _bind_metric_children(self) -> None:
        """Resolve this server's labelled children ONCE (per-call label
        resolution costs a dict+tuple build inside the serialized scoring
        section); called by start() with the resolved port.  Also pre-creates
        the known status series at 0 so scrapers always see shed/error
        counters (a rate() over a series born mid-incident would miss its
        first increment)."""
        label = self._server_label
        self._c_status = {
            s: self._m_requests.labels(server=label, status=s)
            for s in self._STATUSES}
        self._h_latency = self._m_latency.labels(server=label)
        self._h_phase_queue = self._m_phase.labels(server=label, phase="queue")
        self._h_phase_score = self._m_phase.labels(server=label, phase="score")
        self._h_drain = self._m_drain.labels(server=label)

    # ------------------------------------------------------------------ http
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: persistent connections.  Every reply carries an
            # explicit Content-Length, so keep-alive is safe and a client
            # scoring a stream of rows pays TCP/handshake setup once, not
            # per request (the reference's continuous-mode latency claim
            # assumes exactly this client pattern).
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/health":
                    # health is the eviction signal: TopologyService probes
                    # GET this and treat non-200 as unhealthy.  Draining
                    # (about to stop) and an unhealthy model (duck-typed
                    # `serving_healthy` — a quarantined decode engine flips
                    # it) must both fail the probe so routing stops sending
                    # work here.
                    if server.draining:
                        self._write_raw(503, b"draining", b"text/plain")
                    elif not getattr(server.model, "serving_healthy", True):
                        self._write_raw(503, b"unhealthy", b"text/plain")
                    else:
                        self._write_raw(200, b"ok", b"text/plain")
                elif self.path == "/stats":
                    d = server.stats.as_dict()
                    with server.stats.lock:
                        d["pending"] = server._pending
                        d["queue_delay_ewma_ms"] = 1000.0 * server._queue_ewma
                    d["draining"] = server.draining
                    # every breaker instrumented into this registry, with
                    # state / consecutive failures / rolling failure rate
                    d["breakers"] = server.registry.breaker_stats()
                    # a checkpointing worker reports its worst last-success
                    # age so the fleet aggregator can page on "checkpoints
                    # stopped landing" fleet-wide (ISSUE 11); absent when
                    # nothing in this process checkpoints
                    age = server._checkpoint_age_s()
                    if age is not None:
                        d["checkpoint_last_success_age_seconds"] = age
                    self._write_raw(200, json.dumps(d).encode())
                elif self.path == "/metrics":
                    # content negotiation: exemplars are only legal under
                    # the OpenMetrics content type — a 0.0.4 parser reads
                    # the ` # {...}` suffix as a malformed timestamp and
                    # fails the ENTIRE scrape.  Prometheus asks for
                    # OpenMetrics explicitly when it wants exemplars.
                    accept = self.headers.get("Accept", "")
                    if "application/openmetrics-text" in accept:
                        body = (server.registry.to_prometheus(openmetrics=True)
                                + "# EOF\n").encode()
                        ctype = (b"application/openmetrics-text; "
                                 b"version=1.0.0; charset=utf-8")
                    else:
                        body = server.registry.to_prometheus().encode()
                        ctype = b"text/plain; version=0.0.4; charset=utf-8"
                    self._write_raw(200, body, ctype)
                elif self.path.startswith("/trace/"):
                    # slow-request diagnostics: a /metrics exemplar's trace
                    # id resolves here to the assembled span tree while the
                    # trace is still in the collector ring
                    trace_id = self.path[len("/trace/"):]
                    tree = get_collector(server.registry).trace_tree(trace_id)
                    if tree is None:
                        self._respond(404, {"error": "unknown or evicted "
                                                     "trace", "traceId": trace_id})
                    else:
                        self._respond(200, tree)
                elif self.path == "/debug/compile":
                    # compute-plane diagnostics: per-instrumented-function
                    # compile counts, abstract signatures, last cost
                    # analysis — the first stop when "score got slow" is
                    # actually a recompile storm below the host timings
                    from ..observability.compute import compile_report
                    self._respond(200, compile_report(server.registry))
                elif self.path.split("?", 1)[0] == "/debug/slow":
                    k = server.slow_k
                    query = self.path.partition("?")[2]
                    for part in query.split("&"):
                        if part.startswith("k="):
                            try:
                                k = int(part[2:])
                            except ValueError:
                                pass
                    slow = get_collector(server.registry).slowest(
                        k=k, name="serving.request",
                        server=server._server_label)
                    self._respond(200, {"server": server._server_label,
                                        "slowest": slow})
                elif self.path.split("?", 1)[0] == "/debug/profile":
                    # on-demand host-stack sampling window (ISSUE 15):
                    # blocks THIS handler thread for the window (other
                    # requests keep flowing — threaded server), attributes
                    # samples to ambient span names, 409 when a window is
                    # already running
                    from ..observability.profiling import (ProfilerBusy,
                                                           profile_window)
                    seconds, hz, idle = 2.0, None, False
                    query = self.path.partition("?")[2]
                    try:
                        for part in query.split("&"):
                            if part.startswith("seconds="):
                                seconds = float(part[len("seconds="):])
                            elif part.startswith("hz="):
                                hz = float(part[len("hz="):])
                            elif part.startswith("idle="):
                                idle = bool(int(part[len("idle="):]))
                    except ValueError:
                        self._respond(400, {"error": "seconds/hz/idle must "
                                                     "be numeric"})
                        return
                    try:
                        kw = {} if hz is None else {"hz": hz}
                        report = profile_window(seconds=seconds,
                                                registry=server.registry,
                                                include_idle=idle,
                                                **kw)
                    except ProfilerBusy as e:
                        self._write_raw(409, json.dumps(
                            {"error": str(e)}).encode())
                        return
                    self._respond(200, report)
                elif self.path.split("?", 1)[0] == "/debug/requests":
                    # canonical request records (ISSUE 17): newest-first,
                    # filterable by class/verdict — the wide-event ring a
                    # wasted-work investigation starts from (each record
                    # carries the request's full cost stanza)
                    k, klass, verdict = 50, None, None
                    query = self.path.partition("?")[2]
                    try:
                        for part in query.split("&"):
                            if part.startswith("k="):
                                k = int(part[len("k="):])
                            elif part.startswith("class="):
                                klass = part[len("class="):]
                            elif part.startswith("verdict="):
                                verdict = part[len("verdict="):]
                    except ValueError:
                        self._respond(400, {"error": "k must be an integer"})
                        return
                    self._respond(200, {
                        "server": server._server_label,
                        "class": server.request_class,
                        "appended": server._records.appended,
                        "records": server._records.query(
                            k=k, klass=klass, verdict=verdict)})
                elif self.path == "/debug/dump":
                    # on-demand flight-recorder snapshot: books the dump
                    # (and writes the file when a dump dir is configured),
                    # then serves the snapshot itself
                    from ..observability.flightrecorder import \
                        get_flight_recorder
                    rec = get_flight_recorder(server.registry)
                    path = rec.dump(trigger="http")
                    snap = dict(rec.last_snapshot or {})
                    snap["dump_path"] = path
                    self._respond(200, snap)
                else:
                    self._respond(404, {"error": "not found"})

            def do_POST(self):
                # ALWAYS drain the body first: on keep-alive connections an
                # unread body would be parsed as the next request line,
                # desynchronizing the stream after any error reply
                t0 = time.perf_counter()
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if self.path == "/admin/drain":
                    # kick the drain off-thread and ack immediately: drain
                    # blocks until in-flight slots finish, and the admin
                    # caller (an orchestrator mid rolling-restart) polls
                    # /stats or just watches the port close.  Idempotent —
                    # a second POST reports the drain already running.
                    timeout_s = server.drain_timeout_s
                    try:
                        req = json.loads(body.decode() or "{}")
                        if isinstance(req, dict) and "timeout_s" in req:
                            timeout_s = float(req["timeout_s"])
                    except (ValueError, TypeError):
                        self._respond(400, {"error": "timeout_s must be "
                                                     "numeric"})
                        return
                    already = server.draining
                    if not already:
                        threading.Thread(
                            target=server.drain,
                            kwargs={"timeout_s": timeout_s},
                            daemon=True, name="mmlspark-drain").start()
                    with server.stats.lock:
                        pending = server._pending
                    self._respond(200, {"draining": True,
                                        "already_draining": already,
                                        "pending": pending})
                    return
                if self.path != server.api_path:
                    self._respond(404, {"error": "not found"})
                    return
                try:
                    payload = server.input_parser(body)
                except Exception as e:  # noqa: BLE001
                    self._respond(400, {"error": f"bad request: {e}"})
                    return
                # the caller's remaining budget rides the deadline header;
                # without one the server default bounds the request
                t_enq = server.clock()
                budget_s = server.request_timeout_s
                hdr = self.headers.get(Deadline.HEADER)
                if hdr:
                    parsed = Deadline.parse_budget_s(hdr)
                    if parsed is not None:
                        budget_s = min(budget_s, parsed)
                # adopt the caller's trace id so the worker-side spans of
                # this request join the caller's trace: a W3C `traceparent`
                # wins (PR 4 follow-up — external frontends speak Trace
                # Context), else the legacy X-MMLSpark-Trace-Id, else fresh
                tp_in = self.headers.get(TRACEPARENT_HEADER)
                parsed_tp = parse_traceparent(tp_in) if tp_in else None
                if parsed_tp is not None:
                    trace_id = parsed_tp[0]
                else:
                    trace_id = self.headers.get(TRACE_HEADER) or new_trace_id()
                entry = _Entry(uid=f"e{next(_ENTRY_IDS):x}", payload=payload,
                               headers=dict(self.headers), t_enq=t_enq,
                               t_deadline=t_enq + budget_s,
                               trace_id=trace_id,
                               echo_traceparent=parsed_tp is not None)
                # bounded admission: shedding beats queueing toward a
                # certain timeout (503 tells the client to back off; 504
                # would have cost it request_timeout_s of waiting first)
                shed_reason = server._try_admit()
                trace_hdr = {TRACE_HEADER: trace_id}
                if entry.echo_traceparent:
                    # echoed next to the legacy header; the request span's
                    # id rides it once the scorer resolved the entry (the
                    # pre-score shed/timeout replies carry a fresh span id)
                    trace_hdr[TRACEPARENT_HEADER] = format_traceparent(
                        trace_id, entry.span_id or None)
                if shed_reason is not None:
                    extra = {"Retry-After":
                             _retry_after(server.shed_retry_after_s),
                             **trace_hdr}
                    if shed_reason == "draining":
                        # the server is going away: tell the client to tear
                        # the keep-alive connection down and re-resolve (a
                        # pooled connection to a draining server would just
                        # shed again until the port closes)
                        extra["Connection"] = "close"
                        self.close_connection = True
                    self._respond(503, {"error": f"overloaded: {shed_reason}"},
                                  extra_headers=extra)
                    return
                if server.mode == "continuous" and \
                        server._inline_lock.acquire(blocking=False):
                    try:  # idle scorer: skip the queue hand-off entirely
                        server._score_batch([entry])
                    finally:
                        server._inline_lock.release()
                else:
                    server._q.put(entry)
                # wait no longer than the caller still cares about
                if not entry.done.wait(budget_s):
                    self._respond(504, {"error": "timeout"},
                                  extra_headers=trace_hdr)
                    with server.stats.lock:
                        server.stats.errors += 1
                    server._c_status["error"].inc()
                    return
                # count BEFORE the socket write: a client that already holds
                # the reply must never observe its counter lagging (stats
                # aggregation raced the last in-flight write otherwise).  A
                # failed write rolls the count back as an error; latency is
                # sampled after the write so the metric's window is unchanged
                status = entry.status
                stats = server.stats
                extra = dict(trace_hdr)
                if entry.echo_traceparent and entry.span_id:
                    # the scorer resolved the request span: the echo now
                    # names the exact server-side span of this request
                    extra[TRACEPARENT_HEADER] = format_traceparent(
                        trace_id, entry.span_id)
                if status == 503:
                    extra["Retry-After"] = _retry_after(
                        entry.retry_after_s or server.shed_retry_after_s)
                try:
                    if status == 200:
                        with stats.lock:
                            stats.replied += 1
                        self._respond(200, entry.reply, extra_headers=extra)
                        # latency is a SUCCESS metric: only 200s may feed
                        # the (sum, count) pair — latency_avg divides by it
                        latency_s = time.perf_counter() - t0
                        with stats.lock:
                            stats.latency_sum += latency_s
                            stats.latency_count += 1
                        server._c_status["replied"].inc()
                        # exemplar: the bucket this latency lands in keeps
                        # this request's trace id — a p99 outlier on
                        # /metrics resolves to /trace/<id>
                        server._h_latency.observe(latency_s, trace_id)
                    elif status == 503:
                        with stats.lock:
                            stats.shed += 1
                        self._respond(503, entry.reply, extra_headers=extra)
                        server._c_status["shed"].inc()
                    else:
                        with stats.lock:
                            stats.errors += 1
                        self._respond(status, entry.reply, extra_headers=extra)
                        server._c_status["error"].inc()
                except Exception:  # any failed write: invariant must hold
                    # (the stats invariant rolls back exactly; monotonic
                    # registry counters book the write failure as an error
                    # instead — documented divergence in docs/OBSERVABILITY.md)
                    with stats.lock:
                        if status == 200:
                            stats.replied -= 1
                        elif status == 503:
                            stats.shed -= 1
                        else:
                            stats.errors -= 1
                        stats.errors += 1
                    server._c_status["write_error"].inc()
                    raise

            _STATUS = {200: b"200 OK", 400: b"400 Bad Request",
                       404: b"404 Not Found", 409: b"409 Conflict",
                       500: b"500 Internal Server Error",
                       503: b"503 Service Unavailable",
                       504: b"504 Gateway Timeout"}

            def _write_raw(self, status, body, ctype=b"application/json",
                           extra_headers=None):
                # one buffered write per reply: status line + headers + body
                # in a single syscall/TCP segment (the default handler path
                # issues one write per header, which interacts badly with
                # delayed ACKs on loopback)
                hdrs = b""
                for k, v in (extra_headers or {}).items():
                    hdrs += k.encode() + b": " + str(v).encode() + b"\r\n"
                self.wfile.write(
                    b"HTTP/1.1 " + self._STATUS.get(status, b"500 ISE")
                    + b"\r\nContent-Type: " + ctype
                    + b"\r\n" + hdrs
                    + b"Content-Length: " + str(len(body)).encode()
                    + b"\r\n\r\n" + body)

            def _respond(self, status, obj, extra_headers=None):
                self._write_raw(status, json.dumps(obj, default=str).encode(),
                                extra_headers=extra_headers)

        return Handler

    # ------------------------------------------------------------------ work
    def _try_admit(self) -> Optional[str]:
        """Count the request and decide admission; returns None when
        admitted (pending slot taken) or the shed reason.  Three signals
        shed:

        - ``draining`` — the server is emptying itself to stop (graceful
          drain); takes precedence over the load signals;
        - ``queue_full`` — fixed bound: ``_pending >= max_queue_depth``;
        - ``queue_delay_ewma`` — adaptive bound: the scorer-maintained EWMA
          of queue delay exceeds ``shed_queue_delay_ewma_s`` AND a backlog
          exists.  The backlog condition makes recovery automatic: once the
          queue drains, admission resumes regardless of the stale EWMA.
        """
        with self.stats.lock:
            self.stats.received += 1
            shed = None
            if self._draining.is_set():
                # draining beats every other signal: nothing new may join a
                # server that is emptying itself to stop (ISSUE 16)
                shed = "draining"
            elif self._pending >= self.max_queue_depth:
                shed = "queue_full"
            elif self.shed_queue_delay_ewma_s is not None \
                    and self._pending > 0 \
                    and self._queue_ewma > self.shed_queue_delay_ewma_s:
                shed = "queue_delay_ewma"
            if shed is None:
                self._pending += 1
            else:
                self.stats.shed += 1
        self._c_status["received"].inc()
        if shed is not None:
            self._c_status["shed"].inc()
        return shed

    def _checkpoint_age_s(self) -> Optional[float]:
        """Max ``mmlspark_checkpoint_last_success_age_seconds`` across the
        registry's checkpoint sites, or None when nothing checkpoints here.
        The MAX is the pageable number: one stalled site is an outage even
        when the others keep landing.  Finite values only: ``inf`` (armed
        but never saved) would serialize as the non-RFC ``Infinity`` JSON
        literal strict clients reject — the never-saved state stays
        visible as ``+Inf`` on the ``/metrics`` text exposition."""
        fam = self.registry.family(
            "mmlspark_checkpoint_last_success_age_seconds")
        if fam is None:
            return None
        vals = [child.value for _key, child in fam._snapshot()]
        vals = [v for v in vals if math.isfinite(v)]
        return max(vals) if vals else None

    def _oldest_queue_age_s(self) -> float:
        """Age of the oldest queued (not yet drained) entry; gauge callback."""
        with self._q.mutex:
            head = self._q.queue[0] if self._q.queue else None
        return 0.0 if head is None else max(0.0, self.clock() - head.t_enq)

    def _drain(self) -> List[_Entry]:
        try:
            first = self._q.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        if self.mode == "micro_batch":
            flush_at = time.monotonic() + self.interval_ms / 1000.0
            if self.micro_batch_ewma_flush_s is not None:
                # EWMA-predicted trigger (PR 2 follow-up): the scorer's
                # queue-delay EWMA predicts what further waiting costs the
                # entries in hand.  Once the prediction eats the bound,
                # the batch gains cannot pay for the wait — take whatever
                # is queued and flush now; below the bound, pull the flush
                # point forward so total predicted delay stays bounded.
                # The EWMA only moves in _score_batch (this same worker
                # thread), so one read per drain is exact.
                with self.stats.lock:
                    predicted = self._queue_ewma
                ewma_slack_s = self.micro_batch_ewma_flush_s - predicted
                if ewma_slack_s <= 0:
                    while len(batch) < self.max_batch:
                        try:
                            batch.append(self._q.get_nowait())
                        except queue.Empty:
                            break
                    return batch
                flush_at = min(flush_at,
                               time.monotonic() + ewma_slack_s)
            while len(batch) < self.max_batch:
                wait_s = flush_at - time.monotonic()
                if wait_s <= 0:
                    break
                # deadline-aware trigger (PR 1 follow-up): waiting out the
                # full interval past the tightest admitted deadline would
                # turn a scoreable request into a certain 504 — flush as
                # soon as the most impatient entry's slack (minus the
                # margin reserved for scoring itself) runs out.  Entry
                # deadlines live on the injectable server clock; the
                # trigger interval stays on the wall clock.
                slack_s = min(e.t_deadline for e in batch) - self.clock() \
                    - self.micro_batch_deadline_margin_s
                if slack_s <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=min(wait_s, slack_s)))
                except queue.Empty:
                    break
        else:  # continuous: take whatever is already waiting
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
        return batch

    def _score_batch(self, batch: List[_Entry]) -> None:
        """Run the pipeline over a batch of entries and resolve each one.
        Called from the worker thread and, in continuous mode, inline from
        an idle handler thread (guarded by ``_inline_lock``).

        Entries that expired in the queue are resolved without scoring:
        504 when the caller's deadline is gone (it stopped listening), 503
        shed when queue age exceeds ``max_queue_age_s`` (overload — tell
        the caller to back off rather than burn device time on stale work).
        Counting happens in the handler threads (exactly once per request),
        never here; this thread only frees admission slots and wakes them.
        """
        now = self.clock()
        live: List[_Entry] = []
        # per-entry queue delay feeds the phase histogram and the adaptive
        # shed EWMA (in arrival order, so tests on FakeClock are exact)
        alpha = self.ewma_alpha
        with self.stats.lock:
            for e in batch:
                self._queue_ewma = (alpha * max(0.0, now - e.t_enq)
                                    + (1.0 - alpha) * self._queue_ewma)
        verdicts: Dict[str, str] = {}
        for e in batch:
            self._h_phase_queue.observe(max(0.0, now - e.t_enq), e.trace_id)
            if now > e.t_deadline:
                e.status, e.reply = 504, {"error": "deadline expired in queue"}
                verdicts[e.uid] = "deadline_expired_in_queue"
            elif self.max_queue_age_s is not None and \
                    now - e.t_enq > self.max_queue_age_s:
                e.status, e.reply = 503, {"error": "shed: queue age exceeded"}
                e.retry_after_s = self.shed_retry_after_s
                verdicts[e.uid] = "shed_queue_age"
            else:
                live.append(e)
        # continuous admission (ISSUE 13): entries go to the model's own
        # in-flight engine one by one and resolve from it per request —
        # admission failures (no free slot / page pool exhausted) shed THIS
        # entry with 503 + Retry-After and ride the normal resolution loop
        deferred: set = set()
        if live and self.mode == "continuous" and \
                self._continuous_submit is not None:
            for e in live:
                if self._submit_continuous(e, max(0.0, now - e.t_enq)):
                    deferred.add(e.uid)
                elif e.status == 503:
                    verdicts[e.uid] = "shed_decode_admission"
            live = []
        score_s = 0.0
        if live:
            col = np.empty(len(live), dtype=object)
            for i, e in enumerate(live):
                col[i] = e.payload
            ids = np.asarray([e.uid for e in live], dtype=object)
            # `_enq_age_s` (queue age at drain — a RELATIVE duration, so
            # the server's injectable clock never leaks its domain into
            # the scorer) rides along so a TTFT-reporting scorer can
            # anchor first-token latency at admission (extra columns pass
            # through any transformer untouched)
            df = DataFrame([{self.input_col: col, "id": ids,
                             "_enq_age_s": np.asarray(
                                 [max(0.0, now - e.t_enq) for e in live])}])
            # scoring runs under the TIGHTEST deadline in the batch so any
            # HTTP fan-out inside the pipeline (io/http, cognitive) clips
            # its own timeouts/retries to what the most impatient caller
            # still allows.  The batch span adopts the FIRST live entry's
            # trace id (one device pass serves many traces; per-entry
            # serving.request spans below carry each request's own id), and
            # installs it in this thread's context so io/http fan-out inside
            # the pipeline propagates it downstream.
            t_score0 = self.clock()
            try:
                with deadline_scope(Deadline(
                        min(e.t_deadline for e in live), self.clock)):
                    with trace_span("serving.score",
                                    trace_id=live[0].trace_id,
                                    attributes={"batch": len(live)},
                                    registry=self.registry, clock=self.clock):
                        out = self.model.transform(df).collect()
                replies = out[self.reply_col]
                for e, r in zip(live, replies):
                    # per-row shed sentinel (duck-typed `shed_reason`): a
                    # scorer refusing ONE row — mid-decode page denial —
                    # sheds that request without failing its batchmates
                    reason = getattr(r, "shed_reason", None)
                    if reason is not None:
                        e.status = 503
                        e.reply = {"error": f"shed: {reason}"}
                        e.retry_after_s = getattr(r, "retry_after_s", None) \
                            or self.shed_retry_after_s
                        verdicts[e.uid] = "shed_row"
                    else:
                        e.reply = self.reply_encoder(r)
            except Exception as ex:  # noqa: BLE001 — reply errors per-request
                if getattr(ex, "shed", False):
                    # backpressure raised out of the scorer (pool/slot
                    # exhaustion at admission): tell callers to back off
                    # instead of reporting a server fault
                    for e in live:
                        e.status = 503
                        e.reply = {"error": f"shed: {ex}"}
                        e.retry_after_s = self.shed_retry_after_s
                        verdicts[e.uid] = "shed_backpressure"
                else:
                    for e in live:
                        e.status, e.reply = 500, {"error": str(ex)}
            score_s = max(0.0, self.clock() - t_score0)
            for e in live:
                self._h_phase_score.observe(score_s, e.trace_id)
        with self.stats.lock:
            self._pending -= (len(batch) - len(deferred))
        for e in batch:
            if e.uid in deferred:
                continue
            # one serving.request span per entry, back-dated to its enqueue
            # time on the server clock: queue wait + score in one record,
            # joined to the caller's trace.  `server` scopes /debug/slow to
            # one instance in a shared registry; `verdict` names the
            # shed/deadline decision the slow-request view reports.
            verdict = verdicts.get(e.uid,
                                   "ok" if e.status == 200 else "error")
            span = Span("serving.request", trace_id=e.trace_id,
                        clock=self.clock, start_s=e.t_enq,
                        attributes={"status": e.status,
                                    "queue_s": round(max(0.0, now - e.t_enq), 6),
                                    "score_s": round(score_s, 6),
                                    "server": self._server_label,
                                    "verdict": verdict})
            if e.status != 200:
                span.status = f"http:{e.status}"
            span.finish()
            e.span_id = span.span_id  # before done.set(): the handler may
            export_span(span, self.registry)  # echo it in `traceparent`
            self._emit_record(e, verdict, max(0.0, now - e.t_enq), score_s)
            e.done.set()

    def _emit_record(self, e: _Entry, verdict: str, queue_s: float,
                     score_s: float, ttft_s: Optional[float] = None,
                     cost=None) -> None:
        """Append one canonical wide-event record for a terminal request
        (ISSUE 17) and, when it carried a decode cost ledger, book the
        per-class fleet rollups: tokens delivered only on 200s (the
        goodput numerator), device-seconds always — waste is exactly the
        cost the capacity model must keep seeing."""
        rec: Dict[str, Any] = {
            "trace_id": e.trace_id, "class": self.request_class,
            "verdict": verdict, "status": int(e.status),
            "queue_s": round(queue_s, 6), "score_s": round(score_s, 6)}
        if ttft_s is not None:
            rec["ttft_s"] = round(ttft_s, 6)
        if e.prompt_hash is not None:
            rec["prompt_hash"] = e.prompt_hash
        if cost is not None:
            rec["cost"] = cost.as_dict()
            if e.status == 200 and cost.decode_tokens > 0:
                self._c_class_tokens.inc(cost.decode_tokens)
            if cost.device_s > 0:
                self._c_class_device.inc(cost.device_s)
        self._records.append(rec)

    def _submit_continuous(self, e: _Entry, queue_s: float) -> bool:
        """Hand one admitted entry to the model's continuous engine.

        Returns True when the engine owns resolution (the entry's span,
        pending slot and done event are settled by the ``resolve`` callback
        on the engine thread, per request); False when admission failed —
        the entry's status is set here (503 for shed-typed failures, 500
        otherwise) and it rides the caller's normal resolution loop.

        Timing crosses the seam as RELATIVE durations (queue age, deadline
        budget) — the model's engine runs on its own clock and must never
        compare this server's (injectable) clock values."""
        t_submit = self.clock()

        def resolve(reply=None, status=200, verdict="ok",
                    retry_after_s=None, ttft_s=None, cost=None):
            # 200 replies ride the server's reply_encoder exactly like the
            # batch path — a custom encoder applies to both drains
            e.status = status
            e.reply = self.reply_encoder(reply) if status == 200 else reply
            if retry_after_s is not None:
                e.retry_after_s = retry_after_s
            score_s = max(0.0, self.clock() - t_submit)
            self._h_phase_score.observe(score_s, e.trace_id)
            with self.stats.lock:
                self._pending -= 1
            attrs = {"status": status,
                     "queue_s": round(queue_s, 6),
                     "score_s": round(score_s, 6),
                     "server": self._server_label,
                     "verdict": verdict}
            if ttft_s is not None:
                attrs["ttft_s"] = round(ttft_s, 6)
            span = Span("serving.request", trace_id=e.trace_id,
                        clock=self.clock, start_s=e.t_enq, attributes=attrs)
            if status != 200:
                span.status = f"http:{status}"
            span.finish()
            e.span_id = span.span_id  # before done.set(): traceparent echo
            export_span(span, self.registry)
            self._emit_record(e, verdict, queue_s, score_s,
                              ttft_s=ttft_s, cost=cost)
            e.done.set()

        try:
            kw = {"trace_id": e.trace_id} if self._submit_takes_trace else {}
            if self._submit_takes_hash:
                e.prompt_hash = _prompt_hash(e.payload)
                kw["prompt_hash"] = e.prompt_hash
            self._continuous_submit(
                e.payload, resolve=resolve,
                queue_age_s=max(0.0, t_submit - e.t_enq),
                deadline_budget_s=max(0.0, e.t_deadline - t_submit), **kw)
            return True
        except Exception as ex:  # noqa: BLE001 — admission failure shapes
            if getattr(ex, "shed", False):
                e.status = 503
                e.reply = {"error": f"shed: {ex}"}
                e.retry_after_s = self.shed_retry_after_s
            else:
                e.status, e.reply = 500, {"error": str(ex)}
            return False

    def _worker(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            # same lock as the inline fast path: scoring stays serialized
            # end-to-end, so pipeline stages may keep per-call scratch state
            with self._inline_lock:
                self._score_batch(batch)

    # ------------------------------------------------------------------ api
    def start(self) -> "PipelineServer":
        # environment pivot + device-memory series for this registry (both
        # idempotent; no-ops where jax or memory introspection is absent).
        # Registered from a daemon thread: ensure_* may initialize the jax
        # backend, and against a wedged TPU relay jax.local_devices() can
        # block for hours — serving startup must never ride that, and a
        # pure-python pipeline should pay no backend init at all on the
        # start path (the registry is thread-safe by contract).
        def _register_env_gauges():
            from ..observability.compute import (ensure_build_info,
                                                 ensure_device_memory_gauges)
            ensure_build_info(self.registry)
            ensure_device_memory_gauges(self.registry)
        threading.Thread(target=_register_env_gauges, daemon=True,
                         name="mmlspark-env-gauges").start()
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._make_handler())
        self.port = self._httpd.server_port  # resolve port=0
        # label children per resolved address; callback gauges sample live
        # state at scrape time (no push on the hot path)
        self._server_label = f"{self.host}:{self.port}"
        self._bind_metric_children()
        self._m_queue_depth.set_function(lambda: self._pending,
                                         server=self._server_label)
        self._m_queue_age.set_function(self._oldest_queue_age_s,
                                       server=self._server_label)
        self._m_ewma.set_function(lambda: self._queue_ewma,
                                  server=self._server_label)
        # postmortem source (ISSUE 17 satellite): a stall/crash/preemption
        # dump shows the last-K requests this server resolved before it
        # died, cost stanzas included
        self._record_source = f"requests:{self._server_label}"
        self._recorder.add_source(self._record_source, self._records.tail)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        w = threading.Thread(target=self._worker, daemon=True)
        w.start()
        self._threads.append(w)
        # SIGTERM/preemption -> graceful drain (ISSUE 16): any preemption
        # event (a signal landing in a preemption_scope, or a programmatic
        # request_preemption from a membership watcher) drains this server.
        # The hook only spawns the drain thread — hooks must never block
        # the checkpoint-and-exit path they observe.
        def _drain_on_preemption(reason, _self=self):
            threading.Thread(target=_self.drain,
                             kwargs={"timeout_s": _self.drain_timeout_s},
                             daemon=True, name="mmlspark-drain").start()
        self._preemption_hook = _drain_on_preemption
        register_preemption_hook(_drain_on_preemption)
        return self

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout_s: Optional[float] = None,
              poll_s: float = 0.02) -> bool:
        """Gracefully drain and stop: shed new admissions (503 ``draining``
        + ``Connection: close``), let the continuous engine's in-flight
        slots run to eos/budget (no new joins), wait for every admitted
        entry to resolve, then :meth:`stop`.

        Returns True when everything in flight resolved before the budget
        ran out; False means the drain timed out and ``stop()`` cancelled
        the stragglers (they resolve as cancelled — still counted, so the
        exactly-once stats invariant holds either way).  Idempotent:
        concurrent callers ride the first drain and share its verdict.
        """
        with self._drain_lock:
            first = not self._draining.is_set()
            if first:
                self._draining.set()
        if not first:
            self._drained.wait(timeout_s)
            return self._drained.is_set()
        t0 = self.clock()
        deadline = None if timeout_s is None else t0 + timeout_s
        ok = True
        # continuous engine first: existing slots run to eos/budget with no
        # new joins (duck-typed like continuous_submit — a pure-python
        # pipeline has nothing to drain)
        drainer = getattr(self.model, "continuous_drain", None)
        if drainer is not None:
            budget = None if deadline is None \
                else max(0.0, deadline - self.clock())
            ok = bool(drainer(budget)) and ok
        # then the admission ledger: every admitted entry must resolve
        # (micro-batch queue drained, handler threads replied) before the
        # listener goes away
        while True:
            with self.stats.lock:
                pending = self._pending
            if pending <= 0:
                break
            if deadline is not None and self.clock() >= deadline:
                ok = False
                break
            time.sleep(poll_s)
        self.stop()
        self._h_drain.observe(max(0.0, self.clock() - t0))
        self._drained.set()
        return ok

    def stop(self) -> None:
        self._stop.set()
        if self._preemption_hook is not None:
            unregister_preemption_hook(self._preemption_hook)
            self._preemption_hook = None
        if self._record_source is not None:
            self._recorder.remove_source(self._record_source)
            self._record_source = None
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # a continuous-decode scorer owns a live engine thread + borrowed
        # pool slabs: close it with the server (in-flight entries resolve
        # as cancelled; a restarted scorer lazily reopens the stream)
        closer = getattr(self.model, "continuous_close", None)
        if closer is not None:
            closer()
        # retire the accept/worker threads before returning: a stop() that
        # leaves the worker mid-drain races a restart's fresh worker into
        # the same scorer, and chaos drills cannot tell a leaked thread
        # from a hang.  Both loops observe _stop within one 0.1s poll, so
        # the join bound is slack, not a grace period.
        for t in self._threads:
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=5.0)
        self._threads = []
        # unhook the callback gauges: their closures capture this server,
        # so leaving them registered would pin a stopped server (and emit
        # frozen queue/EWMA series) for process lifetime.  Counter and
        # histogram series stay — they are history, and hold no objects.
        for g in (self._m_queue_depth, self._m_queue_age, self._m_ewma):
            g.remove(server=self._server_label)

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"


def _retry_after(seconds: float) -> str:
    """HTTP Retry-After is integer seconds; never advertise 0 (thundering
    herd of immediate retries)."""
    return str(max(1, int(round(seconds))))


def _default_encode(cell):
    if isinstance(cell, np.ndarray):
        return cell.tolist()
    if isinstance(cell, (np.floating, np.integer)):
        return cell.item()
    return cell


def _prompt_hash(payload) -> str:
    """Stable, content-derived identity for a prompt payload (ISSUE 20):
    equal prompts hash equal across requests and processes, so the record
    ring and the prefix-cache hit stats correlate.  Identity only — the
    index matches on token content, so a collision can never corrupt
    decode."""
    import hashlib
    try:
        canon = json.dumps(payload, sort_keys=True, default=str)
    except (TypeError, ValueError):
        canon = repr(payload)
    return hashlib.sha1(canon.encode()).hexdigest()[:16]


class DistributedPipelineServer:
    """Distributed variant: one PipelineServer per worker (the reference runs
    one ``JVMSharedServer`` per executor, ``DistributedHTTPSource.scala:90``,
    with a load balancer in front).  In-process this shards across N worker
    servers on consecutive ports; multi-host deployments run one per host
    behind an external LB, exactly like the reference's deployment doc
    (``docs/mmlspark-serving.md:87-120``)."""

    def __init__(self, model, num_servers: int = 2, base_port: int = 0, **kw):
        self.servers = [PipelineServer(model, port=base_port and base_port + i, **kw)
                        for i in range(num_servers)]

    def start(self):
        for s in self.servers:
            s.start()
        return self

    def stop(self):
        for s in self.servers:
            s.stop()

    @property
    def addresses(self):
        return [s.address for s in self.servers]
