"""Serving — low-latency model web service over pipeline transforms.

Reference: Spark Serving (``core/src/main/scala/org/apache/spark/sql/
execution/streaming/``, SURVEY.md §2.7):
- v1 head-node ``HTTPSource``/``HTTPSink`` (requests buffered as micro-batch
  offsets, replies matched by uuid);
- ``DistributedHTTPSource`` (per-executor ``JVMSharedServer`` +
  ``MultiChannelMap`` request sharding);
- v2 continuous mode (sub-ms replies; worker servers reply directly via
  ``HTTPSourceStateHolder.replyTo``).

TPU-native: the server is host-side Python (threaded HTTP, as the reference's
is JVM HttpServer); scoring goes through an already-jitted pipeline so the
device sees steady pre-compiled batch shapes.  ``continuous`` mode drains
whatever is queued into one dynamic micro-batch per transform (the latency/
throughput trick the reference gets from continuous processing);
``micro_batch`` mode flushes on a trigger interval.
"""
from __future__ import annotations

import json
import queue
import threading
import time
import uuid as uuid_mod
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import DataFrame, Transformer


@dataclass
class _Entry:
    uid: str
    payload: Any
    headers: Dict[str, str]
    done: threading.Event = field(default_factory=threading.Event)
    reply: Any = None
    status: int = 200


class ServingStats:
    """Request counters (reference DistributedHTTPSource.scala:99-110)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.received = 0
        self.replied = 0
        self.errors = 0
        self.latency_sum = 0.0

    def as_dict(self):
        with self.lock:
            n = max(1, self.replied)
            return {"received": self.received, "replied": self.replied,
                    "errors": self.errors,
                    "mean_latency_ms": 1000.0 * self.latency_sum / n}


class PipelineServer:
    """Serve a fitted pipeline as a JSON web service.

    POST <api_path> with a JSON object (one row) -> JSON reply from
    ``reply_col``.  GET /stats -> counters; GET /health -> ok.
    """

    def __init__(self, model: Transformer, input_col: str = "request",
                 reply_col: str = "reply", host: str = "127.0.0.1",
                 port: int = 8899, api_path: str = "/score",
                 mode: str = "continuous", max_batch: int = 64,
                 micro_batch_interval_ms: int = 10,
                 input_parser: Optional[Callable[[bytes], Any]] = None,
                 reply_encoder: Optional[Callable[[Any], Any]] = None,
                 request_timeout_s: float = 30.0):
        if mode not in ("continuous", "micro_batch"):
            raise ValueError("mode must be continuous|micro_batch")
        self.model = model
        self.input_col, self.reply_col = input_col, reply_col
        self.host, self.port, self.api_path = host, port, api_path
        self.mode = mode
        self.max_batch = max_batch
        self.interval_ms = micro_batch_interval_ms
        self.input_parser = input_parser or (lambda b: json.loads(b.decode() or "null"))
        self.reply_encoder = reply_encoder or _default_encode
        self.request_timeout_s = request_timeout_s
        self.stats = ServingStats()
        self._q: "queue.Queue[_Entry]" = queue.Queue()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # continuous-mode fast path: an idle handler thread scores its own
        # request inline instead of paying two thread hand-offs through the
        # queue (reference continuous mode reaches ~1 ms,
        # docs/mmlspark-serving.md:10-11; the hand-off alone costs ~0.5 ms)
        self._inline_lock = threading.Lock()

    # ------------------------------------------------------------------ http
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: persistent connections.  Every reply carries an
            # explicit Content-Length, so keep-alive is safe and a client
            # scoring a stream of rows pays TCP/handshake setup once, not
            # per request (the reference's continuous-mode latency claim
            # assumes exactly this client pattern).
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/health":
                    self._write_raw(200, b"ok", b"text/plain")
                elif self.path == "/stats":
                    self._write_raw(200,
                                    json.dumps(server.stats.as_dict()).encode())
                else:
                    self._respond(404, {"error": "not found"})

            def do_POST(self):
                # ALWAYS drain the body first: on keep-alive connections an
                # unread body would be parsed as the next request line,
                # desynchronizing the stream after any error reply
                t0 = time.perf_counter()
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if self.path != server.api_path:
                    self._respond(404, {"error": "not found"})
                    return
                try:
                    payload = server.input_parser(body)
                except Exception as e:  # noqa: BLE001
                    self._respond(400, {"error": f"bad request: {e}"})
                    return
                entry = _Entry(uid=str(uuid_mod.uuid4()), payload=payload,
                               headers=dict(self.headers))
                with server.stats.lock:
                    server.stats.received += 1
                if server.mode == "continuous" and \
                        server._inline_lock.acquire(blocking=False):
                    try:  # idle scorer: skip the queue hand-off entirely
                        server._score_batch([entry])
                    finally:
                        server._inline_lock.release()
                else:
                    server._q.put(entry)
                if not entry.done.wait(server.request_timeout_s):
                    self._respond(504, {"error": "timeout"})
                    with server.stats.lock:
                        server.stats.errors += 1
                    return
                # count BEFORE the socket write: a client that already holds
                # the reply must never observe replied lagging it (stats
                # aggregation raced the last in-flight write otherwise).  A
                # failed write rolls the count back as an error; latency is
                # sampled after the write so the metric's window is unchanged
                with server.stats.lock:
                    server.stats.replied += 1
                try:
                    self._respond(entry.status, entry.reply)
                    with server.stats.lock:
                        server.stats.latency_sum += time.perf_counter() - t0
                except OSError:
                    with server.stats.lock:
                        server.stats.replied -= 1
                        server.stats.errors += 1

            _STATUS = {200: b"200 OK", 400: b"400 Bad Request",
                       404: b"404 Not Found", 500: b"500 Internal Server Error",
                       504: b"504 Gateway Timeout"}

            def _write_raw(self, status, body, ctype=b"application/json"):
                # one buffered write per reply: status line + headers + body
                # in a single syscall/TCP segment (the default handler path
                # issues one write per header, which interacts badly with
                # delayed ACKs on loopback)
                self.wfile.write(
                    b"HTTP/1.1 " + self._STATUS.get(status, b"500 ISE")
                    + b"\r\nContent-Type: " + ctype
                    + b"\r\nContent-Length: " + str(len(body)).encode()
                    + b"\r\n\r\n" + body)

            def _respond(self, status, obj):
                self._write_raw(status, json.dumps(obj, default=str).encode())

        return Handler

    # ------------------------------------------------------------------ work
    def _drain(self) -> List[_Entry]:
        try:
            first = self._q.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        if self.mode == "micro_batch":
            deadline = time.monotonic() + self.interval_ms / 1000.0
            while len(batch) < self.max_batch and time.monotonic() < deadline:
                try:
                    batch.append(self._q.get(timeout=max(0.0, deadline - time.monotonic())))
                except queue.Empty:
                    break
        else:  # continuous: take whatever is already waiting
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
        return batch

    def _score_batch(self, batch: List[_Entry]) -> None:
        """Run the pipeline over a batch of entries and resolve each one.
        Called from the worker thread and, in continuous mode, inline from
        an idle handler thread (guarded by ``_inline_lock``)."""
        col = np.empty(len(batch), dtype=object)
        for i, e in enumerate(batch):
            col[i] = e.payload
        ids = np.asarray([e.uid for e in batch], dtype=object)
        df = DataFrame([{self.input_col: col, "id": ids}])
        try:
            out = self.model.transform(df).collect()
            replies = out[self.reply_col]
            for e, r in zip(batch, replies):
                e.reply = self.reply_encoder(r)
                e.done.set()
        except Exception as ex:  # noqa: BLE001 — reply errors per-request
            for e in batch:
                e.status, e.reply = 500, {"error": str(ex)}
                e.done.set()
            with self.stats.lock:
                self.stats.errors += len(batch)

    def _worker(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            # same lock as the inline fast path: scoring stays serialized
            # end-to-end, so pipeline stages may keep per-call scratch state
            with self._inline_lock:
                self._score_batch(batch)

    # ------------------------------------------------------------------ api
    def start(self) -> "PipelineServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._make_handler())
        self.port = self._httpd.server_port  # resolve port=0
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        w = threading.Thread(target=self._worker, daemon=True)
        w.start()
        self._threads.append(w)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"


def _default_encode(cell):
    if isinstance(cell, np.ndarray):
        return cell.tolist()
    if isinstance(cell, (np.floating, np.integer)):
        return cell.item()
    return cell


class DistributedPipelineServer:
    """Distributed variant: one PipelineServer per worker (the reference runs
    one ``JVMSharedServer`` per executor, ``DistributedHTTPSource.scala:90``,
    with a load balancer in front).  In-process this shards across N worker
    servers on consecutive ports; multi-host deployments run one per host
    behind an external LB, exactly like the reference's deployment doc
    (``docs/mmlspark-serving.md:87-120``)."""

    def __init__(self, model, num_servers: int = 2, base_port: int = 0, **kw):
        self.servers = [PipelineServer(model, port=base_port and base_port + i, **kw)
                        for i in range(num_servers)]

    def start(self):
        for s in self.servers:
            s.start()
        return self

    def stop(self):
        for s in self.servers:
            s.stop()

    @property
    def addresses(self):
        return [s.address for s in self.servers]
