"""Serving — low-latency model web service over pipeline transforms.

Reference: Spark Serving (``core/src/main/scala/org/apache/spark/sql/
execution/streaming/``, SURVEY.md §2.7):
- v1 head-node ``HTTPSource``/``HTTPSink`` (requests buffered as micro-batch
  offsets, replies matched by uuid);
- ``DistributedHTTPSource`` (per-executor ``JVMSharedServer`` +
  ``MultiChannelMap`` request sharding);
- v2 continuous mode (sub-ms replies; worker servers reply directly via
  ``HTTPSourceStateHolder.replyTo``).

TPU-native: the server is host-side Python (threaded HTTP, as the reference's
is JVM HttpServer); scoring goes through an already-jitted pipeline so the
device sees steady pre-compiled batch shapes.  ``continuous`` mode drains
whatever is queued into one dynamic micro-batch per transform (the latency/
throughput trick the reference gets from continuous processing);
``micro_batch`` mode flushes on a trigger interval.
"""
from __future__ import annotations

import json
import queue
import threading
import time
import uuid as uuid_mod
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import DataFrame, Transformer
from ..utils.resilience import Deadline, deadline_scope


@dataclass
class _Entry:
    uid: str
    payload: Any
    headers: Dict[str, str]
    done: threading.Event = field(default_factory=threading.Event)
    reply: Any = None
    status: int = 200
    # absolute expiry on the server clock; a plain float (not a Deadline
    # object) keeps the per-request hot path allocation-free
    t_deadline: float = float("inf")
    t_enq: float = 0.0
    retry_after_s: Optional[float] = None


class ServingStats:
    """Request counters (reference DistributedHTTPSource.scala:99-110).

    Each request is counted EXACTLY once by its handler thread:
    ``replied`` (200 written), ``errors`` (500/504/failed write), or
    ``shed`` (503 load shed).  At quiescence
    ``received == replied + errors + shed``; mid-flight, admitted-but-
    unresolved requests make up the difference.
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.received = 0
        self.replied = 0
        self.errors = 0
        self.shed = 0
        self.latency_sum = 0.0

    def as_dict(self):
        with self.lock:
            n = max(1, self.replied)
            return {"received": self.received, "replied": self.replied,
                    "errors": self.errors, "shed": self.shed,
                    "mean_latency_ms": 1000.0 * self.latency_sum / n}


class PipelineServer:
    """Serve a fitted pipeline as a JSON web service.

    POST <api_path> with a JSON object (one row) -> JSON reply from
    ``reply_col``.  GET /stats -> counters; GET /health -> ok.

    Graceful degradation: admission is bounded — once ``max_queue_depth``
    requests are in flight, further POSTs are shed immediately with 503 +
    ``Retry-After`` instead of queueing toward certain timeout (the
    reference's LB would do this upstream; in-process we must).  Each
    request carries a deadline (``X-MMLSpark-Deadline-Ms`` header if the
    client sent one, else ``request_timeout_s``); the scorer drops entries
    whose budget expired in the queue (504) or whose queue age exceeds
    ``max_queue_age_s`` (503) without wasting device time on them.
    """

    def __init__(self, model: Transformer, input_col: str = "request",
                 reply_col: str = "reply", host: str = "127.0.0.1",
                 port: int = 8899, api_path: str = "/score",
                 mode: str = "continuous", max_batch: int = 64,
                 micro_batch_interval_ms: int = 10,
                 input_parser: Optional[Callable[[bytes], Any]] = None,
                 reply_encoder: Optional[Callable[[Any], Any]] = None,
                 request_timeout_s: float = 30.0,
                 max_queue_depth: int = 256,
                 max_queue_age_s: Optional[float] = None,
                 shed_retry_after_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if mode not in ("continuous", "micro_batch"):
            raise ValueError("mode must be continuous|micro_batch")
        self.model = model
        self.input_col, self.reply_col = input_col, reply_col
        self.host, self.port, self.api_path = host, port, api_path
        self.mode = mode
        self.max_batch = max_batch
        self.interval_ms = micro_batch_interval_ms
        self.input_parser = input_parser or (lambda b: json.loads(b.decode() or "null"))
        self.reply_encoder = reply_encoder or _default_encode
        self.request_timeout_s = request_timeout_s
        self.max_queue_depth = max_queue_depth
        self.max_queue_age_s = max_queue_age_s
        self.shed_retry_after_s = shed_retry_after_s
        self.clock = clock
        self.stats = ServingStats()
        self._pending = 0  # admitted, not yet resolved (guarded by stats.lock)
        self._q: "queue.Queue[_Entry]" = queue.Queue()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # continuous-mode fast path: an idle handler thread scores its own
        # request inline instead of paying two thread hand-offs through the
        # queue (reference continuous mode reaches ~1 ms,
        # docs/mmlspark-serving.md:10-11; the hand-off alone costs ~0.5 ms)
        self._inline_lock = threading.Lock()

    # ------------------------------------------------------------------ http
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: persistent connections.  Every reply carries an
            # explicit Content-Length, so keep-alive is safe and a client
            # scoring a stream of rows pays TCP/handshake setup once, not
            # per request (the reference's continuous-mode latency claim
            # assumes exactly this client pattern).
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/health":
                    self._write_raw(200, b"ok", b"text/plain")
                elif self.path == "/stats":
                    d = server.stats.as_dict()
                    with server.stats.lock:
                        d["pending"] = server._pending
                    self._write_raw(200, json.dumps(d).encode())
                else:
                    self._respond(404, {"error": "not found"})

            def do_POST(self):
                # ALWAYS drain the body first: on keep-alive connections an
                # unread body would be parsed as the next request line,
                # desynchronizing the stream after any error reply
                t0 = time.perf_counter()
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if self.path != server.api_path:
                    self._respond(404, {"error": "not found"})
                    return
                try:
                    payload = server.input_parser(body)
                except Exception as e:  # noqa: BLE001
                    self._respond(400, {"error": f"bad request: {e}"})
                    return
                # the caller's remaining budget rides the deadline header;
                # without one the server default bounds the request
                t_enq = server.clock()
                budget_s = server.request_timeout_s
                hdr = self.headers.get(Deadline.HEADER)
                if hdr:
                    try:
                        budget_s = min(budget_s, max(0.0, float(hdr)) / 1000.0)
                    except ValueError:
                        pass
                entry = _Entry(uid=str(uuid_mod.uuid4()), payload=payload,
                               headers=dict(self.headers), t_enq=t_enq,
                               t_deadline=t_enq + budget_s)
                # bounded admission: shedding beats queueing toward a
                # certain timeout (503 tells the client to back off; 504
                # would have cost it request_timeout_s of waiting first)
                with server.stats.lock:
                    server.stats.received += 1
                    admitted = server._pending < server.max_queue_depth
                    if admitted:
                        server._pending += 1
                    else:
                        server.stats.shed += 1
                if not admitted:
                    self._respond(503, {"error": "overloaded: queue full"},
                                  extra_headers={
                                      "Retry-After":
                                      _retry_after(server.shed_retry_after_s)})
                    return
                if server.mode == "continuous" and \
                        server._inline_lock.acquire(blocking=False):
                    try:  # idle scorer: skip the queue hand-off entirely
                        server._score_batch([entry])
                    finally:
                        server._inline_lock.release()
                else:
                    server._q.put(entry)
                # wait no longer than the caller still cares about
                if not entry.done.wait(budget_s):
                    self._respond(504, {"error": "timeout"})
                    with server.stats.lock:
                        server.stats.errors += 1
                    return
                # count BEFORE the socket write: a client that already holds
                # the reply must never observe its counter lagging (stats
                # aggregation raced the last in-flight write otherwise).  A
                # failed write rolls the count back as an error; latency is
                # sampled after the write so the metric's window is unchanged
                status = entry.status
                stats = server.stats
                extra = None
                if status == 503:
                    extra = {"Retry-After": _retry_after(
                        entry.retry_after_s or server.shed_retry_after_s)}
                try:
                    if status == 200:
                        with stats.lock:
                            stats.replied += 1
                        self._respond(200, entry.reply)
                        # latency is a SUCCESS metric: mean_latency_ms
                        # divides by replied, so only 200s may feed the sum
                        with stats.lock:
                            stats.latency_sum += time.perf_counter() - t0
                    elif status == 503:
                        with stats.lock:
                            stats.shed += 1
                        self._respond(503, entry.reply, extra_headers=extra)
                    else:
                        with stats.lock:
                            stats.errors += 1
                        self._respond(status, entry.reply)
                except Exception:  # any failed write: invariant must hold
                    with stats.lock:
                        if status == 200:
                            stats.replied -= 1
                        elif status == 503:
                            stats.shed -= 1
                        else:
                            stats.errors -= 1
                        stats.errors += 1
                    raise

            _STATUS = {200: b"200 OK", 400: b"400 Bad Request",
                       404: b"404 Not Found", 500: b"500 Internal Server Error",
                       503: b"503 Service Unavailable",
                       504: b"504 Gateway Timeout"}

            def _write_raw(self, status, body, ctype=b"application/json",
                           extra_headers=None):
                # one buffered write per reply: status line + headers + body
                # in a single syscall/TCP segment (the default handler path
                # issues one write per header, which interacts badly with
                # delayed ACKs on loopback)
                hdrs = b""
                for k, v in (extra_headers or {}).items():
                    hdrs += k.encode() + b": " + str(v).encode() + b"\r\n"
                self.wfile.write(
                    b"HTTP/1.1 " + self._STATUS.get(status, b"500 ISE")
                    + b"\r\nContent-Type: " + ctype
                    + b"\r\n" + hdrs
                    + b"Content-Length: " + str(len(body)).encode()
                    + b"\r\n\r\n" + body)

            def _respond(self, status, obj, extra_headers=None):
                self._write_raw(status, json.dumps(obj, default=str).encode(),
                                extra_headers=extra_headers)

        return Handler

    # ------------------------------------------------------------------ work
    def _drain(self) -> List[_Entry]:
        try:
            first = self._q.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        if self.mode == "micro_batch":
            deadline = time.monotonic() + self.interval_ms / 1000.0
            while len(batch) < self.max_batch and time.monotonic() < deadline:
                try:
                    batch.append(self._q.get(timeout=max(0.0, deadline - time.monotonic())))
                except queue.Empty:
                    break
        else:  # continuous: take whatever is already waiting
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
        return batch

    def _score_batch(self, batch: List[_Entry]) -> None:
        """Run the pipeline over a batch of entries and resolve each one.
        Called from the worker thread and, in continuous mode, inline from
        an idle handler thread (guarded by ``_inline_lock``).

        Entries that expired in the queue are resolved without scoring:
        504 when the caller's deadline is gone (it stopped listening), 503
        shed when queue age exceeds ``max_queue_age_s`` (overload — tell
        the caller to back off rather than burn device time on stale work).
        Counting happens in the handler threads (exactly once per request),
        never here; this thread only frees admission slots and wakes them.
        """
        now = self.clock()
        live: List[_Entry] = []
        for e in batch:
            if now > e.t_deadline:
                e.status, e.reply = 504, {"error": "deadline expired in queue"}
            elif self.max_queue_age_s is not None and \
                    now - e.t_enq > self.max_queue_age_s:
                e.status, e.reply = 503, {"error": "shed: queue age exceeded"}
                e.retry_after_s = self.shed_retry_after_s
            else:
                live.append(e)
        if live:
            col = np.empty(len(live), dtype=object)
            for i, e in enumerate(live):
                col[i] = e.payload
            ids = np.asarray([e.uid for e in live], dtype=object)
            df = DataFrame([{self.input_col: col, "id": ids}])
            # scoring runs under the TIGHTEST deadline in the batch so any
            # HTTP fan-out inside the pipeline (io/http, cognitive) clips
            # its own timeouts/retries to what the most impatient caller
            # still allows
            try:
                with deadline_scope(Deadline(
                        min(e.t_deadline for e in live), self.clock)):
                    out = self.model.transform(df).collect()
                replies = out[self.reply_col]
                for e, r in zip(live, replies):
                    e.reply = self.reply_encoder(r)
            except Exception as ex:  # noqa: BLE001 — reply errors per-request
                for e in live:
                    e.status, e.reply = 500, {"error": str(ex)}
        with self.stats.lock:
            self._pending -= len(batch)
        for e in batch:
            e.done.set()

    def _worker(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            # same lock as the inline fast path: scoring stays serialized
            # end-to-end, so pipeline stages may keep per-call scratch state
            with self._inline_lock:
                self._score_batch(batch)

    # ------------------------------------------------------------------ api
    def start(self) -> "PipelineServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._make_handler())
        self.port = self._httpd.server_port  # resolve port=0
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        w = threading.Thread(target=self._worker, daemon=True)
        w.start()
        self._threads.append(w)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"


def _retry_after(seconds: float) -> str:
    """HTTP Retry-After is integer seconds; never advertise 0 (thundering
    herd of immediate retries)."""
    return str(max(1, int(round(seconds))))


def _default_encode(cell):
    if isinstance(cell, np.ndarray):
        return cell.tolist()
    if isinstance(cell, (np.floating, np.integer)):
        return cell.item()
    return cell


class DistributedPipelineServer:
    """Distributed variant: one PipelineServer per worker (the reference runs
    one ``JVMSharedServer`` per executor, ``DistributedHTTPSource.scala:90``,
    with a load balancer in front).  In-process this shards across N worker
    servers on consecutive ports; multi-host deployments run one per host
    behind an external LB, exactly like the reference's deployment doc
    (``docs/mmlspark-serving.md:87-120``)."""

    def __init__(self, model, num_servers: int = 2, base_port: int = 0, **kw):
        self.servers = [PipelineServer(model, port=base_port and base_port + i, **kw)
                        for i in range(num_servers)]

    def start(self):
        for s in self.servers:
            s.start()
        return self

    def stop(self):
        for s in self.servers:
            s.stop()

    @property
    def addresses(self):
        return [s.address for s in self.servers]
