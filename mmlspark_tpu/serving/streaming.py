"""Structured-streaming facade for serving — source/sink over HTTP requests.

Reference: ``spark.readStream.server().address(h,p,api).load()`` ... pipeline
... ``.makeReply(col).writeStream.server().replyTo(api).start()``
(``io/IOImplicits.scala:22-74``, ``ServingUDFs.scala:22-49``;  micro-batch
source semantics ``HTTPSource.scala:43-140``: buffered requests ARE the
stream offsets, replies matched by uuid).

Here the same three pieces exist as first-class objects:

- ``HTTPStreamSource`` — binds a socket, buffers requests, and emits them as
  micro-batch ``DataFrame``s of ``(id, request)`` rows via ``get_batch``;
- ``reply`` — the sink half: complete requests by id (``sendReplyUDF``);
- ``StreamingQuery`` — the driver loop tying a source, a pipeline transform
  and the reply sink together with a trigger interval, exposed through
  ``read_stream().server(...)`` / ``.start()`` fluent wiring.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import DataFrame, Transformer
from .server import ServingStats, _default_encode, _prompt_hash


def _takes_prompt_hash(submit) -> bool:
    """Whether a continuous-submit front declares ``prompt_hash=``
    (ISSUE 20) — same duck-typed introspection as the PipelineServer
    seam, so older fronts never see a kwarg they did not ask for."""
    import inspect
    try:
        params = inspect.signature(submit).parameters
    except (TypeError, ValueError):
        return False
    return "prompt_hash" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())

# request ids key the pending-reply map: process uniqueness suffices, and
# uuid4's per-call entropy syscall sat on the request hot path (same
# counter pattern as serving/server.py entry ids and tracing span ids)
_REQUEST_IDS = itertools.count()


class _Pending:
    __slots__ = ("payload", "done", "reply", "status")

    def __init__(self, payload):
        self.payload = payload
        self.done = threading.Event()
        self.reply = None
        self.status = 200


class HTTPStreamSource:
    """Micro-batch source: buffered HTTP requests are the stream."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/score", id_col: str = "id",
                 value_col: str = "request",
                 input_parser: Optional[Callable[[bytes], Any]] = None,
                 request_timeout_s: float = 30.0):
        self.host, self.port, self.api_path = host, port, api_path
        self.id_col, self.value_col = id_col, value_col
        self.input_parser = input_parser or (lambda b: json.loads(b.decode() or "null"))
        self.request_timeout_s = request_timeout_s
        self.stats = ServingStats()
        self._buf: List[str] = []
        self._pending: Dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._accept_thread: Optional[threading.Thread] = None

    def _make_handler(self):
        src = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/health":
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                elif self.path == "/stats":
                    body = json.dumps(src.stats.as_dict()).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def do_POST(self):
                if self.path != src.api_path:
                    self.send_response(404)
                    self.end_headers()
                    return
                t0 = time.perf_counter()
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = src.input_parser(self.rfile.read(length))
                except Exception as e:  # noqa: BLE001
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                uid = f"r{next(_REQUEST_IDS):x}"
                entry = _Pending(payload)
                with src._lock:
                    src._pending[uid] = entry
                    src._buf.append(uid)
                with src.stats.lock:
                    src.stats.received += 1
                ok = entry.done.wait(src.request_timeout_s)
                with src._lock:
                    src._pending.pop(uid, None)
                if not ok:
                    self._json(504, {"error": "timeout"})
                    with src.stats.lock:
                        src.stats.errors += 1
                    return
                # count before the socket write (see server.py: a client
                # holding the reply must never observe replied lagging it);
                # failed writes roll back as errors, latency sampled after
                with src.stats.lock:
                    src.stats.replied += 1
                try:
                    self._json(entry.status, entry.reply)
                    if entry.status == 200:
                        # latency is a SUCCESS metric (ServingStats
                        # contract): scorer-set 500s must not feed the pair
                        with src.stats.lock:
                            src.stats.latency_sum += time.perf_counter() - t0
                            src.stats.latency_count += 1
                except OSError:
                    with src.stats.lock:
                        src.stats.replied -= 1
                        src.stats.errors += 1

            def _json(self, status, obj):
                body = json.dumps(obj, default=str).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        return Handler

    # ---------------------------------------------------------------- source
    def start(self) -> "HTTPStreamSource":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_port
        self._accept_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        t, self._accept_thread = self._accept_thread, None
        if t is not None and t.is_alive():
            # shutdown() already unwound serve_forever; the join only
            # fences the handoff so a restart cannot race the old acceptor
            t.join(timeout=5.0)

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def get_batch(self, max_rows: int = 1024) -> Optional[DataFrame]:
        """Drain up to ``max_rows`` buffered requests as one micro-batch
        (the offset-range read, ``HTTPSource.getBatch``)."""
        with self._lock:
            ids, self._buf = self._buf[:max_rows], self._buf[max_rows:]
            entries = [self._pending.get(u) for u in ids]
        rows = [(u, e) for u, e in zip(ids, entries) if e is not None]
        if not rows:
            return None
        vals = np.empty(len(rows), dtype=object)
        for i, (_, e) in enumerate(rows):
            vals[i] = e.payload
        return DataFrame([{self.id_col: np.asarray([u for u, _ in rows],
                                                   dtype=object),
                           self.value_col: vals}])

    def reply(self, ids, replies, encoder=None) -> None:
        """Sink half: complete requests by id (``ServingUDFs.sendReplyUDF``).
        A per-row shed sentinel (duck-typed ``shed_reason`` — the decode
        scorer's mid-flight page denial, ISSUE 13) completes as a 503 shed
        instead of encoding the sentinel object into a 200 body."""
        encoder = encoder or _default_encode
        with self._lock:
            entries = [self._pending.get(str(u)) for u in ids]
        for e, r in zip(entries, replies):
            if e is not None:
                reason = getattr(r, "shed_reason", None)
                if reason is not None:
                    e.status, e.reply = 503, {"error": f"shed: {reason}"}
                else:
                    e.reply = encoder(r)
                e.done.set()


class StreamingQuery:
    """The running query: trigger loop of get_batch -> transform -> reply."""

    def __init__(self, source: HTTPStreamSource, model: Transformer,
                 reply_col: str, trigger_interval_ms: int = 1,
                 max_rows: int = 1024):
        self.source = source
        self.model = model
        self.reply_col = reply_col
        self.interval_s = trigger_interval_ms / 1000.0
        self.max_rows = max_rows
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_error: Optional[str] = None

    def _loop(self):
        # continuous admission (ISSUE 13): a model exposing
        # `continuous_submit` (the runner's continuous decode scorer) gets
        # each drained row the moment the trigger sees it, and every row
        # replies from the model's own engine as IT finishes — the trigger
        # loop goes back to draining instead of blocking on the batch
        submit = getattr(self.model, "continuous_submit", None)
        takes_hash = _takes_prompt_hash(submit) if submit is not None \
            else False
        while not self._stop.is_set():
            batch = self.source.get_batch(self.max_rows)
            if batch is None:
                time.sleep(self.interval_s)
                continue
            cols = batch.collect()
            ids = cols[self.source.id_col]
            if submit is not None:
                vals = cols[self.source.value_col]
                for u, v in zip(ids, vals):
                    self._submit_one(submit, str(u), v,
                                     takes_hash=takes_hash)
                continue
            try:
                out = self.model.transform(batch).collect()
                self.source.reply(ids, out[self.reply_col])
            except Exception as e:  # noqa: BLE001 — reply the error per-row
                self.last_error = str(e)
                with self.source._lock:
                    entries = [self.source._pending.get(str(u)) for u in ids]
                for en in entries:
                    if en is not None:
                        en.status, en.reply = 500, {"error": str(e)}
                        en.done.set()

    def _submit_one(self, submit, uid: str, payload,
                    takes_hash: bool = False) -> None:
        """Admit one row into the model's in-flight engine; shed-typed
        admission failures reply 503 so the client backs off.  When the
        front declares ``prompt_hash=`` the row's stable prompt identity
        rides along (ISSUE 20 — the prefix-cache admission seam)."""
        def resolve(reply=None, status=200, verdict=None,
                    retry_after_s=None, ttft_s=None):
            with self.source._lock:
                entry = self.source._pending.get(uid)
            if entry is not None:
                entry.status = status
                # 200s ride the same default encoding as the batch sink
                entry.reply = _default_encode(reply) if status == 200 \
                    else reply
                entry.done.set()

        try:
            kw = {"prompt_hash": _prompt_hash(payload)} if takes_hash else {}
            submit(payload, resolve=resolve, **kw)
        except Exception as e:  # noqa: BLE001 — per-row admission verdict
            self.last_error = str(e)
            status = 503 if getattr(e, "shed", False) else 500
            prefix = "shed: " if status == 503 else ""
            resolve(reply={"error": f"{prefix}{e}"}, status=status)

    def start(self) -> "StreamingQuery":
        self.source.start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None and thread.is_alive():
            # the trigger loop wakes within one interval (or one drained
            # batch); an unjoined loop here would race a restarted query
            # into the same source's pending map
            thread.join(timeout=5.0)
        self.source.stop()
        closer = getattr(self.model, "continuous_close", None)
        if closer is not None:
            closer()

    def await_termination(self, timeout_s: float) -> None:
        time.sleep(timeout_s)


class _StreamReader:
    """Fluent ``read_stream().server(...)`` wiring (IOImplicits analogue)."""

    def server(self, host: str = "127.0.0.1", port: int = 0,
               api_path: str = "/score", **kw) -> "_StreamPipeline":
        return _StreamPipeline(HTTPStreamSource(host, port, api_path, **kw))


class _StreamPipeline:
    def __init__(self, source: HTTPStreamSource):
        self.source = source
        self._model = None
        self._scorer_kwargs: Dict[str, Any] = {}

    def transform_with(self, model, **scorer_kwargs) -> "_StreamPipeline":
        """Score micro-batches through ``model``: a fitted ``Transformer``,
        or a ``models.ModelRunner`` directly (ISSUE 9) — the runner is
        wrapped in its serving scorer at ``reply_to`` time, bound to this
        source's value column, so streaming scoring rides the SAME
        lower-once executable cache as batch transform and PipelineServer
        (``scorer_kwargs`` forward, e.g. ``mode="decode"``,
        ``max_new_tokens=``)."""
        self._model = model
        self._scorer_kwargs = dict(scorer_kwargs)
        return self

    def reply_to(self, reply_col: str, trigger_interval_ms: int = 1) -> StreamingQuery:
        if self._model is None:
            raise ValueError("call transform_with(model) before reply_to")
        model = self._model
        if not isinstance(model, Transformer) and hasattr(model, "scorer"):
            model = model.scorer(input_col=self.source.value_col,
                                 reply_col=reply_col, **self._scorer_kwargs)
        return StreamingQuery(self.source, model, reply_col,
                              trigger_interval_ms).start()


def read_stream() -> _StreamReader:
    """``spark.readStream`` analogue for the serving engine."""
    return _StreamReader()
