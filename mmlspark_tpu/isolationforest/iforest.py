"""Isolation forest anomaly detection.

Reference: ``isolationforest/IsolationForest.scala:16-65`` — a thin wrapper
over LinkedIn's isolation-forest library with params (numEstimators,
maxSamples, contamination, maxFeatures, scoreCol, predictedLabelCol).  Here
the forest is in-tree: isolation trees are grown host-side (they're tiny —
256-sample subsamples), and scoring walks all trees vectorised per batch.
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, HasFeaturesCol,
                    HasPredictionCol, Model, Param)
from ..core.schema import ColumnType, stack_vector_column
from ..core.serialize import Saveable


def _c(n: float) -> float:
    """Average BST unsuccessful-search path length (iForest normalizer)."""
    if n <= 1:
        return 0.0
    return 2.0 * (math.log(n - 1) + 0.5772156649) - 2.0 * (n - 1) / n


class _ITree:
    __slots__ = ("feature", "threshold", "left", "right", "size")

    def __init__(self, feature=-1, threshold=0.0, left=None, right=None, size=0):
        self.feature, self.threshold = feature, threshold
        self.left, self.right, self.size = left, right, size

    def path_length(self, X: np.ndarray, depth: int = 0) -> np.ndarray:
        if self.feature < 0 or self.left is None:
            return np.full(len(X), depth + _c(self.size))
        mask = X[:, self.feature] < self.threshold
        out = np.empty(len(X))
        if mask.any():
            out[mask] = self.left.path_length(X[mask], depth + 1)
        if (~mask).any():
            out[~mask] = self.right.path_length(X[~mask], depth + 1)
        return out


class _Forest(Saveable):
    def __init__(self, trees: List[_ITree], sub_size: int):
        self.trees = trees
        self.sub_size = sub_size

    def scores(self, X: np.ndarray) -> np.ndarray:
        depths = np.mean([t.path_length(X) for t in self.trees], axis=0)
        return 2.0 ** (-depths / _c(self.sub_size))

    def save(self, path: str) -> None:
        import os, pickle
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "forest.pkl"), "wb") as f:
            pickle.dump(self, f)

    @classmethod
    def load(cls, path: str):
        import os, pickle
        with open(os.path.join(path, "forest.pkl"), "rb") as f:
            return pickle.load(f)


def _grow(X: np.ndarray, depth: int, max_depth: int, rng) -> _ITree:
    n = len(X)
    if depth >= max_depth or n <= 1:
        return _ITree(size=n)
    f = int(rng.integers(0, X.shape[1]))
    lo, hi = X[:, f].min(), X[:, f].max()
    if lo == hi:
        return _ITree(size=n)
    thr = float(rng.uniform(lo, hi))
    mask = X[:, f] < thr
    return _ITree(f, thr, _grow(X[mask], depth + 1, max_depth, rng),
                  _grow(X[~mask], depth + 1, max_depth, rng), n)


class IsolationForest(Estimator, HasFeaturesCol, HasPredictionCol):
    num_estimators = Param("num_estimators", "number of trees", "int", default=100)
    max_samples = Param("max_samples", "subsample per tree", "int", default=256)
    max_features = Param("max_features", "feature fraction per tree", "float", default=1.0)
    contamination = Param("contamination", "expected outlier fraction (sets "
                          "the predicted-label threshold)", "float", default=0.0)
    score_col = Param("score_col", "anomaly score output", "string", default="outlier_score")
    predicted_label_col = Param("predicted_label_col", "0/1 outlier label",
                                "string", default="predicted_label")
    random_seed = Param("random_seed", "seed", "int", default=1)

    def _fit(self, df: DataFrame) -> "IsolationForestModel":
        X = stack_vector_column(df.collect()[self.get_or_fail("features_col")])
        rng = np.random.default_rng(self.get("random_seed"))
        sub = min(self.get("max_samples"), len(X))
        max_depth = int(math.ceil(math.log2(max(sub, 2))))
        trees = []
        for _ in range(self.get("num_estimators")):
            idx = rng.choice(len(X), sub, replace=False)
            Xs = X[idx]
            f_frac = self.get("max_features")
            if f_frac < 1.0:
                keep = rng.choice(X.shape[1], max(1, int(f_frac * X.shape[1])),
                                  replace=False)
                proj = np.zeros_like(Xs)
                proj[:, keep] = Xs[:, keep]
                Xs = proj
            trees.append(_grow(Xs, 0, max_depth, rng))
        forest = _Forest(trees, sub)
        threshold = 0.5
        cont = self.get("contamination")
        if cont and cont > 0:
            threshold = float(np.quantile(forest.scores(X), 1.0 - cont))
        m = IsolationForestModel()
        m.set("forest", forest)
        m.set("threshold", threshold)
        for pcol in ("features_col", "score_col", "predicted_label_col"):
            m.set(pcol, self.get(pcol))
        return m


class IsolationForestModel(Model, HasFeaturesCol):
    forest = ComplexParam("forest", "fitted isolation forest")
    threshold = Param("threshold", "outlier score threshold", "float", default=0.5)
    score_col = Param("score_col", "score output", "string", default="outlier_score")
    predicted_label_col = Param("predicted_label_col", "label output", "string",
                                default="predicted_label")

    def _transform(self, df: DataFrame) -> DataFrame:
        forest: _Forest = self.get_or_fail("forest")
        thr = self.get("threshold")
        fc = self.get_or_fail("features_col")

        def per_part(p):
            X = stack_vector_column(p[fc])
            s = forest.scores(X)
            return {**p, self.get("score_col"): s,
                    self.get("predicted_label_col"): (s >= thr).astype(np.float64)}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("features_col"))
        return schema.add(self.get("score_col"), ColumnType.DOUBLE) \
            .add(self.get("predicted_label_col"), ColumnType.DOUBLE)
