from .registry import all_stage_classes, instantiate_default
from .codegen import generate_stub_file, generate_docs, generate_all
from .testgen import generate_tests
from .rgen import generate_r_classes

__all__ = ["all_stage_classes", "instantiate_default", "generate_stub_file",
           "generate_docs", "generate_all", "generate_tests", "generate_r_classes"]
