"""Stage registry — reflection over every pipeline stage in the package.

Reference: ``JarLoadingUtils`` (``core/utils/JarLoadingUtils.scala``) walks
the jars to find every ``PipelineStage``; the codegen driver and the global
``FuzzingTest`` sweep (``src/test/.../FuzzingTest.scala:18``) both consume it
so coverage is enforced by construction.
"""
from __future__ import annotations

import importlib
import inspect
import pkgutil
from typing import Dict, List, Optional, Type

SUBPACKAGES = ["core", "stages", "featurize", "train", "lightgbm", "vw", "dl",
               "io", "serving", "cognitive", "nn", "recommendation",
               "isolationforest", "automl", "explainers", "opencv", "cyber"]


def _iter_modules():
    import mmlspark_tpu
    for sub in SUBPACKAGES:
        pkg = importlib.import_module(f"mmlspark_tpu.{sub}")
        yield pkg
        if hasattr(pkg, "__path__"):
            for info in pkgutil.iter_modules(pkg.__path__):
                try:
                    yield importlib.import_module(f"mmlspark_tpu.{sub}.{info.name}")
                except ImportError:
                    continue


def all_stage_classes(concrete_only: bool = True) -> List[Type]:
    """Every PipelineStage subclass defined in mmlspark_tpu."""
    from mmlspark_tpu.core import PipelineStage
    seen: Dict[str, Type] = {}
    for mod in _iter_modules():
        for name, obj in vars(mod).items():
            if not inspect.isclass(obj) or not issubclass(obj, PipelineStage):
                continue
            if obj.__module__.split(".")[0] != "mmlspark_tpu":
                continue
            if concrete_only and (name.startswith("_") or inspect.isabstract(obj)):
                continue
            seen[f"{obj.__module__}.{obj.__qualname__}"] = obj
    return [seen[k] for k in sorted(seen)]


def instantiate_default(cls: Type):
    """Try to construct a stage with no arguments (fuzzing entry point)."""
    try:
        return cls()
    except Exception:  # noqa: BLE001 — some stages need ctor args
        return None
