"""Pipeline contract: Estimator / Transformer / Model / Pipeline.

Reference: the SparkML pipeline contract that every mmlspark stage implements
(SURVEY.md §1 — L3 stages expose ``Estimator.fit``/``Transformer.transform``),
plus mmlspark's ``BasicLogging`` telemetry wrapper (``logging/
BasicLogging.scala:25-70``) which logs every ctor/fit/transform.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

from .dataframe import DataFrame
from .params import ComplexParam, Params
from .schema import Schema
from .logging import log_verb


class PipelineStage(Params):
    """Base of all stages.  Subclasses implement ``transform_schema`` for
    schema validation without data movement (Spark's transformSchema)."""

    def transform_schema(self, schema: Schema) -> Schema:
        return schema


class Transformer(PipelineStage):
    def transform(self, df: DataFrame) -> DataFrame:
        with log_verb(self, "transform"):
            self.transform_schema(df.schema)
            return self._transform(df)

    def _transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Model(Transformer):
    """A fitted Transformer, usually produced by an Estimator."""
    pass


class Estimator(PipelineStage):
    def fit(self, df: DataFrame) -> Model:
        with log_verb(self, "fit"):
            self.transform_schema(df.schema)
            return self._fit(df)

    def _fit(self, df: DataFrame) -> Model:
        raise NotImplementedError


class Evaluator(Params):
    def evaluate(self, df: DataFrame) -> float:
        raise NotImplementedError

    @property
    def is_larger_better(self) -> bool:
        return True


class Pipeline(Estimator):
    """Chain of stages; fit() fits estimators in order, transforming through."""

    stages_param = ComplexParam("stages", "ordered pipeline stages")

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, uid: Optional[str] = None):
        super().__init__(uid)
        if stages is not None:
            self.set("stages", list(stages))

    @property
    def stages(self) -> List[PipelineStage]:
        return self.get("stages") or []

    def set_stages(self, stages: Sequence[PipelineStage]) -> "Pipeline":
        self.set("stages", list(stages))
        return self

    def transform_schema(self, schema: Schema) -> Schema:
        for s in self.stages:
            schema = s.transform_schema(schema)
        return schema

    def _fit(self, df: DataFrame) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = df
        stages = self.stages
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"stage {stage} is neither Estimator nor Transformer")
        return PipelineModel(fitted)


class PipelineModel(Model):
    stages_param = ComplexParam("stages", "fitted pipeline stages")

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, uid: Optional[str] = None):
        super().__init__(uid)
        if stages is not None:
            self.set("stages", list(stages))

    @property
    def stages(self) -> List[Transformer]:
        return self.get("stages") or []

    def transform_schema(self, schema: Schema) -> Schema:
        for s in self.stages:
            schema = s.transform_schema(schema)
        return schema

    def _transform(self, df: DataFrame) -> DataFrame:
        for s in self.stages:
            df = s.transform(df)
        return df


class UnaryTransformer(Transformer):
    """Convenience base: one input column -> one output column."""

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")
        return df.with_column(out_col, lambda p: self._apply(p[in_col]))

    def _apply(self, col):
        raise NotImplementedError
