"""Schema & binding codecs — the SparkBindings equivalent.

Reference: ``core/src/main/scala/com/microsoft/ml/spark/core/schema/SparkBindings.scala:14-46``
converts case classes <-> Spark Rows so typed payloads (HTTP requests, service
responses) ride inside DataFrames.  Here the analogue is dataclass <-> columnar
codec: a ``Binding`` turns a list of dataclass instances into object columns
and back, and ``Schema`` records per-column dtypes for validation in
``transformSchema``-style checks.
"""
from __future__ import annotations

import dataclasses
import numpy as np
from typing import Any, Dict, List, Mapping, Optional, Sequence, Type, TypeVar

T = TypeVar("T")


class ColumnType:
    """Logical column types (numpy-backed)."""
    FLOAT = "float"
    DOUBLE = "double"
    INT = "int"
    LONG = "long"
    BOOL = "bool"
    STRING = "string"
    BINARY = "binary"
    VECTOR = "vector"    # fixed or ragged numeric vectors (object or 2-d)
    STRUCT = "struct"    # dicts / dataclasses
    ARRAY = "array"      # nested lists
    OBJECT = "object"

    _KIND_MAP = {"f": DOUBLE, "i": LONG, "u": LONG, "b": BOOL}

    @staticmethod
    def of(arr: np.ndarray) -> str:
        if arr.dtype == object:
            for v in arr:
                if v is None:
                    continue
                if isinstance(v, str):
                    return ColumnType.STRING
                if isinstance(v, (bytes, bytearray)):
                    return ColumnType.BINARY
                if isinstance(v, (list, tuple, np.ndarray)):
                    return ColumnType.VECTOR
                if isinstance(v, Mapping) or dataclasses.is_dataclass(v):
                    return ColumnType.STRUCT
                return ColumnType.OBJECT
            return ColumnType.OBJECT
        if arr.ndim >= 2:
            return ColumnType.VECTOR
        return ColumnType._KIND_MAP.get(arr.dtype.kind, ColumnType.OBJECT)


class Schema(dict):
    """column name -> logical type.  Dict subclass so it stays JSON-friendly."""

    def require(self, col: str, *types: str) -> None:
        if col not in self:
            raise ValueError(f"required column '{col}' missing; schema has {list(self)}")
        if types and self[col] not in types:
            raise ValueError(f"column '{col}' has type {self[col]}, expected one of {types}")

    def add(self, col: str, typ: str) -> "Schema":
        s = Schema(self)
        s[col] = typ
        return s


def infer_schema(partitions: Sequence[Mapping[str, np.ndarray]]) -> Schema:
    s = Schema()
    for p in partitions:
        for k, v in p.items():
            if k not in s and len(v):
                s[k] = ColumnType.of(v)
            elif k not in s:
                s[k] = ColumnType.OBJECT
        break
    # refine OBJECT columns using later partitions that have data
    for p in partitions:
        for k, v in p.items():
            if s.get(k) == ColumnType.OBJECT and len(v):
                s[k] = ColumnType.of(v)
    return s


def unify_schemas(a: Schema, b: Schema) -> Schema:
    out = Schema(a)
    for k, v in b.items():
        if k in out and out[k] != v and ColumnType.OBJECT not in (out[k], v):
            raise ValueError(f"schema conflict on '{k}': {out[k]} vs {v}")
        out.setdefault(k, v)
    return out


class Binding:
    """dataclass <-> object-column codec (SparkBindings analogue)."""

    def __init__(self, cls: Type[T]):
        if not dataclasses.is_dataclass(cls):
            raise TypeError(f"{cls} is not a dataclass")
        self.cls = cls
        self.fields = [f.name for f in dataclasses.fields(cls)]

    def to_column(self, items: Sequence[Optional[T]]) -> np.ndarray:
        out = np.empty(len(items), dtype=object)
        for i, it in enumerate(items):
            out[i] = None if it is None else dataclasses.asdict(it)
        return out

    def from_column(self, col: np.ndarray) -> List[Optional[T]]:
        return [None if v is None else self._decode(self.cls, v) for v in col]

    def _decode(self, cls, value):
        if dataclasses.is_dataclass(cls) and isinstance(value, Mapping):
            kwargs = {}
            for f in dataclasses.fields(cls):
                v = value.get(f.name)
                sub = f.type
                if isinstance(sub, str):
                    sub = None  # forward-ref strings: pass through raw
                if sub is not None and dataclasses.is_dataclass(sub) and isinstance(v, Mapping):
                    v = self._decode(sub, v)
                kwargs[f.name] = v
            return cls(**kwargs)
        return value


def vector_column(vectors: Sequence[Any]) -> np.ndarray:
    """Pack possibly-ragged numeric vectors into a column.  Rectangular input
    becomes a dense 2-d float array (device-transfer friendly); ragged input
    falls back to object dtype."""
    try:
        arr = np.asarray([np.asarray(v, dtype=np.float64) for v in vectors])
        if arr.dtype != object and arr.ndim == 2:
            return arr
    except (ValueError, TypeError):
        pass
    out = np.empty(len(vectors), dtype=object)
    for i, v in enumerate(vectors):
        out[i] = np.asarray(v, dtype=np.float64)
    return out


def stack_vector_column(col: np.ndarray) -> np.ndarray:
    """Object column of equal-length vectors -> dense (n, d) float array."""
    if col.dtype != object:
        return np.asarray(col, dtype=np.float64)
    if len(col) == 0:
        return np.zeros((0, 0))
    return np.stack([np.asarray(v, dtype=np.float64) for v in col])


def find_unused_column_name(base: str, schema: Mapping[str, Any]) -> str:
    """Reference ``DatasetExtensions.findUnusedColumnName`` (core/schema/)."""
    name = base
    i = 0
    while name in schema:
        i += 1
        name = f"{base}_{i}"
    return name
