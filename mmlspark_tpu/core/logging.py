"""BasicLogging equivalent — per-stage structured telemetry.

Reference: ``core/src/main/scala/com/microsoft/ml/spark/logging/
BasicLogging.scala:25-70``: every ctor/fit/transform/predict emits JSON
``{uid, className, method, buildVersion}``; errors are logged with the verb.
Here the transport is the stdlib ``logging`` module under the
``mmlspark_tpu.telemetry`` logger; a ring buffer keeps recent events for tests.
"""
from __future__ import annotations

import contextlib
import json
import logging
import time
from collections import deque
from typing import Any, Dict

logger = logging.getLogger("mmlspark_tpu.telemetry")

_RECENT: deque = deque(maxlen=512)


def build_version() -> str:
    from mmlspark_tpu import __version__
    return __version__


def log_event(payload: Dict[str, Any]) -> None:
    _RECENT.append(payload)
    # serialize only when a debug handler will actually see it: with span
    # events riding every request, an unconditional json.dumps would tax
    # the serving hot path for output nobody receives
    if logger.isEnabledFor(logging.DEBUG):
        logger.debug(json.dumps(payload, default=str))


def recent_events():
    return list(_RECENT)


@contextlib.contextmanager
def log_verb(stage, method: str):
    """Wrap a verb (fit/transform/...) with telemetry incl. errors + wall time.

    Every verb is also a span on the observability layer: nested stage calls
    build a trace (a Pipeline.fit's transforms hang off it), and a verb
    running inside a served request inherits that request's wire trace id —
    so the event ring and ``/metrics`` agree on where a request's time went.
    The span exports before the verb event is appended, keeping the verb
    payload the LAST ring entry for its stage (tests rely on that order).
    """
    payload = {
        "uid": getattr(stage, "uid", "?"),
        "className": type(stage).__name__,
        "method": method,
        "buildVersion": build_version(),
    }
    # lazy: observability imports this module for ring export
    from ..observability.tracing import trace_span
    t0 = time.perf_counter()
    try:
        with trace_span(f"{type(stage).__name__}.{method}",
                        attributes={"uid": payload["uid"]}) as span:
            payload["traceId"] = span.trace_id
            yield
        payload["seconds"] = round(time.perf_counter() - t0, 6)
        log_event(payload)
    except Exception as e:
        payload["seconds"] = round(time.perf_counter() - t0, 6)
        payload["error"] = f"{type(e).__name__}: {e}"
        log_event(payload)
        raise
