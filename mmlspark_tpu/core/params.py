"""Params — single source of truth for stage configuration.

Reference: SparkML ``Params`` extended by mmlspark with ``ComplexParam``
(``core/serialize/ComplexParam.scala:13`` — params holding non-JSON payloads
with their own save/load) and ``ServiceParam`` (``cognitive/.../
CognitiveServiceBase.scala:29-127`` — a value *or* a column reference).

Params metadata drives three subsystems exactly as in the reference:
serialization (§core.serialize), codegen (stub/doc generation), and the
fuzzing test harness (reflection sweep over declared params).
"""
from __future__ import annotations

import copy
import uuid as _uuid
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")


def _next_uid(prefix: str) -> str:
    return f"{prefix}_{_uuid.uuid4().hex[:12]}"


class Param(Generic[T]):
    """Declarative parameter: name, doc, type tag, default, validator."""

    def __init__(self, name: str, doc: str, dtype: str = "object",
                 default: Any = None, validator: Optional[Callable[[Any], bool]] = None,
                 is_complex: bool = False):
        self.name = name
        self.doc = doc
        self.dtype = dtype
        self.default = default
        self.validator = validator
        self.is_complex = is_complex

    def validate(self, value: Any) -> None:
        if value is not None and self.validator is not None and not self.validator(value):
            raise ValueError(f"invalid value for param '{self.name}': {value!r}")

    def __repr__(self):
        return f"Param({self.name}: {self.dtype})"


class ComplexParam(Param):
    """Param holding a non-JSON payload (model bytes, DataFrames, functions,
    ball trees).  Serialized via the payload's own save/load hooks — see
    ``core.serialize``.  Reference: ``ComplexParam.scala:13`` and the concrete
    types under ``org/apache/spark/ml/param/``."""

    def __init__(self, name: str, doc: str, dtype: str = "complex",
                 default: Any = None, validator=None):
        super().__init__(name, doc, dtype, default, validator, is_complex=True)


class ServiceParam(Param):
    """Value-or-column duality for request fields (cognitive services).

    ``set(v)`` binds a literal; ``set_col(c)`` binds a column name, resolved
    per-row at transform time.  Reference: ``HasServiceParams``
    (``CognitiveServiceBase.scala:29-127``)."""

    def __init__(self, name: str, doc: str, dtype: str = "service",
                 default: Any = None, validator=None, required: bool = False):
        super().__init__(name, doc, dtype, default, validator)
        self.required = required


class ServiceValue:
    """Bound value of a ServiceParam: either a literal or a column reference."""
    __slots__ = ("value", "col")

    def __init__(self, value: Any = None, col: Optional[str] = None):
        if (value is None) == (col is None):
            raise ValueError("exactly one of value/col must be set")
        self.value = value
        self.col = col

    def resolve(self, row) -> Any:
        return row[self.col] if self.col is not None else self.value

    def to_json(self):
        return {"col": self.col} if self.col is not None else {"value": self.value}

    @staticmethod
    def from_json(d):
        return ServiceValue(value=d.get("value"), col=d.get("col"))

    def __repr__(self):
        return f"ServiceValue(col={self.col!r})" if self.col else f"ServiceValue({self.value!r})"


class _ParamsMeta(type):
    """Collects Param class attributes into `_params`, inheriting from bases."""

    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        params: Dict[str, Param] = {}
        for base in reversed(cls.__mro__):
            for k, v in vars(base).items():
                if isinstance(v, Param):
                    params[v.name] = v
        cls._params = params
        return cls


class Params(metaclass=_ParamsMeta):
    """Base for anything configurable via Params (all pipeline stages).

    Values live in ``_paramMap``; defaults in each Param.  ``set``/``get``
    accept either the Param object or its name.  Fluent ``set_<name>`` and
    ``get_<name>`` accessors are synthesised on attribute access, mirroring
    the reference's setter/getter convention so generated bindings look alike.
    """

    _params: Dict[str, Param] = {}

    def __init__(self, uid: Optional[str] = None):
        self.uid = uid or _next_uid(type(self).__name__)
        self._paramMap: Dict[str, Any] = {}

    # ------------------------------------------------------------- access
    @classmethod
    def params(cls) -> List[Param]:
        return list(cls._params.values())

    @classmethod
    def get_param(cls, name: str) -> Param:
        try:
            return cls._params[name]
        except KeyError:
            raise KeyError(f"{cls.__name__} has no param '{name}'; has {list(cls._params)}")

    def _resolve(self, param) -> Param:
        return param if isinstance(param, Param) else self.get_param(param)

    def set(self, param, value) -> "Params":
        p = self._resolve(param)
        if isinstance(p, ServiceParam) and not isinstance(value, ServiceValue):
            value = ServiceValue(value=value)
        if isinstance(value, ServiceValue):
            if value.col is None:  # column bindings bypass literal validation
                p.validate(value.value)
        else:
            p.validate(value)
        self._paramMap[p.name] = value
        return self

    def set_col(self, param, col: str) -> "Params":
        p = self._resolve(param)
        if not isinstance(p, ServiceParam):
            raise TypeError(f"param '{p.name}' is not a ServiceParam")
        self._paramMap[p.name] = ServiceValue(col=col)
        return self

    def get(self, param) -> Any:
        p = self._resolve(param)
        if p.name in self._paramMap:
            return self._paramMap[p.name]
        return p.default

    def get_or_fail(self, param) -> Any:
        v = self.get(param)
        if v is None:
            raise ValueError(f"param '{self._resolve(param).name}' is required but unset on {self.uid}")
        return v

    def is_set(self, param) -> bool:
        return self._resolve(param).name in self._paramMap

    def is_defined(self, param) -> bool:
        p = self._resolve(param)
        return p.name in self._paramMap or p.default is not None

    def set_params(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    # ------------------------------------------------------------- fluent api
    def __getattr__(self, item: str):
        # Only called when normal lookup fails; synthesise set_x/get_x.
        if item.startswith("set_"):
            name = item[4:]
            if name in type(self)._params:
                return lambda v: self.set(name, v)
        elif item.startswith("get_"):
            name = item[4:]
            if name in type(self)._params:
                return self.get(name)
        raise AttributeError(f"{type(self).__name__} has no attribute {item!r}")

    # ------------------------------------------------------------- copy/explain
    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        other = copy.copy(self)
        other._paramMap = dict(self._paramMap)
        if extra:
            for k, v in extra.items():
                other.set(k, v)
        return other

    def explain_params(self) -> str:
        lines = []
        for p in self.params():
            cur = self._paramMap.get(p.name, "undefined")
            lines.append(f"{p.name}: {p.doc} (default: {p.default!r}, current: {cur!r})")
        return "\n".join(lines)

    def extract_param_map(self) -> Dict[str, Any]:
        out = {p.name: p.default for p in self.params() if p.default is not None}
        out.update(self._paramMap)
        return out

    def has_same_params(self, other: "Params") -> bool:
        return type(self) is type(other) and _param_maps_equal(self.extract_param_map(),
                                                              other.extract_param_map())


def _param_maps_equal(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    import numpy as np
    if a.keys() != b.keys():
        return False
    for k in a:
        va, vb = a[k], b[k]
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            if not np.array_equal(np.asarray(va), np.asarray(vb)):
                return False
        elif isinstance(va, ServiceValue) and isinstance(vb, ServiceValue):
            if va.col != vb.col or va.value != vb.value:
                return False
        elif va != vb:
            return False
    return True


# --------------------------------------------------------------------------
# Shared param mixins (reference: core/contracts/Params.scala)
# --------------------------------------------------------------------------

class HasInputCol(Params):
    input_col = Param("input_col", "name of the input column", "string", default="input")


class HasInputCols(Params):
    input_cols = Param("input_cols", "names of the input columns", "list")


class HasOutputCol(Params):
    output_col = Param("output_col", "name of the output column", "string", default="output")


class HasFeaturesCol(Params):
    features_col = Param("features_col", "name of the features column", "string", default="features")


class HasLabelCol(Params):
    label_col = Param("label_col", "name of the label column", "string", default="label")


class HasWeightCol(Params):
    weight_col = Param("weight_col", "name of the sample-weight column", "string")


class HasPredictionCol(Params):
    prediction_col = Param("prediction_col", "name of the prediction column", "string", default="prediction")


class HasProbabilityCol(Params):
    probability_col = Param("probability_col", "probability output column", "string", default="probability")


class HasRawPredictionCol(Params):
    raw_prediction_col = Param("raw_prediction_col", "raw margin output column", "string", default="raw_prediction")
