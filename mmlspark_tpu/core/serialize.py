"""Stage persistence — save/load for all pipeline stages and models.

Reference: SparkML ``ComplexParamsWritable`` + mmlspark's ``ComplexParam``
save/load hooks (``core/serialize/ComplexParam.scala:13-24``) which let params
carry non-JSON payloads (native model strings, DataFrames, UDFs, ball trees).

Layout on disk::

    <path>/metadata.json          {"class": "mod.Cls", "uid": ..., "params": {...}}
    <path>/complex/<param>/...    payload-specific (see _save_complex)

Every complex payload kind gets a tagged directory so load() can dispatch
without pickle-by-default; arbitrary objects fall back to pickle (stdlib).

.. warning:: **Security.** ``load()`` imports the class named in
   ``metadata.json`` and, for closure-typed params (UDFs, Lambda stages),
   falls back to ``pickle`` — both execute code from the artifact.  Only
   load model/pipeline directories you trust, exactly as the reference's
   serializers (SparkML ``DefaultParamsReader`` class-forname + Java
   deserialization) and ``torch.load`` require.  For artifacts from
   untrusted sources, pass ``safe=True`` (or set env
   ``MMLSPARK_TPU_SAFE_LOAD=1``): class imports are then restricted to
   registered trusted prefixes (``mmlspark_tpu.`` plus
   ``register_loadable_prefix(...)``) and pickle payloads refuse to load.
"""
from __future__ import annotations

import importlib
import json
import os
import shutil

from ..utils import pickling as pickle
import numpy as np
from typing import Any, Dict, Optional

from .params import Params, ServiceValue


class Saveable:
    """Protocol for payloads with their own persistence (boosters, trees)."""

    def save(self, path: str) -> None:
        raise NotImplementedError

    @classmethod
    def load(cls, path: str):
        raise NotImplementedError


def _qualname(obj) -> str:
    cls = obj if isinstance(obj, type) else type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


_TRUSTED_PREFIXES = {"mmlspark_tpu."}


def register_loadable_prefix(prefix: str) -> None:
    """Allow classes under ``prefix`` (e.g. ``myproject.stages.``) to be
    instantiated by ``load(..., safe=True)``."""
    _TRUSTED_PREFIXES.add(prefix)


def _default_safe() -> bool:
    return os.environ.get("MMLSPARK_TPU_SAFE_LOAD", "0") not in ("0", "", "false")


def _import_qual(qual: str, safe: bool = False):
    if safe and not any(qual.startswith(p) for p in _TRUSTED_PREFIXES):
        raise PermissionError(
            f"safe load: class {qual!r} is outside the trusted prefixes "
            f"{sorted(_TRUSTED_PREFIXES)}; call register_loadable_prefix() "
            f"for code you trust, or load with safe=False for trusted paths")
    mod, _, name = qual.rpartition(".")
    m = importlib.import_module(mod)
    obj = m
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def _is_jsonable(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def _save_complex(value: Any, path: str) -> Dict[str, Any]:
    os.makedirs(path, exist_ok=True)
    from .dataframe import DataFrame
    from .pipeline import PipelineStage
    if isinstance(value, Saveable) or (hasattr(value, "save") and hasattr(type(value), "load")
                                       and not isinstance(value, (DataFrame, PipelineStage))):
        value.save(os.path.join(path, "payload"))
        return {"kind": "saveable", "class": _qualname(value)}
    if isinstance(value, PipelineStage):
        save_stage(value, os.path.join(path, "stage"))
        return {"kind": "stage"}
    if isinstance(value, list) and value and all(isinstance(s, PipelineStage) for s in value):
        for i, s in enumerate(value):
            save_stage(s, os.path.join(path, f"stage_{i}"))
        return {"kind": "stage_list", "n": len(value)}
    if isinstance(value, DataFrame):
        save_dataframe(value, os.path.join(path, "frame"))
        return {"kind": "dataframe"}
    if isinstance(value, np.ndarray):
        np.save(os.path.join(path, "array.npy"), value, allow_pickle=value.dtype == object)
        return {"kind": "ndarray"}
    if isinstance(value, (bytes, bytearray)):
        with open(os.path.join(path, "payload.bin"), "wb") as f:
            f.write(value)
        return {"kind": "bytes"}
    if isinstance(value, dict) and all(isinstance(v, np.ndarray) for v in value.values()) and value:
        np.savez(os.path.join(path, "arrays.npz"), **value)
        return {"kind": "ndarray_dict"}
    with open(os.path.join(path, "payload.pkl"), "wb") as f:
        pickle.dump(value, f)
    return {"kind": "pickle"}


def _load_complex(tag: Dict[str, Any], path: str, safe: bool = False) -> Any:
    kind = tag["kind"]
    if kind == "saveable":
        cls = _import_qual(tag["class"], safe=safe)
        return cls.load(os.path.join(path, "payload"))
    if kind == "stage":
        return load_stage(os.path.join(path, "stage"), safe=safe)
    if kind == "stage_list":
        return [load_stage(os.path.join(path, f"stage_{i}"), safe=safe)
                for i in range(tag["n"])]
    if kind == "dataframe":
        return load_dataframe(os.path.join(path, "frame"), safe=safe)
    if kind == "ndarray":
        return np.load(os.path.join(path, "array.npy"), allow_pickle=not safe)
    if kind == "bytes":
        with open(os.path.join(path, "payload.bin"), "rb") as f:
            return f.read()
    if kind == "ndarray_dict":
        with np.load(os.path.join(path, "arrays.npz"), allow_pickle=not safe) as z:
            return {k: z[k] for k in z.files}
    if kind == "pickle":
        if safe:
            raise PermissionError(
                "safe load: refusing pickle payload at "
                f"{os.path.join(path, 'payload.pkl')!r} (pickle executes "
                "arbitrary code); load with safe=False only on trusted paths")
        with open(os.path.join(path, "payload.pkl"), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"unknown complex payload kind {kind!r}")


def save_stage(stage: Params, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(path)
    meta: Dict[str, Any] = {"class": _qualname(stage), "uid": stage.uid,
                            "params": {}, "complex": {}, "service": {}}
    for name, value in stage._paramMap.items():
        if isinstance(value, ServiceValue):
            meta["service"][name] = value.to_json()
        elif _is_jsonable(value):
            meta["params"][name] = value
        else:
            tag = _save_complex(value, os.path.join(path, "complex", name))
            meta["complex"][name] = tag
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1, default=str)


def load_stage(path: str, safe: bool = None) -> Params:
    """Load a stage directory.  ``safe=True`` (default from env
    ``MMLSPARK_TPU_SAFE_LOAD``) restricts class imports to trusted prefixes
    and refuses pickle payloads — see the module security warning."""
    if safe is None:
        safe = _default_safe()
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = _import_qual(meta["class"], safe=safe)
    stage = cls.__new__(cls)
    Params.__init__(stage, uid=meta["uid"])
    for name, value in meta["params"].items():
        stage._paramMap[name] = value
    for name, d in meta.get("service", {}).items():
        stage._paramMap[name] = ServiceValue.from_json(d)
    for name, tag in meta.get("complex", {}).items():
        stage._paramMap[name] = _load_complex(tag, os.path.join(path, "complex", name),
                                              safe=safe)
    if hasattr(stage, "_post_load"):
        stage._post_load()
    return stage


def save_dataframe(df, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    from .dataframe import DataFrame
    assert isinstance(df, DataFrame)
    manifest = {"num_partitions": df.num_partitions, "columns": df.columns,
                "schema": dict(df.schema)}
    for i, p in enumerate(df.partitions):
        np.savez(os.path.join(path, f"part_{i}.npz"),
                 **{k: v for k, v in p.items()})
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f)


def load_dataframe(path: str, safe: Optional[bool] = None):
    """``safe=True`` loads arrays with ``allow_pickle=False`` — object-dtype
    columns (sparse dicts, nested arrays) then raise instead of unpickling.
    Default resolves MMLSPARK_TPU_SAFE_LOAD like ``load_stage``/``load`` do,
    so the documented env opt-in covers direct calls too."""
    from .dataframe import DataFrame
    from .schema import Schema
    if safe is None:
        safe = _default_safe()
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    parts = []
    for i in range(manifest["num_partitions"]):
        with np.load(os.path.join(path, f"part_{i}.npz"),
                     allow_pickle=not safe) as z:
            parts.append({k: z[k] for k in manifest["columns"]})
    return DataFrame(parts, schema=Schema(manifest["schema"]))


# Convenience mixin-style functions attached to Params via monkey-free helpers
def save(stage: Params, path: str, overwrite: bool = True) -> None:
    save_stage(stage, path, overwrite)


def load(path: str, safe: bool = None) -> Params:
    return load_stage(path, safe=safe)
