"""Partitioned columnar DataFrame — the data substrate of mmlspark_tpu.

The reference operates on Spark DataFrames (row-oriented JVM iterators which
the hot paths painstakingly re-columnarise into native chunked arrays, see
reference ``lightgbm/.../dataset/DatasetAggregator.scala:69-459``).  On TPU the
natural layout is columnar from the start: a partition is a dict of numpy
arrays, ready for zero-ish-copy transfer to device HBM.  This class keeps the
Spark surface the rest of the framework expects (select / withColumn /
mapPartitions / repartition / coalesce / union / filter / groupBy-agg / join)
while staying eager and in-process: multi-host execution shards *partitions*
over executors, each pinned to one TPU chip (SURVEY.md §7 design stance).
"""
from __future__ import annotations

import numpy as np
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Sequence, Union

from .schema import Schema, infer_schema, unify_schemas

Partition = Dict[str, np.ndarray]


def _as_column(values: Any, n: Optional[int] = None) -> np.ndarray:
    """Coerce python values to a numpy column; object dtype for ragged/str."""
    if isinstance(values, np.ndarray):
        return values
    if values is None and n is not None:
        arr = np.empty(n, dtype=object)
        arr[:] = None
        return arr
    if np.isscalar(values) and n is not None:
        arr = np.empty(n, dtype=object) if isinstance(values, (str, bytes)) else None
        if arr is None:
            return np.full(n, values)
        arr[:] = values
        return arr
    values = list(values)
    if values and isinstance(values[0], (list, tuple, np.ndarray, dict)):
        # Ragged / nested columns are stored as object arrays unless rectangular numeric.
        try:
            arr = np.asarray(values)
            if arr.dtype.kind in "fiub" and arr.ndim >= 2:
                return arr
        except (ValueError, TypeError):
            pass
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    arr = np.asarray(values)
    if arr.dtype.kind in ("U", "S"):
        out = np.empty(len(values), dtype=object)
        out[:] = values
        return out
    return arr


def _part_len(part: Partition) -> int:
    for v in part.values():
        return len(v)
    return 0


def _slice_part(part: Partition, sl) -> Partition:
    return {k: v[sl] for k, v in part.items()}


def _concat_parts(parts: Sequence[Partition], columns: Sequence[str]) -> Partition:
    if not parts:
        return {c: np.empty(0) for c in columns}
    out = {}
    for c in columns:
        cols = [p[c] for p in parts]
        if any(col.dtype == object for col in cols):
            merged = np.empty(sum(len(c_) for c_ in cols), dtype=object)
            i = 0
            for col in cols:
                merged[i:i + len(col)] = col
                i += len(col)
            out[c] = merged
        else:
            out[c] = np.concatenate(cols) if len(cols) > 1 else cols[0]
    return out


class Row(dict):
    """Dict-backed row with attribute access, for row-wise UDF convenience."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e


class DataFrame:
    """Eager, partitioned, columnar DataFrame.

    Mirrors the subset of the Spark DataFrame API the reference framework
    relies on.  Columns are numpy arrays (object dtype for strings / nested
    values); partitions model executor-local shards.
    """

    def __init__(self, partitions: Sequence[Partition], schema: Optional[Schema] = None):
        parts = [dict(p) for p in partitions]
        if not parts:
            parts = [{}]
        cols = list(parts[0].keys())
        for p in parts:
            if list(p.keys()) != cols:
                raise ValueError(f"partition column mismatch: {list(p.keys())} vs {cols}")
            n = _part_len(p)
            for k, v in p.items():
                if len(v) != n:
                    raise ValueError(f"column {k} length {len(v)} != partition length {n}")
        self._parts: List[Partition] = parts
        self._schema = schema or infer_schema(parts)

    # ---------------------------------------------------------------- factory
    @staticmethod
    def from_dict(data: Mapping[str, Any], num_partitions: int = 1) -> "DataFrame":
        cols = {k: _as_column(v) for k, v in data.items()}
        n = len(next(iter(cols.values()))) if cols else 0
        for k, v in cols.items():
            if len(v) != n:
                raise ValueError(f"column {k} has length {len(v)}, expected {n}")
        df = DataFrame([cols])
        return df.repartition(num_partitions) if num_partitions > 1 else df

    @staticmethod
    def from_rows(rows: Iterable[Mapping[str, Any]], num_partitions: int = 1) -> "DataFrame":
        rows = list(rows)
        if not rows:
            return DataFrame([{}])
        cols = {k: _as_column([r.get(k) for r in rows]) for k in rows[0].keys()}
        return DataFrame.from_dict(cols, num_partitions)

    @staticmethod
    def from_pandas(pdf, num_partitions: int = 1) -> "DataFrame":
        return DataFrame.from_dict({c: pdf[c].to_numpy() for c in pdf.columns}, num_partitions)

    # ---------------------------------------------------------------- schema
    @property
    def columns(self) -> List[str]:
        return list(self._parts[0].keys())

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def partition(self, i: int) -> Partition:
        return self._parts[i]

    @property
    def partitions(self) -> List[Partition]:
        return self._parts

    def count(self) -> int:
        return sum(_part_len(p) for p in self._parts)

    def __len__(self) -> int:
        return self.count()

    def is_empty(self) -> bool:
        return self.count() == 0

    # ---------------------------------------------------------------- columnar ops
    def select(self, *cols: str) -> "DataFrame":
        names = [c for group in cols for c in (group if isinstance(group, (list, tuple)) else [group])]
        missing = [c for c in names if c not in self.columns]
        if missing:
            raise KeyError(f"columns not found: {missing}; have {self.columns}")
        return DataFrame([{c: p[c] for c in names} for p in self._parts],
                         schema=Schema({c: self._schema[c] for c in names if c in self._schema}))

    def drop(self, *cols: str) -> "DataFrame":
        keep = [c for c in self.columns if c not in cols]
        return self.select(*keep)

    def with_column(self, name: str, value: Union[np.ndarray, Callable[[Partition], np.ndarray], Any]) -> "DataFrame":
        """Add/replace a column.  `value` may be a full-length array, a scalar,
        or a function mapping a partition dict to a new column array."""
        new_parts = []
        if callable(value) and not isinstance(value, np.ndarray):
            for p in self._parts:
                col = _as_column(value(p), _part_len(p))
                q = dict(p)
                q[name] = col
                new_parts.append(q)
        elif isinstance(value, np.ndarray) or isinstance(value, (list, tuple)):
            arr = _as_column(value)
            if len(arr) != self.count():
                raise ValueError(f"column length {len(arr)} != frame length {self.count()}")
            i = 0
            for p in self._parts:
                n = _part_len(p)
                q = dict(p)
                q[name] = arr[i:i + n]
                new_parts.append(q)
                i += n
        else:  # scalar
            for p in self._parts:
                q = dict(p)
                q[name] = _as_column(value, _part_len(p))
                new_parts.append(q)
        new_schema = Schema(self._schema)
        new_schema[name] = infer_schema([q for q in new_parts if len(q[name])] or new_parts[:1]).get(name, "object")
        return DataFrame(new_parts, schema=new_schema)

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        return DataFrame([{(new if k == old else k): v for k, v in p.items()} for p in self._parts])

    def with_columns(self, mapping: Mapping[str, Any]) -> "DataFrame":
        df = self
        for k, v in mapping.items():
            df = df.with_column(k, v)
        return df

    # ---------------------------------------------------------------- row-ish ops
    def filter(self, predicate: Union[Callable[[Partition], np.ndarray], np.ndarray]) -> "DataFrame":
        """Keep rows where the boolean mask (per-partition fn or full array) is True."""
        new_parts = []
        if callable(predicate):
            for p in self._parts:
                mask = np.asarray(predicate(p), dtype=bool)
                new_parts.append(_slice_part(p, mask))
        else:
            mask = np.asarray(predicate, dtype=bool)
            if len(mask) != self.count():
                raise ValueError(f"mask length {len(mask)} != frame length {self.count()}")
            i = 0
            for p in self._parts:
                n = _part_len(p)
                new_parts.append(_slice_part(p, mask[i:i + n]))
                i += n
        return DataFrame(new_parts, schema=self._schema)

    def map_partitions(self, fn: Callable[[Partition], Partition]) -> "DataFrame":
        """Apply fn to every partition; fn returns a new partition dict.
        The TPU-side analogue of Spark's ``mapPartitions`` hot path."""
        outs = [fn(p) for p in self._parts]
        outs = [{k: _as_column(v) for k, v in o.items()} for o in outs]
        return DataFrame(outs)

    def map_rows(self, fn: Callable[[Row], Mapping[str, Any]]) -> "DataFrame":
        def part_fn(p: Partition) -> Partition:
            n = _part_len(p)
            rows_out = [fn(Row({k: p[k][i] for k in p})) for i in range(n)]
            if not rows_out:
                return {k: np.empty(0, dtype=object) for k in p}
            keys = rows_out[0].keys()
            return {k: _as_column([r[k] for r in rows_out]) for k in keys}
        return self.map_partitions(part_fn)

    def iter_rows(self) -> Iterable[Row]:
        for p in self._parts:
            for i in range(_part_len(p)):
                yield Row({k: p[k][i] for k in p})

    # ---------------------------------------------------------------- partitioning
    def repartition(self, n: int) -> "DataFrame":
        """Even row redistribution into n partitions (Spark: full shuffle)."""
        if n <= 0:
            raise ValueError("num partitions must be positive")
        whole = self.collect()
        total = len(next(iter(whole.values()))) if whole else 0
        bounds = np.linspace(0, total, n + 1).astype(int)
        parts = [_slice_part(whole, slice(bounds[i], bounds[i + 1])) for i in range(n)]
        return DataFrame(parts, schema=self._schema) if self.columns else DataFrame([{}])

    def coalesce(self, n: int) -> "DataFrame":
        """Merge adjacent partitions down to n without a full shuffle."""
        if n >= self.num_partitions:
            return self
        groups = np.array_split(np.arange(self.num_partitions), n)
        cols = self.columns
        parts = [_concat_parts([self._parts[i] for i in g], cols) for g in groups if len(g)]
        return DataFrame(parts, schema=self._schema)

    def collect(self) -> Partition:
        """Concatenate all partitions into one columnar dict (driver-side)."""
        return _concat_parts(self._parts, self.columns)

    def to_pandas(self):
        import pandas as pd
        data = self.collect()
        return pd.DataFrame({k: list(v) if v.dtype == object else v for k, v in data.items()})

    def cache(self) -> "DataFrame":
        return self  # eager: already materialised

    def limit(self, n: int) -> "DataFrame":
        out, remaining = [], n
        for p in self._parts:
            if remaining <= 0:
                break
            take = min(remaining, _part_len(p))
            out.append(_slice_part(p, slice(0, take)))
            remaining -= take
        return DataFrame(out if out else [{c: p[c][:0] for c in self.columns} for p in self._parts[:1]])

    def head(self, n: int = 5) -> List[Row]:
        return list(self.limit(n).iter_rows())

    # ---------------------------------------------------------------- set ops
    def union(self, other: "DataFrame") -> "DataFrame":
        if set(self.columns) != set(other.columns):
            raise ValueError(f"union column mismatch: {self.columns} vs {other.columns}")
        other_parts = [{c: p[c] for c in self.columns} for p in other._parts]
        return DataFrame(self._parts + other_parts)

    def distinct(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        cols = list(subset) if subset else self.columns
        whole = self.collect()
        seen, keep = set(), []
        n = len(next(iter(whole.values()))) if whole else 0
        for i in range(n):
            key = tuple(_hashable(whole[c][i]) for c in cols)
            if key not in seen:
                seen.add(key)
                keep.append(i)
        return DataFrame([_slice_part(whole, np.asarray(keep, dtype=int))])

    def sort(self, *cols: str, ascending: bool = True) -> "DataFrame":
        whole = self.collect()
        keys = [whole[c] for c in reversed(cols)]
        order = np.lexsort([k.astype("U") if k.dtype == object else k for k in keys])
        if not ascending:
            order = order[::-1]
        return DataFrame([_slice_part(whole, order)])

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        return self.filter(lambda p: rng.random(_part_len(p)) < fraction)

    def random_split(self, weights: Sequence[float], seed: int = 0) -> List["DataFrame"]:
        w = np.asarray(weights, dtype=float)
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        whole = self.collect()
        n = len(next(iter(whole.values()))) if whole else 0
        draws = rng.random(n)
        edges = np.concatenate([[0.0], np.cumsum(w)])
        outs = []
        for i in range(len(w)):
            mask = (draws >= edges[i]) & (draws < edges[i + 1])
            outs.append(DataFrame([_slice_part(whole, mask)]))
        return outs

    # ---------------------------------------------------------------- relational
    def group_by(self, *cols: str) -> "GroupedFrame":
        return GroupedFrame(self, list(cols))

    def join(self, other: "DataFrame", on: Union[str, Sequence[str]], how: str = "inner") -> "DataFrame":
        on = [on] if isinstance(on, str) else list(on)
        left, right = self.collect(), other.collect()
        n_l = len(next(iter(left.values()))) if left else 0
        n_r = len(next(iter(right.values()))) if right else 0
        index: Dict[tuple, List[int]] = {}
        for j in range(n_r):
            index.setdefault(tuple(_hashable(right[c][j]) for c in on), []).append(j)
        li, ri = [], []
        matched_r = np.zeros(n_r, dtype=bool)
        for i in range(n_l):
            key = tuple(_hashable(left[c][i]) for c in on)
            js = index.get(key)
            if js:
                for j in js:
                    li.append(i)
                    ri.append(j)
                    matched_r[j] = True
            elif how in ("left", "outer", "left_outer"):
                li.append(i)
                ri.append(-1)
        li, ri = np.asarray(li, dtype=int), np.asarray(ri, dtype=int)
        out: Partition = {}
        right_only = [c for c in other.columns if c not in on and c not in self.columns]
        right_dup = [c for c in other.columns if c not in on and c in self.columns]
        for c in self.columns:
            out[c] = left[c][li] if n_l else left[c][:0]
        for c in right_only + right_dup:
            name = c if c in right_only else f"{c}_right"
            src = right[c]
            col = np.empty(len(ri), dtype=src.dtype if src.dtype != object else object)
            valid = ri >= 0
            if src.dtype.kind in "iu" and not valid.all():
                col = col.astype(float)
            col[valid] = src[ri[valid]]
            if not valid.all():
                if col.dtype == object:
                    col[~valid] = None
                else:
                    col = col.astype(float)
                    col[~valid] = np.nan
            out[name] = col
        df = DataFrame([out])
        if how in ("outer", "right", "right_outer"):
            # append unmatched right rows
            extra_idx = np.nonzero(~matched_r)[0]
            if len(extra_idx):
                extra: Partition = {}
                for c in self.columns:
                    if c in on:
                        extra[c] = right[c][extra_idx]
                    else:
                        src = left[c]
                        if src.dtype == object:
                            e = np.empty(len(extra_idx), dtype=object)
                            e[:] = None
                        else:
                            e = np.full(len(extra_idx), np.nan)
                        extra[c] = e
                for c in right_only + right_dup:
                    name = c if c in right_only else f"{c}_right"
                    extra[name] = right[c][extra_idx]
                df = df.union(DataFrame([extra]))
        return df

    # ---------------------------------------------------------------- misc
    def __repr__(self) -> str:
        return f"DataFrame(columns={self.columns}, rows={self.count()}, partitions={self.num_partitions})"

    def show(self, n: int = 10) -> None:
        rows = self.head(n)
        print(" | ".join(self.columns))
        for r in rows:
            print(" | ".join(str(r[c]) for c in self.columns))


def _hashable(v):
    if isinstance(v, np.ndarray):
        return (v.shape, v.tobytes())
    if isinstance(v, (list, dict)):
        return repr(v)
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    return v


_AGGS = {
    "sum": np.sum,
    "mean": np.mean,
    "min": np.min,
    "max": np.max,
    "count": len,
    "first": lambda a: a[0],
    "collect_list": lambda a: list(a),
}


class GroupedFrame:
    """Minimal groupBy-agg, enough for SAR / ranking eval / class balancing."""

    def __init__(self, df: DataFrame, keys: List[str]):
        self._df = df
        self._keys = keys

    def _groups(self):
        whole = self._df.collect()
        n = len(next(iter(whole.values()))) if whole else 0
        groups: Dict[tuple, List[int]] = {}
        for i in range(n):
            groups.setdefault(tuple(_hashable(whole[k][i]) for k in self._keys), []).append(i)
        return whole, groups

    def agg(self, **aggs: str) -> DataFrame:
        """agg(out_name=("col", "sum"), n=("col", "count"), ...)"""
        whole, groups = self._groups()
        out: Dict[str, list] = {k: [] for k in self._keys}
        for name in aggs:
            out[name] = []
        for key, idx in groups.items():
            idx = np.asarray(idx, dtype=int)
            for k_i, k in enumerate(self._keys):
                out[k].append(whole[k][idx[0]])
            for name, (col, how) in aggs.items():
                out[name].append(_AGGS[how](whole[col][idx]))
        return DataFrame.from_dict({k: _as_column(v) for k, v in out.items()})

    def count(self, name: str = "count") -> DataFrame:
        whole, groups = self._groups()
        out: Dict[str, list] = {k: [] for k in self._keys}
        out[name] = []
        for key, idx in groups.items():
            for k in self._keys:
                out[k].append(whole[k][idx[0]])
            out[name].append(len(idx))
        return DataFrame.from_dict({k: _as_column(v) for k, v in out.items()})

    def apply(self, fn: Callable[[Partition], Mapping[str, Any]]) -> DataFrame:
        """mapGroups: fn(sub-partition) -> single dict of columns (reference
        ``LIMEBase.transform`` uses groupByKey.mapGroups, ``LIMEBase.scala:67``)."""
        whole, groups = self._groups()
        rows = []
        for key, idx in groups.items():
            sub = _slice_part(whole, np.asarray(idx, dtype=int))
            res = fn(sub)
            if res is not None:
                rows.append(res)
        return DataFrame.from_rows(rows)
