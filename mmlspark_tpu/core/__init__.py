from .dataframe import DataFrame, GroupedFrame, Row
from .schema import Schema, ColumnType, Binding, infer_schema, vector_column, \
    stack_vector_column, find_unused_column_name
from .params import (Param, ComplexParam, ServiceParam, ServiceValue, Params,
                     HasInputCol, HasInputCols, HasOutputCol, HasFeaturesCol,
                     HasLabelCol, HasWeightCol, HasPredictionCol,
                     HasProbabilityCol, HasRawPredictionCol)
from .pipeline import (PipelineStage, Transformer, Model, Estimator, Evaluator,
                       Pipeline, PipelineModel, UnaryTransformer)
from .serialize import save, load, save_stage, load_stage, save_dataframe, \
    load_dataframe, Saveable

__all__ = [
    "DataFrame", "GroupedFrame", "Row", "Schema", "ColumnType", "Binding",
    "infer_schema", "vector_column", "stack_vector_column",
    "find_unused_column_name", "Param", "ComplexParam", "ServiceParam",
    "ServiceValue", "Params", "HasInputCol", "HasInputCols", "HasOutputCol",
    "HasFeaturesCol", "HasLabelCol", "HasWeightCol", "HasPredictionCol",
    "HasProbabilityCol", "HasRawPredictionCol", "PipelineStage", "Transformer",
    "Model", "Estimator", "Evaluator", "Pipeline", "PipelineModel",
    "UnaryTransformer", "save", "load", "save_stage", "load_stage",
    "save_dataframe", "load_dataframe", "Saveable",
]
