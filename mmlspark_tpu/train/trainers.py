"""TrainClassifier / TrainRegressor — auto-featurizing wrapped learners.

Reference: ``train/TrainClassifier.scala:49`` (label reindex + auto
featurization wiring :140-180) and ``TrainRegressor``: wrap any learner,
``Featurize`` the raw columns into a vector, reindex labels, fit, and emit a
model that runs featurization + scoring + label decode in one transform.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, HasFeaturesCol,
                    HasLabelCol, Model, Param)
from ..featurize import Featurize, ValueIndexer


def _wire_categorical_slots(learner, featurizer) -> None:
    """Auto-pass index-encoded slots as LightGBM categorical features — the
    reference reads categorical slot metadata off the assembled vector
    (``getCategoricalIndexes``, LightGBMBase.scala:168).  Only fires when
    the learner HAS the param and the user hasn't set it explicitly."""
    if "categorical_features" not in type(learner)._params:
        return
    if learner.is_set("categorical_features"):
        return  # respect an explicit user setting, even an empty list
    slots = featurizer.categorical_slots()
    if slots:
        learner.set("categorical_features", slots)


class TrainClassifier(Estimator, HasLabelCol):
    model = ComplexParam("model", "underlying classifier estimator")
    features_col = Param("features_col", "assembled features column", "string",
                         default="TrainClassifier_features")
    number_of_features = Param("number_of_features", "hash dims for text", "int",
                               default=2 ** 8)
    one_hot_encode_categoricals = Param(
        "one_hot_encode_categoricals", "one-hot string columns; False = index"
        "-encode and auto-wire LightGBM categorical splits", "bool",
        default=True)
    reindex_label = Param("reindex_label", "index labels to 0..K-1", "bool", default=True)

    def __init__(self, model=None, uid=None, **kwargs):
        super().__init__(uid)
        if model is not None:
            self.set("model", model)
        if kwargs:
            self.set_params(**kwargs)

    def _fit(self, df: DataFrame) -> "TrainedClassifierModel":
        learner = self.get_or_fail("model")
        lc = self.get_or_fail("label_col")
        fc = self.get("features_col")

        label_model = None
        work = df
        if self.get("reindex_label"):
            label_model = ValueIndexer().set_params(
                input_col=lc, output_col=lc + "_idx").fit(df)
            work = label_model.transform(df)
            label_for_fit = lc + "_idx"
        else:
            label_for_fit = lc

        feat_cols = [c for c in df.columns if c != lc]
        featurizer = Featurize().set_params(
            input_cols=feat_cols, output_col=fc,
            one_hot_encode_categoricals=self.get("one_hot_encode_categoricals"),
            num_features=self.get("number_of_features")).fit(work)
        work = featurizer.transform(work)

        learner = learner.copy()
        learner.set("features_col", fc)
        learner.set("label_col", label_for_fit)
        _wire_categorical_slots(learner, featurizer)
        fitted = learner.fit(work)

        out = TrainedClassifierModel()
        out.set("featurizer", featurizer)
        out.set("inner_model", fitted)
        out.set("label_model", label_model)
        out.set("label_col", lc)
        out.set("features_col", fc)
        return out


class TrainedClassifierModel(Model, HasLabelCol):
    featurizer = ComplexParam("featurizer", "fitted featurize model")
    inner_model = ComplexParam("inner_model", "fitted classifier")
    label_model = ComplexParam("label_model", "fitted label indexer")
    features_col = Param("features_col", "features column", "string")

    def _transform(self, df: DataFrame) -> DataFrame:
        work = self.get_or_fail("featurizer").transform(df)
        scored = self.get_or_fail("inner_model").transform(work)
        label_model = self.get("label_model")
        if label_model is not None:
            levels = label_model.get("levels")

            def decode(p):
                out = np.empty(len(p["prediction"]), dtype=object)
                for i, v in enumerate(p["prediction"]):
                    iv = int(v)
                    out[i] = levels[iv] if 0 <= iv < len(levels) else None
                return out

            scored = scored.with_column("predicted_" + self.get("label_col"), decode)
        return scored.drop(self.get("features_col"))


class TrainRegressor(Estimator, HasLabelCol):
    model = ComplexParam("model", "underlying regressor estimator")
    features_col = Param("features_col", "assembled features column", "string",
                         default="TrainRegressor_features")
    number_of_features = Param("number_of_features", "hash dims for text", "int",
                               default=2 ** 8)
    one_hot_encode_categoricals = Param(
        "one_hot_encode_categoricals", "one-hot string columns; False = index"
        "-encode and auto-wire LightGBM categorical splits", "bool",
        default=True)

    def __init__(self, model=None, uid=None, **kwargs):
        super().__init__(uid)
        if model is not None:
            self.set("model", model)
        if kwargs:
            self.set_params(**kwargs)

    def _fit(self, df: DataFrame) -> "TrainedRegressorModel":
        learner = self.get_or_fail("model")
        lc = self.get_or_fail("label_col")
        fc = self.get("features_col")
        feat_cols = [c for c in df.columns if c != lc]
        featurizer = Featurize().set_params(
            input_cols=feat_cols, output_col=fc,
            one_hot_encode_categoricals=self.get("one_hot_encode_categoricals"),
            num_features=self.get("number_of_features")).fit(df)
        work = featurizer.transform(df)
        learner = learner.copy()
        learner.set("features_col", fc)
        learner.set("label_col", lc)
        _wire_categorical_slots(learner, featurizer)
        fitted = learner.fit(work)
        out = TrainedRegressorModel()
        out.set("featurizer", featurizer)
        out.set("inner_model", fitted)
        out.set("features_col", fc)
        return out


class TrainedRegressorModel(Model):
    featurizer = ComplexParam("featurizer", "fitted featurize model")
    inner_model = ComplexParam("inner_model", "fitted regressor")
    features_col = Param("features_col", "features column", "string")

    def _transform(self, df: DataFrame) -> DataFrame:
        work = self.get_or_fail("featurizer").transform(df)
        return self.get_or_fail("inner_model").transform(work) \
            .drop(self.get("features_col"))
