from .trainers import (TrainClassifier, TrainedClassifierModel, TrainRegressor,
                       TrainedRegressorModel)
from .metrics import (ComputeModelStatistics, ComputePerInstanceStatistics,
                      MetricConstants, classification_metrics,
                      regression_metrics)

__all__ = ["TrainClassifier", "TrainedClassifierModel", "TrainRegressor",
           "TrainedRegressorModel", "ComputeModelStatistics",
           "ComputePerInstanceStatistics", "MetricConstants",
           "classification_metrics", "regression_metrics"]
