"""Evaluation metrics engine.

Reference: ``train/ComputeModelStatistics.scala:58`` (confusion-matrix math
:330-371), ``ComputePerInstanceStatistics``, metric registry
``core/metrics/MetricConstants.scala``.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core import DataFrame, HasLabelCol, Param, Transformer
from ..core.schema import vector_column


class MetricConstants:
    ACCURACY = "accuracy"
    PRECISION = "precision"
    RECALL = "recall"
    AUC = "AUC"
    F1 = "f1_score"
    MSE = "mean_squared_error"
    RMSE = "root_mean_squared_error"
    MAE = "mean_absolute_error"
    R2 = "R^2"
    ALL = "all"
    CLASSIFICATION_METRICS = [ACCURACY, PRECISION, RECALL, AUC, F1]
    REGRESSION_METRICS = [MSE, RMSE, MAE, R2]


def _auc(y: np.ndarray, score: np.ndarray) -> float:
    order = np.argsort(score)
    y_s = y[order]
    pos = (y_s > 0).astype(float)
    neg = 1.0 - pos
    cum_neg = np.cumsum(neg)
    P, N = pos.sum(), neg.sum()
    if P == 0 or N == 0:
        return 0.5
    return float(np.sum(pos * (cum_neg - 0.5 * neg)) / (P * N))


def confusion_matrix(y: np.ndarray, pred: np.ndarray, classes: np.ndarray) -> np.ndarray:
    k = len(classes)
    idx = {c: i for i, c in enumerate(classes)}
    cm = np.zeros((k, k), np.float64)
    for t, p in zip(y, pred):
        cm[idx[t], idx[p]] += 1
    return cm


def classification_metrics(y: np.ndarray, pred: np.ndarray,
                           scores: Optional[np.ndarray] = None) -> Dict[str, float]:
    classes = np.unique(np.concatenate([y, pred]))
    cm = confusion_matrix(y, pred, classes)
    acc = float(np.trace(cm) / max(cm.sum(), 1))
    # macro precision/recall (reference computes per-class then averages)
    with np.errstate(invalid="ignore", divide="ignore"):
        prec = np.nan_to_num(np.diag(cm) / cm.sum(axis=0))
        rec = np.nan_to_num(np.diag(cm) / cm.sum(axis=1))
    precision, recall = float(prec.mean()), float(rec.mean())
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    out = {MetricConstants.ACCURACY: acc, MetricConstants.PRECISION: precision,
           MetricConstants.RECALL: recall, MetricConstants.F1: f1}
    if scores is not None and len(classes) <= 2:
        pos_label = classes.max()
        out[MetricConstants.AUC] = _auc((y == pos_label).astype(float), scores)
    return out


def regression_metrics(y: np.ndarray, pred: np.ndarray) -> Dict[str, float]:
    err = pred - y
    mse = float(np.mean(err ** 2))
    return {MetricConstants.MSE: mse,
            MetricConstants.RMSE: float(np.sqrt(mse)),
            MetricConstants.MAE: float(np.mean(np.abs(err))),
            MetricConstants.R2: float(1.0 - mse / max(np.var(y), 1e-12))}


class ComputeModelStatistics(Transformer, HasLabelCol):
    """Metrics frame from a scored dataset (reference :58)."""

    scores_col = Param("scores_col", "prediction column", "string", default="prediction")
    scored_probabilities_col = Param("scored_probabilities_col",
                                     "probability column (binary AUC)", "string",
                                     default=None)
    evaluation_metric = Param("evaluation_metric", "classification|regression|all",
                              "string", default="all")

    def _transform(self, df: DataFrame) -> DataFrame:
        data = df.collect()
        y = np.asarray(data[self.get_or_fail("label_col")], np.float64)
        pred = np.asarray(data[self.get_or_fail("scores_col")], np.float64)
        kind = self.get("evaluation_metric")
        if kind in ("classification", "all") and len(np.unique(y)) <= max(20, 2):
            is_classification = np.allclose(y, np.round(y)) and len(np.unique(y)) <= 20
        else:
            is_classification = False
        if kind == "classification" or (kind == "all" and is_classification):
            scores = None
            pc = self.get("scored_probabilities_col")
            if pc and pc in data:
                col = data[pc]
                scores = np.asarray([np.asarray(v)[-1] if isinstance(v, (list, np.ndarray))
                                     else float(v) for v in col], np.float64)
            m = classification_metrics(y, pred, scores)
            m["confusion_matrix"] = confusion_matrix(
                y, pred, np.unique(np.concatenate([y, pred]))).tolist()
        else:
            m = regression_metrics(y, pred)
        return DataFrame.from_rows([m])


class ComputePerInstanceStatistics(Transformer, HasLabelCol):
    """Per-row loss/correctness columns (reference
    ``ComputePerInstanceStatistics.scala``)."""

    scores_col = Param("scores_col", "prediction column", "string", default="prediction")
    scored_probabilities_col = Param("scored_probabilities_col", "probability column",
                                     "string", default=None)
    evaluation_metric = Param("evaluation_metric", "classification|regression",
                              "string", default="regression")

    def _transform(self, df: DataFrame) -> DataFrame:
        lc, sc = self.get_or_fail("label_col"), self.get("scores_col")
        kind = self.get("evaluation_metric")
        pc = self.get("scored_probabilities_col")

        def per_part(p):
            y = np.asarray(p[lc], np.float64)
            pred = np.asarray(p[sc], np.float64)
            if kind == "classification":
                correct = (y == pred).astype(np.float64)
                res = {**p, "correct": correct}
                if pc and pc in p:
                    probs = np.asarray([np.asarray(v) for v in p[pc]])
                    picked = probs[np.arange(len(y)), y.astype(int)]
                    res["log_loss"] = -np.log(np.clip(picked, 1e-15, None))
                return res
            err = pred - y
            return {**p, "L1_loss": np.abs(err), "L2_loss": err ** 2}

        return df.map_partitions(per_part)
