"""VowpalWabbitFeaturizer — hash any columns into sparse vectors.

Reference: ``vw/.../VowpalWabbitFeaturizer.scala:25`` with per-type dispatch
(``:67-82``) to 11 typed featurizers (Numeric/String/StringSplit/Map*/Seq/
Struct/Vector) plus ``VowpalWabbitInteractions`` (namespace cross products)
and ``VectorUtils`` sorted sparse merge.  Hashing stays host-side
(``docs/vw.md:29-30``); the TPU consumes the (indices, values) arrays.

Output column cells are dicts {"indices": int32[], "values": float32[]} with
indices already masked to 2^num_bits (VW's -b).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import (DataFrame, HasInputCols, HasOutputCol, Param, Transformer)
from ..core.schema import ColumnType
from .murmur import StringHashCache, murmur3_ints

VW_DEFAULT_SEED = 0


def _sorted_merge(idx_list, val_list):
    """Merge sparse (idx, val) pairs, summing duplicates (VectorUtils)."""
    if not idx_list:
        return np.empty(0, np.int32), np.empty(0, np.float32)
    idx = np.concatenate(idx_list)
    val = np.concatenate(val_list)
    order = np.argsort(idx, kind="stable")
    idx, val = idx[order], val[order]
    uniq, start = np.unique(idx, return_index=True)
    sums = np.add.reduceat(val, start) if len(val) else val
    return uniq.astype(np.int32), sums.astype(np.float32)


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    num_bits = Param("num_bits", "hash space bits (VW -b)", "int", default=18)
    seed = Param("seed", "murmur seed", "int", default=VW_DEFAULT_SEED)
    string_split_cols = Param("string_split_cols", "string columns to tokenize "
                              "on whitespace (StringSplitFeaturizer)", "list", default=[])
    prefix_strings_with_column_name = Param("prefix_strings_with_column_name",
                                            "namespace the hashes by column", "bool",
                                            default=True)
    sum_collisions = Param("sum_collisions", "sum colliding hash values", "bool",
                           default=True)

    def __init__(self, uid: Optional[str] = None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    def _hash_column(self, name: str, col: np.ndarray, mask: int,
                     hasher: StringHashCache, split: bool):
        """Returns per-row (idx_arrays, val_arrays) lists."""
        n = len(col)
        prefix = name if self.get("prefix_strings_with_column_name") else ""
        ns_seed = hasher(prefix) if prefix else self.get("seed")
        out_idx: List[np.ndarray] = [None] * n
        out_val: List[np.ndarray] = [None] * n
        first = next((v for v in col if v is not None), None)

        if first is None:
            z = np.empty(0, np.int32)
            zv = np.empty(0, np.float32)
            return [z] * n, [zv] * n

        if isinstance(first, str) and split:
            # StringSplitFeaturizer: bag of tokens
            for i, v in enumerate(col):
                toks = (v or "").split()
                hashes = np.asarray([hasher(prefix + t) for t in toks], np.uint32)
                out_idx[i] = (hashes & mask).astype(np.int32)
                out_val[i] = np.ones(len(toks), np.float32)
        elif isinstance(first, str):
            # StringFeaturizer: categorical one-hot at hash(col+value)
            hashed = hasher.hash_array(np.asarray([prefix + (v or "") for v in col]))
            for i in range(n):
                out_idx[i] = np.asarray([hashed[i] & mask], np.int32)
                out_val[i] = np.ones(1, np.float32)
        elif isinstance(first, dict):
            # Map featurizer: key -> numeric/string value
            for i, v in enumerate(col):
                v = v or {}
                idxs, vals = [], []
                for k, x in v.items():
                    if isinstance(x, str):
                        idxs.append(hasher(prefix + str(k) + "^" + x))
                        vals.append(1.0)
                    else:
                        idxs.append(hasher(prefix + str(k)))
                        vals.append(float(x))
                out_idx[i] = (np.asarray(idxs, np.uint32) & mask).astype(np.int32)
                out_val[i] = np.asarray(vals, np.float32)
        elif isinstance(first, (list, tuple, np.ndarray)):
            # Vector/Seq featurizer: index-hashed dense values
            for i, v in enumerate(col):
                arr = np.asarray(v, np.float32)
                nz = np.nonzero(arr)[0]
                hashes = murmur3_ints(nz.astype(np.uint32), ns_seed)
                out_idx[i] = (hashes & mask).astype(np.int32)
                out_val[i] = arr[nz]
        else:
            # NumericFeaturizer: single weight at hash(column name)
            base = np.int32(hasher(prefix or name) & mask)
            vals = np.asarray(col, np.float32)
            for i in range(n):
                out_idx[i] = np.asarray([base], np.int32)
                out_val[i] = np.asarray([vals[i]], np.float32)
        return out_idx, out_val

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get_or_fail("input_cols")
        mask = (1 << self.get("num_bits")) - 1
        hasher = StringHashCache(self.get("seed"))
        split_cols = set(self.get("string_split_cols") or [])
        out_col = self.get_or_fail("output_col")

        def per_part(p):
            n = len(next(iter(p.values()))) if p else 0
            per_col = [self._hash_column(c, p[c], mask, hasher, c in split_cols)
                       for c in cols]
            out = np.empty(n, dtype=object)
            for i in range(n):
                idx, val = _sorted_merge([pc[0][i] for pc in per_col],
                                         [pc[1][i] for pc in per_col])
                out[i] = {"indices": idx, "values": val}
            return {**p, out_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        for c in self.get_or_fail("input_cols"):
            schema.require(c)
        return schema.add(self.get_or_fail("output_col"), ColumnType.STRUCT)


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol):
    """Namespace cross-products (quadratic features).

    Reference: ``vw/.../VowpalWabbitInteractions.scala`` — VW's ``-q``:
    hash of the pair = interaction of the two namespaces' hashes.
    """

    num_bits = Param("num_bits", "hash space bits", "int", default=18)

    def __init__(self, uid: Optional[str] = None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get_or_fail("input_cols")
        mask = (1 << self.get("num_bits")) - 1
        out_col = self.get_or_fail("output_col")

        def cross(a, b):
            # VW pair hash: h = h_a * prime + h_b
            prime = np.uint32(16777619)
            ia = a["indices"].astype(np.uint32)
            ib = b["indices"].astype(np.uint32)
            with np.errstate(over="ignore"):
                hh = (ia[:, None] * prime + ib[None, :]).reshape(-1)
            vv = (a["values"][:, None] * b["values"][None, :]).reshape(-1)
            return (hh & mask).astype(np.int32), vv.astype(np.float32)

        def per_part(p):
            n = len(next(iter(p.values()))) if p else 0
            out = np.empty(n, dtype=object)
            for i in range(n):
                idx_list, val_list = [], []
                for ci in range(len(cols)):
                    for cj in range(ci + 1, len(cols)):
                        idx, val = cross(p[cols[ci]][i], p[cols[cj]][i])
                        idx_list.append(idx)
                        val_list.append(val)
                idx, val = _sorted_merge(idx_list, val_list)
                out[i] = {"indices": idx, "values": val}
            return {**p, out_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        return schema.add(self.get_or_fail("output_col"), ColumnType.STRUCT)
