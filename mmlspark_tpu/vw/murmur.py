"""MurmurHash3 (x86 32-bit) — VW-compatible feature hashing.

Reference: VW's hashing reimplemented JVM-side for speed
(``VowpalWabbitMurmurWithPrefix``, ``vw/.../featurizer/``; ``docs/vw.md:29-30``
notes hashing host-side beat hashing through JNI — the same argument applies
here: hash on host CPU in vectorized numpy, ship only (indices, values) to
the TPU).  Matches the canonical MurmurHash3_x86_32 bit-for-bit.
"""
from __future__ import annotations

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _fmix(h: np.ndarray) -> np.ndarray:
    h ^= h >> np.uint32(16)
    h *= np.uint32(0x85EBCA6B)
    h ^= h >> np.uint32(13)
    h *= np.uint32(0xC2B2AE35)
    h ^= h >> np.uint32(16)
    return h


def murmur3_bytes(data: bytes, seed: int = 0) -> int:
    """Scalar reference implementation over a byte string."""
    with np.errstate(over="ignore"):
        h = np.uint32(seed)
        n = len(data)
        nblocks = n // 4
        blocks = np.frombuffer(data[: nblocks * 4], dtype="<u4").copy()
        for k in blocks:
            k = np.uint32(k) * _C1
            k = _rotl(k, 15) * _C2
            h = (_rotl(h ^ k, 13) * np.uint32(5)) + np.uint32(0xE6546B64)
        k = np.uint32(0)
        tail = data[nblocks * 4:]
        if len(tail) >= 3:
            k ^= np.uint32(tail[2]) << np.uint32(16)
        if len(tail) >= 2:
            k ^= np.uint32(tail[1]) << np.uint32(8)
        if len(tail) >= 1:
            k ^= np.uint32(tail[0])
            k = _rotl(k * _C1, 15) * _C2
            h ^= k
        return int(_fmix(h ^ np.uint32(n)))


def murmur3_ints(values: np.ndarray, seed: int = 0) -> np.ndarray:
    """Vectorized murmur3 of 4-byte little-endian ints (VW hashes numeric
    feature indices this way).  values: (n,) uint32 -> (n,) uint32."""
    with np.errstate(over="ignore"):
        k = values.astype(np.uint32) * _C1
        k = _rotl(k, 15) * _C2
        h = np.uint32(seed) ^ k
        h = (_rotl(h, 13) * np.uint32(5)) + np.uint32(0xE6546B64)
        return _fmix(h ^ np.uint32(4))


class StringHashCache:
    """Memoized string hashing (feature names repeat across rows)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._cache: dict = {}

    def __call__(self, s: str) -> int:
        v = self._cache.get(s)
        if v is None:
            v = murmur3_bytes(s.encode("utf-8"), self.seed)
            self._cache[s] = v
        return v

    def hash_array(self, arr: np.ndarray) -> np.ndarray:
        uniq, inv = np.unique(arr.astype(str), return_inverse=True)
        misses = [u for u in uniq if u not in self._cache]
        if len(misses) > 32:  # batch the cold strings through the C++ kernel
            from ..utils.native_loader import murmur3_batch_native
            hashed = murmur3_batch_native(misses, self.seed)
            if hashed is not None:
                for u, h in zip(misses, hashed):
                    self._cache[u] = int(h)
        hashes = np.asarray([self(u) for u in uniq], dtype=np.uint32)
        return hashes[inv]
