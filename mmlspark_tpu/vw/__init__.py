from .murmur import murmur3_bytes, murmur3_ints, StringHashCache
from .featurizer import VowpalWabbitFeaturizer, VowpalWabbitInteractions
from .learners import (VowpalWabbitClassifier, VowpalWabbitClassificationModel,
                       VowpalWabbitRegressor, VowpalWabbitRegressionModel,
                       VowpalWabbitContextualBandit,
                       VowpalWabbitContextualBanditModel, TrainingStats,
                       pack_sparse_column)

__all__ = ["murmur3_bytes", "murmur3_ints", "StringHashCache",
           "VowpalWabbitFeaturizer", "VowpalWabbitInteractions",
           "VowpalWabbitClassifier", "VowpalWabbitClassificationModel",
           "VowpalWabbitRegressor", "VowpalWabbitRegressionModel",
           "VowpalWabbitContextualBandit", "VowpalWabbitContextualBanditModel",
           "TrainingStats", "pack_sparse_column"]
