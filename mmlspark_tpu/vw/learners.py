"""VowpalWabbit-equivalent online learners on TPU.

Reference: ``vw/src/main/scala/.../VowpalWabbitBase.scala`` — per-partition
native VW training (``trainRow`` hot loop :261-292) with spanning-tree
allreduce between passes (``trainInternalDistributed:434-462``), and
``VowpalWabbitClassifier/Regressor/ContextualBandit``.

TPU-native redesign: the model is a dense weight vector over the 2^b hash
space living in HBM; examples arrive as padded (indices, values) minibatches;
one jitted step does predict + VW-style adaptive/normalized gradient update
via segment scatter-adds.  In multi-process (executor) runs each process
trains its own partition shard and passes end with a cross-process mean of
weights and optimizer accumulators (``_allreduce_pass_end``) — the
spanning-tree replacement (SURVEY.md §2.12).

The update rule follows VW's ``--adaptive --normalized`` defaults: AdaGrad
per-weight step sizes with per-weight scale normalization; ``--bfgs``
switches to full-batch L-BFGS over the cached examples (optax.lbfgs with
line search — the batch-mode reduction).  TrainingStats diagnostics mirror the reference's
per-partition stats DataFrame (``VowpalWabbitBase.scala:27-49``).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, HasFeaturesCol,
                    HasLabelCol, HasPredictionCol, HasProbabilityCol,
                    HasRawPredictionCol, HasWeightCol, Model, Param)
from ..core.schema import ColumnType
from ..utils.stopwatch import StopWatch


def pack_sparse_column(col: np.ndarray, max_nnz: Optional[int] = None,
                       mask: Optional[int] = None):
    """Object column of {'indices','values'} dicts -> padded (n, k) arrays.
    Padding uses value 0.0 so padded slots contribute nothing.  ``mask``
    folds indices into the learner's weight space (VW masks hashes into the
    2^b table at example-parse time, so a featurizer hashed with more bits
    than the learner's ``-b`` still trains — out-of-range indices would be
    silently dropped by XLA's scatter instead)."""
    n = len(col)
    if max_nnz is None:
        max_nnz = max((len(v["indices"]) for v in col), default=1) or 1
    idx = np.zeros((n, max_nnz), np.int32)
    val = np.zeros((n, max_nnz), np.float32)
    for i, v in enumerate(col):
        k = min(len(v["indices"]), max_nnz)
        idx[i, :k] = v["indices"][:k]
        val[i, :k] = v["values"][:k]
    if mask is not None:
        idx &= mask
    return idx, val


@dataclasses.dataclass
class TrainingStats:
    """Reference ``TrainingStats`` (VowpalWabbitBase.scala:27-49)."""
    partition_id: int
    rows: int
    features_per_example: float
    passes: int
    total_time_s: float
    ingest_time_s: float
    learn_time_s: float

    def as_row(self) -> Dict:
        return dataclasses.asdict(self)


def _allreduce_pass_end(state):
    """End-of-pass weight averaging across executor processes — the
    spanning-tree allreduce replacement (``trainInternalDistributed``,
    VowpalWabbitBase.scala:434-462; SURVEY.md §2.12).  Each executor trains
    its own partition shard; at pass end, weights AND the AdaGrad/normalizer
    accumulators are averaged so every process continues the next pass from
    the same model.  Single-process runs return the state untouched."""
    import jax
    if jax.process_count() <= 1:
        return state
    from jax.experimental import multihost_utils
    import jax.numpy as jnp
    weights, gsq, xmax = state
    gathered = multihost_utils.process_allgather(
        jnp.stack([weights, gsq]))                      # (P, 2, D)
    mean = gathered.mean(axis=0)
    xmax_all = multihost_utils.process_allgather(xmax)  # (P, D)
    return (jnp.asarray(mean[0]), jnp.asarray(mean[1]),
            jnp.asarray(xmax_all.max(axis=0)))


def _interaction_features(part: Dict, base_col: np.ndarray, specs: List[str],
                          mask: int) -> np.ndarray:
    """VW ``-q ab`` semantics: cross every namespace whose name starts with
    'a' against every one starting with 'b' and append the crossed features
    to each example.  Namespaces are sparse-dict columns of the frame (the
    featurizer's namespace=column convention); the pair hash matches
    ``VowpalWabbitInteractions`` (h_a * 16777619 + h_b)."""
    ns_cols = {name: col for name, col in part.items()
               if len(col) and isinstance(col[0], dict) and "indices" in col[0]}
    prime = np.uint32(16777619)
    n = len(base_col)
    out = np.empty(n, dtype=object)
    for i in range(n):
        idx_list = [np.asarray(base_col[i]["indices"], np.int32)]
        val_list = [np.asarray(base_col[i]["values"], np.float32)]
        for spec in specs:
            if len(spec) < 2:
                continue
            a_cols = [c for c in ns_cols if c.startswith(spec[0])]
            b_cols = [c for c in ns_cols if c.startswith(spec[1])]
            for ca in a_cols:
                for cb in b_cols:
                    fa, fb = ns_cols[ca][i], ns_cols[cb][i]
                    ia = np.asarray(fa["indices"]).astype(np.uint32)
                    ib = np.asarray(fb["indices"]).astype(np.uint32)
                    with np.errstate(over="ignore"):
                        hh = (ia[:, None] * prime + ib[None, :]).reshape(-1)
                    vv = (np.asarray(fa["values"])[:, None]
                          * np.asarray(fb["values"])[None, :]).reshape(-1)
                    idx_list.append((hh & mask).astype(np.int32))
                    val_list.append(vv.astype(np.float32))
        out[i] = {"indices": np.concatenate(idx_list),
                  "values": np.concatenate(val_list)}
    return out


def _loss_values(loss: str, quantile_tau: float):
    """Loss VALUES (for the --bfgs batch objective; grads below for SGD)."""
    import jax.numpy as jnp

    def logistic(pred, y):
        return jnp.logaddexp(0.0, -y * pred)

    def squared(pred, y):
        return 0.5 * (pred - y) ** 2

    def hinge(pred, y):
        return jnp.maximum(0.0, 1.0 - y * pred)

    def quantile(pred, y):
        e = y - pred
        return jnp.maximum(quantile_tau * e, (quantile_tau - 1.0) * e)

    return {"logistic": logistic, "squared": squared, "hinge": hinge,
            "quantile": quantile}[loss]


def _loss_grads(loss: str, quantile_tau: float):
    import jax.numpy as jnp

    def logistic(pred, y):   # y in {-1, +1}
        return -y / (1.0 + jnp.exp(y * pred))

    def squared(pred, y):
        return pred - y

    def hinge(pred, y):
        return jnp.where(y * pred < 1.0, -y, 0.0)

    def quantile(pred, y):
        return jnp.where(pred > y, quantile_tau, quantile_tau - 1.0)

    return {"logistic": logistic, "squared": squared, "hinge": hinge,
            "quantile": quantile}[loss]


class _VWBase(Estimator, HasFeaturesCol, HasLabelCol, HasWeightCol):
    num_bits = Param("num_bits", "hash space bits (VW -b)", "int", default=18)
    learning_rate = Param("learning_rate", "base learning rate (VW -l)", "float", default=0.5)
    power_t = Param("power_t", "lr decay exponent", "float", default=0.5)
    initial_t = Param("initial_t", "initial example-count t (VW --initial_t); "
                      "the non-adaptive lr denominator is t^power_t", "float",
                      default=0.0)
    num_passes = Param("num_passes", "passes over the data", "int", default=1)
    l1 = Param("l1", "L1 regularization", "float", default=0.0)
    l2 = Param("l2", "L2 regularization", "float", default=0.0)
    adaptive = Param("adaptive", "AdaGrad per-weight rates (VW --adaptive)", "bool", default=True)
    normalized = Param("normalized", "scale-normalized updates (VW --normalized)", "bool", default=True)
    batch_size = Param("batch_size", "device minibatch size", "int", default=256)
    initial_model = Param("initial_model", "warm-start model bytes", "object")
    optimizer = Param("optimizer", "sgd (online adaptive/normalized updates) "
                      "| bfgs (full-batch L-BFGS, the VW --bfgs batch mode)",
                      "string", default="sgd",
                      validator=lambda v: v in ("sgd", "bfgs"))
    args = Param("args", "VW-style passthrough arg string (subset parsed: "
                         "-b -l --l1 --l2 --passes --loss_function --power_t "
                         "--initial_t --(no)adaptive --(no)normalized -q "
                         "--interactions --cb_type --quiet)", "string", default="")
    interactions = Param("interactions", "namespace-pair interaction specs "
                         "(VW -q/--interactions)", "list", default=None)
    use_barrier_execution_mode = Param("use_barrier_execution_mode",
                                       "parity param (gang scheduling is implicit "
                                       "in XLA collectives)", "bool", default=False)

    def _parse_args(self):
        """Reference builds its native command line from Params and a raw
        passthrough string (``VowpalWabbitBase.buildCommandLineArguments``,
        VowpalWabbitBase.scala:237, args param :80).  Parsed flags land in
        this INSTANCE's Params only — never in class state."""
        toks = (self.get("args") or "").split()
        i = 0
        while i < len(toks):
            t = toks[i]
            def nxt():
                return toks[i + 1] if i + 1 < len(toks) else None
            if t in ("-b", "--bit_precision") and nxt():
                self.set("num_bits", int(nxt())); i += 1
            elif t in ("-l", "--learning_rate") and nxt():
                self.set("learning_rate", float(nxt())); i += 1
            elif t == "--l1" and nxt():
                self.set("l1", float(nxt())); i += 1
            elif t == "--l2" and nxt():
                self.set("l2", float(nxt())); i += 1
            elif t == "--passes" and nxt():
                self.set("num_passes", int(nxt())); i += 1
            elif t == "--power_t" and nxt():
                self.set("power_t", float(nxt())); i += 1
            elif t == "--initial_t" and nxt():
                self.set("initial_t", float(nxt())); i += 1
            elif t == "--loss_function" and nxt():
                if "loss_function" in type(self)._params:
                    self.set("loss_function", nxt())
                i += 1
            elif t == "--adaptive":
                self.set("adaptive", True)
            elif t == "--noadaptive":
                self.set("adaptive", False)
            elif t == "--normalized":
                self.set("normalized", True)
            elif t == "--nonormalized":
                self.set("normalized", False)
            elif t in ("-q", "--quadratic", "--interactions") and nxt():
                pairs = list(self.get("interactions") or [])
                if nxt() not in pairs:  # idempotent across re-parses
                    pairs.append(nxt())
                self.set("interactions", pairs); i += 1
            elif t == "--cb_type" and nxt():
                if "cb_type" in type(self)._params:
                    self.set("cb_type", nxt())
                elif nxt() != "ips":
                    raise NotImplementedError(
                        f"--cb_type {nxt()} on a non-bandit learner")
                i += 1
            elif t == "--quiet":
                pass
            elif t == "--bfgs":
                self.set("optimizer", "bfgs")
            i += 1

    def _make_trainer(self, loss_name: str):
        import jax
        import jax.numpy as jnp

        D = 1 << self.get("num_bits")
        lr = self.get("learning_rate")
        adaptive = self.get("adaptive")
        normalized = self.get("normalized")
        l1, l2 = self.get("l1"), self.get("l2")
        power_t = self.get("power_t")
        grad_fn = _loss_grads(loss_name, 0.5)

        @jax.jit
        def step(state, idx, val, y, w, t):
            weights, gsq, xmax = state
            pred = jnp.sum(weights[idx] * val, axis=1)          # (bs,)
            g = grad_fn(pred, y) * w                            # (bs,)
            gv = g[:, None] * val                               # (bs, k)
            flat_idx = idx.reshape(-1)
            flat_gv = gv.reshape(-1)
            if normalized:
                xmax = xmax.at[flat_idx].max(jnp.abs(val).reshape(-1))
            if adaptive:
                gsq = gsq.at[flat_idx].add(flat_gv * flat_gv)
                denom = jnp.sqrt(gsq[flat_idx]) + 1e-8
            else:
                denom = jnp.power(t, power_t)
            scale = jnp.where(xmax[flat_idx] > 0, xmax[flat_idx], 1.0) if normalized else 1.0
            delta = lr * flat_gv / (denom * scale)
            if l2:
                delta = delta + lr * l2 * weights[flat_idx]
            weights = weights.at[flat_idx].add(-delta)
            if l1:
                wv = weights[flat_idx]
                weights = weights.at[flat_idx].set(
                    jnp.sign(wv) * jnp.maximum(jnp.abs(wv) - lr * l1, 0.0))
            return (weights, gsq, xmax), pred

        return step, D

    def _fit_bfgs(self, df: DataFrame, loss_name: str, y_transform):
        """VW ``--bfgs``: batch optimization over the cached examples
        (reference: VW's bfgs reduction runs L-BFGS passes over the cache
        file).  One padded (n, k) gather turns the hashed-sparse model into
        a dense objective; ``optax.lbfgs`` with line search drives it."""
        import jax
        import jax.numpy as jnp
        import optax

        D = 1 << self.get("num_bits")
        mask = D - 1
        fc, lc = self.get("features_col"), self.get("label_col")
        wc = self.get("weight_col")
        specs = self.get("interactions") or []
        sw = StopWatch()
        parts_idx, part_ids, ys, ws = [], [], [], []
        max_nnz = 1
        with sw.measure("ingest"):
            for pid, part in enumerate(df.partitions):
                if fc not in part or len(part[fc]) == 0:
                    continue
                feats = part[fc]
                if specs:
                    feats = _interaction_features(part, feats, specs, mask)
                max_nnz = max(max_nnz, max((len(v["indices"]) for v in feats),
                                           default=1))
                parts_idx.append(feats)
                part_ids.append(pid)
                ys.append(y_transform(np.asarray(part[lc], np.float64)))
                ws.append(np.asarray(part[wc], np.float32) if wc
                          else np.ones(len(feats), np.float32))
            cols = np.concatenate([np.asarray(c, dtype=object) for c in parts_idx]) \
                if parts_idx else np.empty(0, dtype=object)
            idx, val = pack_sparse_column(cols, max_nnz=max_nnz, mask=mask)
            y = np.concatenate(ys).astype(np.float32) if ys else np.zeros(0, np.float32)
            w = np.concatenate(ws) if ws else np.zeros(0, np.float32)
        n = len(y)
        loss_vals = _loss_values(loss_name, 0.5)
        l1, l2 = self.get("l1"), self.get("l2")
        idx_d, val_d = jnp.asarray(idx), jnp.asarray(val)
        y_d, w_d = jnp.asarray(y), jnp.asarray(w)

        def objective(weights):
            pred = jnp.sum(weights[idx_d] * val_d, axis=1)
            base = jnp.sum(loss_vals(pred, y_d) * w_d) / jnp.maximum(w_d.sum(), 1e-9)
            return base + 0.5 * l2 * jnp.sum(weights * weights) \
                + l1 * jnp.sum(jnp.abs(weights))

        init = self.get("initial_model")
        w0 = jnp.asarray(VowpalWabbitModelBase.bytes_to_weights(init, D)
                         if init is not None else np.zeros(D, np.float32))
        opt = optax.lbfgs()
        value_and_grad = optax.value_and_grad_from_state(objective)

        @jax.jit
        def lbfgs_step(weights, opt_state):
            value, grad = value_and_grad(weights, state=opt_state)
            updates, opt_state = opt.update(grad, opt_state, weights,
                                            value=value, grad=grad,
                                            value_fn=objective)
            return optax.apply_updates(weights, updates), opt_state

        # respect an explicit --passes; default to 20 L-BFGS iterations when
        # the user didn't set one (num_passes' online default of 1 would be
        # a single line-search step)
        iters = self.get("num_passes") if "num_passes" in self._paramMap else 20
        with sw.measure("learn"):
            opt_state = opt.init(w0)
            weights = w0
            for _ in range(iters):
                weights, opt_state = lbfgs_step(weights, opt_state)
        state = _allreduce_pass_end((weights, jnp.zeros(D), jnp.zeros(D)))
        # features/example from pre-padding index lengths: explicit zero
        # values count, all-padding rows don't (ADVICE r2); one stats row
        # per source partition with its true id, mirroring the online path
        stats = []
        for pid, feats in zip(part_ids, parts_idx):
            rows_p = len(feats)
            nnz_p = sum(len(v["indices"]) for v in feats)
            stats.append(TrainingStats(
                partition_id=pid, rows=rows_p,
                features_per_example=float(nnz_p / max(rows_p, 1)),
                passes=iters, total_time_s=sw.total_elapsed(),
                ingest_time_s=sw.elapsed("ingest"),
                learn_time_s=sw.elapsed("learn")))
        if not stats:
            stats = [TrainingStats(partition_id=0, rows=0,
                                   features_per_example=0.0, passes=iters,
                                   total_time_s=sw.total_elapsed(),
                                   ingest_time_s=sw.elapsed("ingest"),
                                   learn_time_s=sw.elapsed("learn"))]
        return np.asarray(state[0]), stats

    def _fit_weights(self, df: DataFrame, loss_name: str, y_transform):
        import jax
        import jax.numpy as jnp

        self._parse_args()
        if self.get("optimizer") == "bfgs":
            return self._fit_bfgs(df, loss_name, y_transform)
        step, D = self._make_trainer(loss_name)
        fc, lc = self.get("features_col"), self.get("label_col")
        wc = self.get("weight_col")
        bs = self.get("batch_size")
        sw = StopWatch()

        init = self.get("initial_model")
        if init is not None:
            weights0 = VowpalWabbitModelBase.bytes_to_weights(init, D)
        else:
            weights0 = np.zeros(D, np.float32)
        state = (jnp.asarray(weights0), jnp.zeros(D, jnp.float32),
                 jnp.zeros(D, jnp.float32))

        stats: List[TrainingStats] = []
        specs = self.get("interactions") or []
        mask = (1 << self.get("num_bits")) - 1
        t = 1.0 + self.get("initial_t")
        for pass_i in range(self.get("num_passes")):
            for pid, part in enumerate(df.partitions):
                n = len(part[fc]) if fc in part else 0
                if n == 0:
                    continue
                with sw.measure("ingest"):
                    feats = part[fc]
                    if specs:
                        feats = _interaction_features(part, feats, specs, mask)
                    idx, val = pack_sparse_column(feats, mask=mask)
                    y = y_transform(np.asarray(part[lc], np.float64)).astype(np.float32)
                    w = np.asarray(part[wc], np.float32) if wc else np.ones(n, np.float32)
                with sw.measure("learn"):
                    for s in range(0, n, bs):
                        bidx, bval = idx[s:s + bs], val[s:s + bs]
                        by, bw = y[s:s + bs], w[s:s + bs]
                        m = len(by)
                        if m < bs:  # pad batch to bucket to avoid recompiles
                            pad = bs - m
                            bidx = np.pad(bidx, ((0, pad), (0, 0)))
                            bval = np.pad(bval, ((0, pad), (0, 0)))
                            by = np.pad(by, (0, pad))
                            bw = np.pad(bw, (0, pad))
                        state, _ = step(state, jnp.asarray(bidx), jnp.asarray(bval),
                                        jnp.asarray(by), jnp.asarray(bw),
                                        jnp.float32(t))
                        t += m
                if pass_i == self.get("num_passes") - 1:
                    stats.append(TrainingStats(
                        partition_id=pid, rows=n,
                        features_per_example=float((val != 0).sum() / max(n, 1)),
                        passes=self.get("num_passes"),
                        total_time_s=sw.total_elapsed(),
                        ingest_time_s=sw.elapsed("ingest"),
                        learn_time_s=sw.elapsed("learn")))
            # end of pass: average weights across executor processes — the
            # reference's spanning-tree allreduce (VowpalWabbitBase.scala:
            # 434-462).  No-op in a single-process run.
            state = _allreduce_pass_end(state)
        return np.asarray(state[0]), stats

    def _attach_common(self, model, stats):
        model.set("features_col", self.get("features_col"))
        model.set("num_bits", self.get("num_bits"))
        model.set("interactions", self.get("interactions"))
        model.set("stats", [s.as_row() for s in stats])
        for pc in ("prediction_col",):
            if pc in type(model)._params and pc in type(self)._params:
                model.set(pc, self.get(pc))
        return model


class VowpalWabbitModelBase(Model, HasFeaturesCol, HasPredictionCol):
    weights_param = ComplexParam("weights", "dense hash-space weights")
    num_bits = Param("num_bits", "hash space bits", "int", default=18)
    stats = Param("stats", "per-partition training stats rows", "list")
    interactions = Param("interactions", "namespace-pair interaction specs "
                         "applied at scoring time", "list", default=None)

    def _effective_features(self, part: Dict) -> np.ndarray:
        """Feature column with any trained ``-q`` interactions appended —
        scoring must hash exactly what training hashed."""
        col = part[self.get("features_col")]
        specs = self.get("interactions") or []
        if specs:
            col = _interaction_features(part, col, specs,
                                        (1 << self.get("num_bits")) - 1)
        return col

    @property
    def weights(self) -> np.ndarray:
        return self.get_or_fail("weights")

    def get_performance_statistics(self) -> DataFrame:
        """Reference diagnostics DataFrame (VowpalWabbitBase.scala:475-489)."""
        return DataFrame.from_rows(self.get("stats") or [])

    # model-bytes interop (reference ByteArrayParam model, :137)
    def model_bytes(self) -> bytes:
        return self.weights.astype(np.float32).tobytes()

    @staticmethod
    def bytes_to_weights(b: bytes, dim: int) -> np.ndarray:
        w = np.frombuffer(b, np.float32)
        if len(w) != dim:
            raise ValueError(f"model bytes hold {len(w)} weights, expected {dim}")
        return w.copy()

    def _raw_scores(self, col: np.ndarray) -> np.ndarray:
        idx, val = pack_sparse_column(col, mask=(1 << self.get("num_bits")) - 1)
        w = self.weights
        return (w[idx] * val).sum(axis=1)


class VowpalWabbitClassifier(_VWBase, HasPredictionCol, HasProbabilityCol,
                             HasRawPredictionCol):
    """Binary classifier, logistic loss (reference VowpalWabbitClassifier)."""
    loss_function = Param("loss_function", "logistic|hinge", "string", default="logistic")

    def _fit(self, df: DataFrame) -> "VowpalWabbitClassificationModel":
        self._parse_args()  # --loss_function etc. must land before reads
        weights, stats = self._fit_weights(
            df, self.get("loss_function"),
            lambda y: np.where(y > 0, 1.0, -1.0))
        model = VowpalWabbitClassificationModel()
        model.set("weights", weights)
        model.set("probability_col", self.get("probability_col"))
        model.set("raw_prediction_col", self.get("raw_prediction_col"))
        return self._attach_common(model, stats)


class VowpalWabbitClassificationModel(VowpalWabbitModelBase, HasProbabilityCol,
                                      HasRawPredictionCol):
    def _transform(self, df: DataFrame) -> DataFrame:
        fc = self.get("features_col")

        def per_part(p):
            raw = self._raw_scores(self._effective_features(p))
            # clipped sigmoid: extreme margins would overflow np.exp (the
            # probability saturates at float precision well before |30|)
            prob = 1.0 / (1.0 + np.exp(-np.clip(raw, -30.0, 30.0)))
            prob_col = np.empty(len(raw), dtype=object)
            raw_col = np.empty(len(raw), dtype=object)
            for i in range(len(raw)):
                prob_col[i] = np.asarray([1 - prob[i], prob[i]])
                raw_col[i] = np.asarray([-raw[i], raw[i]])
            return {**p, self.get("prediction_col"): (raw > 0).astype(np.float64),
                    self.get("probability_col"): prob_col,
                    self.get("raw_prediction_col"): raw_col}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get("features_col"))
        return schema.add(self.get("prediction_col"), ColumnType.DOUBLE)


class VowpalWabbitRegressor(_VWBase, HasPredictionCol):
    loss_function = Param("loss_function", "squared|quantile", "string", default="squared")

    def _fit(self, df: DataFrame) -> "VowpalWabbitRegressionModel":
        self._parse_args()  # --loss_function etc. must land before reads
        weights, stats = self._fit_weights(df, self.get("loss_function"), lambda y: y)
        model = VowpalWabbitRegressionModel()
        model.set("weights", weights)
        return self._attach_common(model, stats)


class VowpalWabbitRegressionModel(VowpalWabbitModelBase):
    def _transform(self, df: DataFrame) -> DataFrame:
        fc = self.get("features_col")

        def per_part(p):
            return {**p, self.get("prediction_col"):
                    self._raw_scores(self._effective_features(p))}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get("features_col"))
        return schema.add(self.get("prediction_col"), ColumnType.DOUBLE)


class VowpalWabbitContextualBandit(_VWBase):
    """Contextual bandit via IPS-weighted cost regression.

    Reference: ``VowpalWabbitContextualBandit`` (376 LoC; DataFrame-of-actions
    API).  Columns: shared features, per-action features (object column of
    lists of sparse dicts), chosen action (1-based), cost, probability.
    Learns a scorer s(shared, action); ``predict`` emits per-action scores
    (lower = better, VW cost semantics).
    """

    shared_col = Param("shared_col", "shared-context sparse features column", "string",
                       default="shared_features")
    action_col = Param("action_col", "per-action features column (list of sparse "
                       "dicts per row)", "string", default="action_features")
    chosen_action_col = Param("chosen_action_col", "1-based chosen action", "string",
                              default="chosen_action")
    cost_col = Param("cost_col", "observed cost of chosen action", "string", default="cost")
    probability_col = Param("probability_col", "logging policy probability", "string",
                            default="probability")
    cb_type = Param("cb_type", "bandit estimator: ips (inverse-propensity "
                    "weights) | mtr (regress observed costs unweighted)",
                    "string", default="ips")

    def _fit(self, df: DataFrame) -> "VowpalWabbitContextualBanditModel":
        import jax.numpy as jnp
        self._parse_args()
        if self.get("optimizer") == "bfgs":
            raise NotImplementedError(
                "--bfgs is not supported for the contextual bandit (the IPS "
                "objective is trained online); use the default sgd optimizer")
        step, D = self._make_trainer("squared")
        sw = StopWatch()
        shared_c = self.get("shared_col")
        act_c = self.get("action_col")
        bs = self.get("batch_size")

        state = (jnp.zeros(D, jnp.float32), jnp.zeros(D, jnp.float32),
                 jnp.zeros(D, jnp.float32))
        t = 1.0 + self.get("initial_t")
        stats: List[TrainingStats] = []
        for pass_i in range(self.get("num_passes")):
            for pid, part in enumerate(df.partitions):
                n = len(part[act_c])
                if n == 0:
                    continue
                rows_idx, rows_val, targets, ws = [], [], [], []
                cbt = self.get("cb_type")
                if cbt not in ("ips", "mtr"):
                    raise NotImplementedError(
                        f"--cb_type {cbt}: only ips/mtr on this backend")
                with sw.measure("ingest"):
                    chosen = np.asarray(part[self.get("chosen_action_col")], np.int64) - 1
                    cost = np.asarray(part[self.get("cost_col")], np.float64)
                    prob = np.asarray(part[self.get("probability_col")], np.float64)
                    for i in range(n):
                        a = part[act_c][i][int(chosen[i])]
                        sh = part[shared_c][i] if shared_c in part else \
                            {"indices": np.empty(0, np.int32), "values": np.empty(0, np.float32)}
                        rows_idx.append(np.concatenate([sh["indices"], a["indices"]]))
                        rows_val.append(np.concatenate([sh["values"], a["values"]]))
                        targets.append(cost[i])
                        ws.append(1.0 / max(prob[i], 1e-6) if cbt == "ips" else 1.0)
                col = np.empty(n, dtype=object)
                for i in range(n):
                    col[i] = {"indices": rows_idx[i], "values": rows_val[i]}
                idx, val = pack_sparse_column(col, mask=(1 << self.get("num_bits")) - 1)
                y = np.asarray(targets, np.float32)
                w = np.asarray(ws, np.float32)
                w = w / w.mean()
                with sw.measure("learn"):
                    for s in range(0, n, bs):
                        m = len(y[s:s + bs])
                        pad = bs - m
                        bidx = np.pad(idx[s:s + bs], ((0, pad), (0, 0)))
                        bval = np.pad(val[s:s + bs], ((0, pad), (0, 0)))
                        by = np.pad(y[s:s + bs], (0, pad))
                        bw = np.pad(w[s:s + bs], (0, pad))
                        state, _ = step(state, jnp.asarray(bidx), jnp.asarray(bval),
                                        jnp.asarray(by), jnp.asarray(bw), jnp.float32(t))
                        t += m
                if pass_i == self.get("num_passes") - 1:
                    stats.append(TrainingStats(pid, n, float(np.mean([len(r) for r in rows_idx])),
                                               self.get("num_passes"), sw.total_elapsed(),
                                               sw.elapsed("ingest"), sw.elapsed("learn")))
        model = VowpalWabbitContextualBanditModel()
        model.set("weights", np.asarray(state[0]))
        model.set("shared_col", shared_c)
        model.set("action_col", act_c)
        return self._attach_common(model, stats)


class VowpalWabbitContextualBanditModel(VowpalWabbitModelBase):
    shared_col = Param("shared_col", "shared features column", "string", default="shared_features")
    action_col = Param("action_col", "per-action features column", "string", default="action_features")

    def _transform(self, df: DataFrame) -> DataFrame:
        w = self.weights
        shared_c, act_c = self.get("shared_col"), self.get("action_col")

        mask = (1 << self.get("num_bits")) - 1

        def per_part(p):
            n = len(p[act_c])
            out = np.empty(n, dtype=object)
            for i in range(n):
                acts = p[act_c][i]
                scores = []
                sh = p[shared_c][i] if shared_c in p else None
                for a in acts:
                    s = float((w[np.asarray(a["indices"]) & mask] * a["values"]).sum())
                    if sh is not None:
                        s += float((w[np.asarray(sh["indices"]) & mask]
                                    * sh["values"]).sum())
                    scores.append(s)
                out[i] = np.asarray(scores)
            return {**p, self.get("prediction_col"): out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get("action_col"))
        return schema.add(self.get("prediction_col"), ColumnType.VECTOR)
