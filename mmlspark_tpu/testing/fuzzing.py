"""Fuzzing harness — reflection-driven stage testing.

Reference: ``core/src/test/.../core/test/fuzzing/Fuzzing.scala`` —
``ExperimentFuzzing`` (:192 run fit/transform on declared TestObjects),
``SerializationFuzzing`` (:222 save/load stage + fitted model, assert
identical transforms), and the global sweep ``FuzzingTest.scala:18`` that
reflects over every stage and enforces coverage by construction.
"""
from __future__ import annotations

import dataclasses
import tempfile
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import DataFrame, Estimator, Params, Transformer
from ..core.serialize import load_stage, save_stage


def assert_frames_equal(a: DataFrame, b: DataFrame, atol: float = 1e-6) -> None:
    """DataFrameEquality analogue."""
    assert sorted(a.columns) == sorted(b.columns), (a.columns, b.columns)
    da, db = a.collect(), b.collect()
    for c in a.columns:
        ca, cb = da[c], db[c]
        assert len(ca) == len(cb), f"column {c}: {len(ca)} vs {len(cb)} rows"
        if ca.dtype == object or cb.dtype == object:
            for i, (x, y) in enumerate(zip(ca, cb)):
                if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                    assert np.allclose(np.asarray(x, float), np.asarray(y, float),
                                       atol=atol), f"{c}[{i}]"
                else:
                    assert x == y, f"{c}[{i}]: {x!r} != {y!r}"
        else:
            assert np.allclose(ca.astype(float), cb.astype(float), atol=atol,
                               equal_nan=True), f"column {c}"


@dataclasses.dataclass
class TestObject:
    """A stage + the frames needed to exercise it (reference TestObject)."""
    __test__ = False  # not a pytest class
    stage: Params
    fit_df: Optional[DataFrame] = None          # estimators
    transform_df: Optional[DataFrame] = None    # transformers / fitted models

    @property
    def df(self) -> DataFrame:
        return self.transform_df if self.transform_df is not None else self.fit_df


class ExperimentFuzzing:
    """Run the declared experiments (reference ExperimentFuzzing:192)."""

    @staticmethod
    def run(obj: TestObject):
        stage = obj.stage
        if isinstance(stage, Estimator):
            model = stage.fit(obj.fit_df)
            out_df = obj.transform_df if obj.transform_df is not None else obj.fit_df
            return model, model.transform(out_df)
        assert isinstance(stage, Transformer), type(stage)
        return stage, stage.transform(obj.df)


class SerializationFuzzing:
    """save/load the raw stage AND the fitted model; assert the reloaded
    artifacts transform identically (reference SerializationFuzzing:222)."""

    @staticmethod
    def run(obj: TestObject, atol: float = 1e-5):
        stage = obj.stage
        with tempfile.TemporaryDirectory() as d:
            # raw stage roundtrip preserves params
            save_stage(stage, f"{d}/raw")
            reloaded = load_stage(f"{d}/raw")
            assert type(reloaded) is type(stage)
            assert reloaded.uid == stage.uid
            if isinstance(stage, Estimator):
                model = stage.fit(obj.fit_df)
                out_df = obj.transform_df if obj.transform_df is not None else obj.fit_df
                expected = model.transform(out_df)
                save_stage(model, f"{d}/model")
                model2 = load_stage(f"{d}/model")
                got = model2.transform(out_df)
            else:
                out_df = obj.df
                expected = stage.transform(out_df)
                got = reloaded.transform(out_df)
            assert_frames_equal(expected, got, atol=atol)
