"""Accuracy-benchmark regression harness.

Reference: ``core/src/test/.../benchmarks/Benchmarks.scala:36`` — metric
values are appended to a CSV and compared against a checked-in baseline file
with per-metric precision (``compareBenchmark:70``); higherIsBetter rows only
fail when the new value is worse by more than the precision.

The reference's baseline datasets are fetched at build time from Azure
(BuildInfo.datasetDir) and are unavailable offline; this harness keeps the
exact file format and comparison semantics over deterministic synthetic
datasets (seeded), so regressions gate the same way.
"""
from __future__ import annotations

import csv
import dataclasses
import os
from typing import Dict, List


@dataclasses.dataclass
class Benchmark:
    name: str
    value: float
    precision: float
    higher_is_better: bool = True

    @staticmethod
    def from_row(row: Dict[str, str]) -> "Benchmark":
        return Benchmark(row["name"], float(row["value"]), float(row["precision"]),
                         row["higherIsBetter"].strip().lower() == "true")


class Benchmarks:
    """Collect benchmarks during a run, then compare to the baseline CSV."""

    def __init__(self, baseline_path: str):
        self.baseline_path = baseline_path
        self.new: List[Benchmark] = []

    def add(self, name: str, value: float, precision: float,
            higher_is_better: bool = True) -> None:
        self.new.append(Benchmark(name, value, precision, higher_is_better))

    def write_baseline(self, path: str = None) -> None:
        path = path or self.baseline_path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["name", "value", "precision", "higherIsBetter"])
            for b in self.new:
                w.writerow([b.name, b.value, b.precision,
                            str(b.higher_is_better).lower()])

    def load_baseline(self) -> Dict[str, Benchmark]:
        with open(self.baseline_path, newline="") as f:
            return {b.name: b for b in
                    (Benchmark.from_row(r) for r in csv.DictReader(f))}

    @staticmethod
    def compare(new: Benchmark, old: Benchmark) -> None:
        """Reference compareBenchmark:70 semantics."""
        if old.higher_is_better:
            assert new.value >= old.value - old.precision, \
                f"{new.name}: {new.value} below baseline {old.value} - {old.precision}"
        else:
            assert new.value <= old.value + old.precision, \
                f"{new.name}: {new.value} above baseline {old.value} + {old.precision}"

    def verify(self) -> None:
        old = self.load_baseline()
        new_names = {b.name for b in self.new}
        assert new_names == set(old), \
            f"benchmark set changed: +{new_names - set(old)} -{set(old) - new_names}"
        for b in self.new:
            self.compare(b, old[b.name])
