"""Seed TestObjects for flagship stages — consumed both by the in-repo
fuzzing sweep and by the GENERATED per-stage test files (the reference's
per-suite ``testObjects()`` declarations feeding PyTestFuzzing,
``Fuzzing.scala:47-172``)."""
from __future__ import annotations

from typing import Dict

import numpy as np

from ..core import DataFrame
from ..core.schema import vector_column
from .fuzzing import TestObject


def vec_frame(n=60, d=5, seed=0, label=True) -> DataFrame:
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    cols = {"features": vector_column(list(X))}
    if label:
        cols["label"] = (X[:, 0] > 0).astype(float)
    return DataFrame.from_dict(cols, 2)


def seed_objects() -> Dict[str, TestObject]:
    """Qualname -> TestObject for every stage with a declared seed."""
    from mmlspark_tpu.lightgbm import (LightGBMClassifier, LightGBMRanker,
                                       LightGBMRegressor)
    from mmlspark_tpu.vw import VowpalWabbitClassifier, VowpalWabbitFeaturizer
    from mmlspark_tpu.featurize import CleanMissingData, ValueIndexer
    from mmlspark_tpu.isolationforest import IsolationForest
    from mmlspark_tpu.nn import KNN
    from mmlspark_tpu.stages import (FixedMiniBatchTransformer, SummarizeData,
                                     TextPreprocessor)
    from mmlspark_tpu.opencv import ImageTransformer, ImageSetAugmenter
    from mmlspark_tpu.recommendation import SAR
    from mmlspark_tpu.cognitive import SpeechToTextSDK
    from mmlspark_tpu.featurize.text import MultiNGram
    from mmlspark_tpu.io.audio import write_wav

    vec = vec_frame()
    rng = np.random.default_rng(1)
    sp_col = np.empty(40, dtype=object)
    for i in range(40):
        sp_col[i] = {"indices": np.arange(5, dtype=np.int32),
                     "values": rng.normal(size=5).astype(np.float32)}
    sparse = DataFrame.from_dict({"features": sp_col,
                                  "label": (rng.random(40) > 0.5).astype(float)}, 2)
    txt = DataFrame.from_dict({"text": np.array(["Hello World", "FOO bar"],
                                                dtype=object)})
    imgs = np.empty(2, dtype=object)
    for i in range(2):
        imgs[i] = rng.uniform(0, 255, (8, 8, 3)).astype(np.float32)
    img_df = DataFrame.from_dict({"image": imgs})
    nan_df = DataFrame.from_dict({"x": np.array([1.0, np.nan, 5.0])})

    objs = [
        TestObject(LightGBMClassifier().set_params(num_iterations=5, min_data_in_leaf=2), vec),
        TestObject(LightGBMRegressor().set_params(num_iterations=5, min_data_in_leaf=2), vec),
        TestObject(VowpalWabbitClassifier().set_params(num_bits=8, num_passes=2), sparse),
        TestObject(VowpalWabbitFeaturizer().set_params(input_cols=["text"], output_col="f"),
                   transform_df=txt),
        TestObject(CleanMissingData().set_params(input_cols=["x"]), nan_df),
        TestObject(ValueIndexer().set_params(input_col="text", output_col="i"), txt),
        TestObject(IsolationForest().set_params(num_estimators=10), vec.drop("label")),
        TestObject(KNN().set_params(k=2, output_col="m"), vec.drop("label")),
        TestObject(FixedMiniBatchTransformer().set_params(batch_size=3),
                   transform_df=vec),
        TestObject(SummarizeData(), transform_df=vec_frame(20, 2, label=False)
                   .with_column("n", lambda p: np.arange(len(p["features"]), dtype=float))
                   .drop("features")),
        TestObject(TextPreprocessor().set_params(input_col="text", output_col="t"),
                   transform_df=txt),
        TestObject(ImageTransformer(input_col="image", output_col="o").resize(4, 4),
                   transform_df=img_df),
        TestObject(ImageSetAugmenter().set_params(input_col="image",
                                                  output_col="aug"),
                   transform_df=img_df),
    ]

    # SAR: three users x five items, every pair seen twice
    sar_df = DataFrame.from_rows(
        [{"user": f"u{i % 3}", "item": f"i{(i * 7) % 5}", "rating": 1.0}
         for i in range(30)])
    objs.append(TestObject(SAR().set_params(support_threshold=1), sar_df))

    # ranker: grouped queries
    gsize, nq = 8, 6
    Xr = rng.normal(size=(gsize * nq, 4))
    rank_df = DataFrame.from_dict({
        "features": vector_column(list(Xr)),
        "label": (Xr[:, 0] > 0).astype(float),
        "group": np.repeat(np.arange(nq), gsize).astype(float)}, 1)
    objs.append(TestObject(LightGBMRanker().set_params(
        num_iterations=3, min_data_in_leaf=2), rank_df))

    # streaming speech over a wav column
    t = np.arange(4000) / 16000.0
    wavs = np.empty(1, dtype=object)
    wavs[0] = write_wav((0.3 * np.sin(2 * np.pi * 440 * t)).astype(np.float32),
                        16000)
    stt_df = DataFrame.from_dict({"audio": wavs})
    objs.append(TestObject(SpeechToTextSDK(input_col="audio",
                                           output_col="events", chunk_s=0.1),
                           transform_df=stt_df))

    # n-grams over TOKENIZED text (the stage's contract: list column)
    toks = np.empty(2, dtype=object)
    toks[0] = ["the", "quick", "brown", "fox"]
    toks[1] = ["hello", "world"]
    tok_df = DataFrame.from_dict({"text": toks})
    objs.append(TestObject(MultiNGram().set_params(input_col="text",
                                                   output_col="ngrams"),
                           transform_df=tok_df))
    return {type(o.stage).__qualname__: o for o in objs}
