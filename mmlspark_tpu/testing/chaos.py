"""Deterministic fault injection — seeded chaos for the resilience layer.

Every injector owns a ``random.Random(seed)``: the fault sequence is a pure
function of the seed and the call sequence, so chaos tests replay exactly
(no real network flakes, no wall-clock races).  Injectors wrap the
``transport`` callable that ``io/http.HTTPClient`` exposes (monkeypatch an
instance's ``.transport`` or pass ``transport=`` at construction) and
compose by nesting::

    t = LatencyInjector(seed=1, rate=0.3, latency_s=0.2, sleep=clk.sleep).wrap(
        ConnectionErrorInjector(seed=2, rate=0.5).wrap(base_transport))
    client = HTTPClient(transport=t, clock=clk, sleep=clk.sleep)

Server-side chaos: ``WorkerKiller`` kills a ``WorkerServer``'s socket
without deregistering (a crash, as the topology service sees it) and can
restart it on a fresh port, re-registering with the driver — driving the
health-probe eviction and failover paths end to end.
"""
from __future__ import annotations

import random
import signal as _signal
import threading
import time
from typing import Callable, Optional

from ..io.http import HTTPRequestData, HTTPResponseData
from ..utils.resilience import FakeClock  # re-export for chaos suites

__all__ = ["ChaosInjector", "LatencyInjector", "ConnectionErrorInjector",
           "StatusStormInjector", "WorkerKiller", "FakeClock",
           "FlakyLoadInjector", "PreemptionSimulator"]

Transport = Callable[[HTTPRequestData, float], HTTPResponseData]


class ChaosInjector:
    """Base: a seeded coin decides per call whether to inject.  ``injected``
    and ``calls`` counters make assertions about the schedule cheap."""

    def __init__(self, seed: int = 0, rate: float = 1.0):
        self.rng = random.Random(seed)
        self.rate = float(rate)
        self.calls = 0
        self.injected = 0
        self._lock = threading.Lock()

    def _fire(self) -> bool:
        with self._lock:
            self.calls += 1
            fire = self.rng.random() < self.rate
            if fire:
                self.injected += 1
            return fire

    def _inject(self, req: HTTPRequestData, timeout_s: float,
                inner: Transport) -> HTTPResponseData:
        raise NotImplementedError

    def wrap(self, inner: Transport) -> Transport:
        def transport(req: HTTPRequestData, timeout_s: float) -> HTTPResponseData:
            if self._fire():
                return self._inject(req, timeout_s, inner)
            return inner(req, timeout_s)
        return transport


class LatencyInjector(ChaosInjector):
    """Latency spike before the real exchange.  ``sleep`` is injectable —
    pass a FakeClock's ``sleep`` so spikes advance virtual time only."""

    def __init__(self, seed: int = 0, rate: float = 1.0,
                 latency_s: float = 0.2,
                 sleep: Optional[Callable[[float], None]] = None):
        super().__init__(seed, rate)
        self.latency_s = latency_s
        self.sleep = sleep or time.sleep

    def _inject(self, req, timeout_s, inner):
        self.sleep(self.latency_s)
        if self.latency_s > timeout_s:
            raise TimeoutError(
                f"injected latency {self.latency_s}s > timeout {timeout_s}s")
        return inner(req, timeout_s)


class ConnectionErrorInjector(ChaosInjector):
    """Transport-level failure (refused/reset), as urllib would raise it."""

    def _inject(self, req, timeout_s, inner):
        raise ConnectionError(f"injected connection failure -> {req.url}")


class StatusStormInjector(ChaosInjector):
    """HTTP error storm: 429/503 replies with an optional Retry-After, the
    shape a throttling or overloaded service produces."""

    def __init__(self, seed: int = 0, rate: float = 1.0, status: int = 503,
                 retry_after_s: Optional[float] = None):
        super().__init__(seed, rate)
        self.status = status
        self.retry_after_s = retry_after_s

    def _inject(self, req, timeout_s, inner):
        headers = {}
        if self.retry_after_s is not None:
            headers["Retry-After"] = str(self.retry_after_s)
        return HTTPResponseData(status_code=self.status,
                                reason="injected storm", headers=headers,
                                entity=b'{"error": "injected"}')


class FlakyLoadInjector(ChaosInjector):
    """Compute-plane twin of the HTTP injectors: wraps a prefetcher
    ``load_fn`` and makes it raise a transient error on a seeded coin —
    the tile-load failure class (flaky storage, wedged device relay) the
    ``TilePrefetcher`` retry exists for.  ``max_injections`` bounds the
    total faults so a high rate cannot exhaust a bounded retry budget by
    pure bad luck; ``exc_factory`` picks the failure shape (default: a
    transient ``ConnectionError``)."""

    def __init__(self, seed: int = 0, rate: float = 1.0,
                 max_injections: Optional[int] = None,
                 exc_factory: Callable[[int], BaseException] = None):
        super().__init__(seed, rate)
        self.max_injections = max_injections
        self.exc_factory = exc_factory or (
            lambda k: ConnectionError(f"injected tile-load failure #{k}"))

    def _fire(self) -> bool:
        with self._lock:
            self.calls += 1
            if self.max_injections is not None \
                    and self.injected >= self.max_injections:
                return False
            fire = self.rng.random() < self.rate
            if fire:
                self.injected += 1
            return fire

    def wrap(self, load_fn: Callable) -> Callable:
        def flaky(item):
            if self._fire():
                raise self.exc_factory(self.injected)
            return load_fn(item)
        return flaky


class PreemptionSimulator:
    """Fires SIGTERM at a seeded boosting-iteration boundary — the
    scheduled-preemption drill for checkpoint-aware training loops.

    Shaped as a ``callbacks`` entry (``cb(iteration, eval)``, the contract
    ``train``/``train_streamed`` already expose): install it and the
    process receives SIGTERM at the END of the chosen iteration, exactly
    where a cloud scheduler's grace window would land mid-run.  The
    iteration is drawn from ``random.Random(seed)`` over [lo, hi), so the
    kill point replays exactly.  ``fired`` makes schedule assertions
    cheap; ``signum`` defaults to SIGTERM (``preemption_scope`` handles
    SIGINT identically)."""

    def __init__(self, seed: int = 0, lo: int = 0, hi: int = 1,
                 signum: int = _signal.SIGTERM):
        if hi <= lo:
            raise ValueError(f"need hi > lo, got [{lo}, {hi})")
        self.rng = random.Random(seed)
        self.at_iteration = self.rng.randrange(lo, hi)
        self.signum = signum
        self.fired = False

    def __call__(self, iteration: int, evals=None) -> None:
        if not self.fired and iteration >= self.at_iteration:
            self.fired = True
            _signal.raise_signal(self.signum)


class WorkerKiller:
    """Kill/restart chaos for distributed serving.

    ``kill`` stops the worker's HTTP socket WITHOUT deregistering — exactly
    what a crashed executor looks like to the driver: still in the routing
    table until the health prober evicts it.  ``restart`` brings the worker
    back on a fresh ``PipelineServer`` (same model/config, port 0) and
    re-registers it.
    """

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.killed: list = []

    def kill(self, worker) -> None:
        """worker: serving.distributed.WorkerServer"""
        worker.server.stop()
        self.killed.append(worker.server_id)

    def kill_one(self, workers) -> object:
        """Seeded pick — deterministic victim selection."""
        victim = workers[self.rng.randrange(len(workers))]
        self.kill(victim)
        return victim

    def restart(self, worker) -> None:
        from ..serving.server import PipelineServer
        old = worker.server
        worker.server = PipelineServer(
            old.model, input_col=old.input_col, reply_col=old.reply_col,
            host=old.host, port=0, api_path=old.api_path, mode=old.mode,
            max_batch=old.max_batch,
            micro_batch_interval_ms=old.interval_ms,
            input_parser=old.input_parser, reply_encoder=old.reply_encoder,
            request_timeout_s=old.request_timeout_s,
            max_queue_depth=old.max_queue_depth,
            max_queue_age_s=old.max_queue_age_s,
            shed_retry_after_s=old.shed_retry_after_s, clock=old.clock)
        worker.start()
