"""Deterministic fault injection — seeded chaos for the resilience layer.

Every injector owns a ``random.Random(seed)``: the fault sequence is a pure
function of the seed and the call sequence, so chaos tests replay exactly
(no real network flakes, no wall-clock races).  Injectors wrap the
``transport`` callable that ``io/http.HTTPClient`` exposes (monkeypatch an
instance's ``.transport`` or pass ``transport=`` at construction) and
compose by nesting::

    t = LatencyInjector(seed=1, rate=0.3, latency_s=0.2, sleep=clk.sleep).wrap(
        ConnectionErrorInjector(seed=2, rate=0.5).wrap(base_transport))
    client = HTTPClient(transport=t, clock=clk, sleep=clk.sleep)

Server-side chaos: ``WorkerKiller`` kills a ``WorkerServer``'s socket
without deregistering (a crash, as the topology service sees it) and can
restart it on a fresh port, re-registering with the driver — driving the
health-probe eviction and failover paths end to end.
"""
from __future__ import annotations

import random
import signal as _signal
import threading
import time
from typing import Callable, Optional

from ..io.http import HTTPRequestData, HTTPResponseData
from ..utils.resilience import FakeClock  # re-export for chaos suites

__all__ = ["ChaosInjector", "LatencyInjector", "ConnectionErrorInjector",
           "StatusStormInjector", "WorkerKiller", "FakeClock",
           "FlakyLoadInjector", "HungLoadInjector", "PreemptionSimulator",
           "ElasticTopologyDrill", "HungWorkerInjector"]

Transport = Callable[[HTTPRequestData, float], HTTPResponseData]


class ChaosInjector:
    """Base: a seeded coin decides per call whether to inject.  ``injected``
    and ``calls`` counters make assertions about the schedule cheap."""

    def __init__(self, seed: int = 0, rate: float = 1.0):
        self.rng = random.Random(seed)
        self.rate = float(rate)
        self.calls = 0
        self.injected = 0
        self._lock = threading.Lock()

    def _fire(self) -> bool:
        with self._lock:
            self.calls += 1
            fire = self.rng.random() < self.rate
            if fire:
                self.injected += 1
            return fire

    def _inject(self, req: HTTPRequestData, timeout_s: float,
                inner: Transport) -> HTTPResponseData:
        raise NotImplementedError

    def wrap(self, inner: Transport) -> Transport:
        def transport(req: HTTPRequestData, timeout_s: float) -> HTTPResponseData:
            if self._fire():
                return self._inject(req, timeout_s, inner)
            return inner(req, timeout_s)
        return transport


class LatencyInjector(ChaosInjector):
    """Latency spike before the real exchange.  ``sleep`` is injectable —
    pass a FakeClock's ``sleep`` so spikes advance virtual time only."""

    def __init__(self, seed: int = 0, rate: float = 1.0,
                 latency_s: float = 0.2,
                 sleep: Optional[Callable[[float], None]] = None):
        super().__init__(seed, rate)
        self.latency_s = latency_s
        self.sleep = sleep or time.sleep

    def _inject(self, req, timeout_s, inner):
        self.sleep(self.latency_s)
        if self.latency_s > timeout_s:
            raise TimeoutError(
                f"injected latency {self.latency_s}s > timeout {timeout_s}s")
        return inner(req, timeout_s)


class ConnectionErrorInjector(ChaosInjector):
    """Transport-level failure (refused/reset), as urllib would raise it."""

    def _inject(self, req, timeout_s, inner):
        raise ConnectionError(f"injected connection failure -> {req.url}")


class StatusStormInjector(ChaosInjector):
    """HTTP error storm: 429/503 replies with an optional Retry-After, the
    shape a throttling or overloaded service produces."""

    def __init__(self, seed: int = 0, rate: float = 1.0, status: int = 503,
                 retry_after_s: Optional[float] = None):
        super().__init__(seed, rate)
        self.status = status
        self.retry_after_s = retry_after_s

    def _inject(self, req, timeout_s, inner):
        headers = {}
        if self.retry_after_s is not None:
            headers["Retry-After"] = str(self.retry_after_s)
        return HTTPResponseData(status_code=self.status,
                                reason="injected storm", headers=headers,
                                entity=b'{"error": "injected"}')


class FlakyLoadInjector(ChaosInjector):
    """Compute-plane twin of the HTTP injectors: wraps a prefetcher
    ``load_fn`` and makes it raise a transient error on a seeded coin —
    the tile-load failure class (flaky storage, wedged device relay) the
    ``TilePrefetcher`` retry exists for.  ``max_injections`` bounds the
    total faults so a high rate cannot exhaust a bounded retry budget by
    pure bad luck; ``exc_factory`` picks the failure shape (default: a
    transient ``ConnectionError``)."""

    def __init__(self, seed: int = 0, rate: float = 1.0,
                 max_injections: Optional[int] = None,
                 exc_factory: Callable[[int], BaseException] = None):
        super().__init__(seed, rate)
        self.max_injections = max_injections
        self.exc_factory = exc_factory or (
            lambda k: ConnectionError(f"injected tile-load failure #{k}"))

    def _fire(self) -> bool:
        with self._lock:
            self.calls += 1
            if self.max_injections is not None \
                    and self.injected >= self.max_injections:
                return False
            fire = self.rng.random() < self.rate
            if fire:
                self.injected += 1
            return fire

    def wrap(self, load_fn: Callable) -> Callable:
        def flaky(item):
            if self._fire():
                raise self.exc_factory(self.injected)
            return load_fn(item)
        return flaky


class HungLoadInjector:
    """The failure the retry CANNOT see: a tile load that never returns
    (NFS server gone away mid-read, wedged device relay holding the
    transfer lock).  No exception is raised, so ``FlakyLoadInjector``'s
    retry path never engages — the prefetch worker just blocks, the
    consumer's tick stream freezes, and only the ISSUE 19 stall watchdog
    notices.  Deterministic by construction: hangs at the ``hang_at``-th
    load call (0-based), not on a coin.

    ``hanging`` is set when the worker is actually blocked (tests wait on
    it instead of sleeping); ``release()`` unblocks the load so the
    stream — and the test — can finish cleanly."""

    def __init__(self, hang_at: int = 0):
        self.hang_at = int(hang_at)
        self.calls = 0
        self.hanging = threading.Event()   # worker is blocked NOW
        self._gate = threading.Event()     # release() opens it
        self._lock = threading.Lock()

    def release(self) -> None:
        self._gate.set()

    def wrap(self, load_fn: Callable) -> Callable:
        def hung(item):
            with self._lock:
                k = self.calls
                self.calls += 1
            if k == self.hang_at and not self._gate.is_set():
                self.hanging.set()
                self._gate.wait()
                self.hanging.clear()
            return load_fn(item)
        return hung


class PreemptionSimulator:
    """Fires SIGTERM at a seeded boosting-iteration boundary — the
    scheduled-preemption drill for checkpoint-aware training loops.

    Shaped as a ``callbacks`` entry (``cb(iteration, eval)``, the contract
    ``train``/``train_streamed`` already expose): install it and the
    process receives SIGTERM at the END of the chosen iteration, exactly
    where a cloud scheduler's grace window would land mid-run.  The
    iteration is drawn from ``random.Random(seed)`` over [lo, hi), so the
    kill point replays exactly.  ``fired`` makes schedule assertions
    cheap; ``signum`` defaults to SIGTERM (``preemption_scope`` handles
    SIGINT identically)."""

    def __init__(self, seed: int = 0, lo: int = 0, hi: int = 1,
                 signum: int = _signal.SIGTERM):
        if hi <= lo:
            raise ValueError(f"need hi > lo, got [{lo}, {hi})")
        self.rng = random.Random(seed)
        self.at_iteration = self.rng.randrange(lo, hi)
        self.signum = signum
        self.fired = False

    def __call__(self, iteration: int, evals=None) -> None:
        if not self.fired and iteration >= self.at_iteration:
            self.fired = True
            _signal.raise_signal(self.signum)


class ElasticTopologyDrill:
    """SIGKILL a sharded training child mid-run, resume it at a DIFFERENT
    mesh width, grow back — the ISSUE 10 crash drill generalized across
    topology (elastic resume, ISSUE 14).

    Each leg runs ``lightgbm.train(shard_rows=True)`` on a ``data`` mesh
    of ``width`` CPU devices (``--xla_force_host_platform_device_count``
    fakes the fleet) against one shared checkpoint directory.  The child
    appends each completed iteration to a marker file; :meth:`run_child`
    SIGKILLs it — no grace, no handler, the crash class atomic
    publication exists for — once enough NEW iterations landed.
    :meth:`train_inline` runs a leg (or the uninterrupted baseline)
    in-process and returns the TrainResult, so the final assertion —
    resumed-across-widths booster == uninterrupted booster, bit for bit —
    stays a plain array compare.  Quantized histograms are forced ON:
    integer accumulation plus global-row-keyed rounding noise is what
    makes the cross-width replay exact."""

    def __init__(self, ckpt_dir: str, marker_path: str, *, rows: int = 801,
                 features: int = 6, num_iterations: int = 8,
                 max_depth: int = 3, seed: int = 3, data_seed: int = 0):
        self.ckpt_dir = str(ckpt_dir)
        self.marker_path = str(marker_path)
        self.rows, self.features = int(rows), int(features)
        self.num_iterations = int(num_iterations)
        self.max_depth, self.seed = int(max_depth), int(seed)
        self.data_seed = int(data_seed)

    # ---- one data/params recipe, shared by children and inline legs
    def make_data(self):
        import numpy as np
        rng = np.random.default_rng(self.data_seed)
        X = rng.normal(size=(self.rows, self.features)).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1]
             + rng.normal(scale=0.3, size=self.rows) > 0).astype(np.float32)
        return X, y

    def make_params(self):
        from ..lightgbm import GBDTParams
        return GBDTParams(num_iterations=self.num_iterations,
                          objective="binary", max_depth=self.max_depth,
                          growth="level", seed=self.seed,
                          use_quantized_grad=True, bagging_fraction=0.7,
                          bagging_freq=2, feature_fraction=0.8)

    def child_program(self, width: int) -> str:
        """Source of one training leg: mesh of ``width`` devices, resume
        from (and checkpoint into) the shared directory, marker line per
        iteration."""
        return (
            "import numpy as np\n"
            "import jax\n"
            "from mmlspark_tpu.lightgbm import GBDTParams\n"
            "from mmlspark_tpu.lightgbm import core as gbdt_core\n"
            "from mmlspark_tpu.parallel import active_mesh, make_mesh\n"
            "from mmlspark_tpu.testing.chaos import ElasticTopologyDrill\n"
            f"drill = ElasticTopologyDrill({self.ckpt_dir!r}, "
            f"{self.marker_path!r}, rows={self.rows}, "
            f"features={self.features}, "
            f"num_iterations={self.num_iterations}, "
            f"max_depth={self.max_depth}, seed={self.seed}, "
            f"data_seed={self.data_seed})\n"
            "X, y = drill.make_data()\n"
            "def cb(it, ev):\n"
            "    with open(drill.marker_path, 'a') as f:\n"
            "        f.write(str(it) + chr(10))\n"
            f"mesh = make_mesh({{'data': {int(width)}}}, "
            f"jax.devices()[:{int(width)}])\n"
            "with active_mesh(mesh):\n"
            "    gbdt_core.train(X, y, drill.make_params(), shard_rows=True,\n"
            "                    checkpoint_dir=drill.ckpt_dir,\n"
            "                    checkpoint_every=1, callbacks=[cb])\n")

    def _marker_lines(self) -> int:
        import os
        if not os.path.exists(self.marker_path):
            return 0
        with open(self.marker_path) as f:
            return len(f.read().splitlines())

    def run_child(self, width: int, min_new_iterations: int = 2,
                  timeout_s: float = 240.0, env: Optional[dict] = None):
        """Spawn one leg at ``width`` and SIGKILL it after it has logged
        ``min_new_iterations`` NEW iterations (children that finish
        first are left finished).  Returns the iteration count observed
        at the kill."""
        import os
        import subprocess
        import sys
        base = self._marker_lines()
        run_env = dict(os.environ, JAX_PLATFORMS="cpu")
        flags = run_env.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            run_env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        if env:
            run_env.update(env)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        proc = subprocess.Popen([sys.executable, "-c",
                                 self.child_program(width)],
                                env=run_env, cwd=repo_root)
        try:
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                if self._marker_lines() >= base + min_new_iterations:
                    break
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            if proc.poll() is None:
                proc.kill()              # SIGKILL: no cleanup, no handler
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        return self._marker_lines()

    def train_inline(self, width: int, checkpoint: bool = True,
                     resume: str = "auto"):
        """Run one leg (or, with ``checkpoint=False``, the uninterrupted
        baseline) in this process on a ``width``-wide mesh."""
        import jax
        from ..lightgbm import core as gbdt_core
        from ..parallel import active_mesh, make_mesh
        X, y = self.make_data()
        kw = {}
        if checkpoint:
            kw = dict(checkpoint_dir=self.ckpt_dir, checkpoint_every=1,
                      resume=resume)
        mesh = make_mesh({"data": int(width)}, jax.devices()[: int(width)])
        with active_mesh(mesh):
            return gbdt_core.train(X, y, self.make_params(),
                                   shard_rows=True, **kw)


class HungWorkerInjector:
    """A worker that accepts connections and never replies — the SLOW
    failure class (hung XLA dispatch, wedged TPU relay) the tail-tolerance
    layer exists for (ISSUE 16).  Unlike :class:`WorkerKiller`'s crash, a
    hung worker keeps its socket OPEN: a connect succeeds, the request is
    swallowed, and without hedging/timeouts the client slot is tied up
    forever.

    Binds a real listening socket; :meth:`register` announces it to a
    ``TopologyService`` as a routable worker so real traffic lands on it.
    ``mode``:

    - ``"black_hole"`` — accept, read the request, write nothing;
    - ``"mid_body"`` — write the status line + headers and a partial body
      (``Content-Length`` promises more), then stall forever.

    ``/health`` probes hang identically, so the driver's prober fails
    them by timeout and eviction proceeds.  Held connections close only
    at :meth:`stop`.  ``accepted`` counts hung exchanges for assertions.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 mode: str = "black_hole"):
        if mode not in ("black_hole", "mid_body"):
            raise ValueError("mode must be black_hole|mid_body")
        self.host, self.port = host, port
        self.mode = mode
        self.accepted = 0
        self._sock = None
        self._conns: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> "HungWorkerInjector":
        import socket
        self._stop.clear()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._sock.settimeout(0.2)  # bounded accept: stop() can join
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="hung-worker")
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        import socket
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us
            with self._lock:
                self.accepted += 1
                self._conns.append(conn)
            if self.mode == "mid_body":
                try:
                    # promise a body that never arrives: the client is
                    # left blocked mid-read, not mid-connect
                    conn.sendall(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Type: application/json\r\n"
                                 b"Content-Length: 1000\r\n\r\n"
                                 b'{"partial": ')
                except OSError:
                    pass
            # never reply, never close: the connection hangs until stop()

    def register(self, driver_address: str, server_id: str = "hung-worker",
                 api_path: str = "/score", request_class: str = "default",
                 role: str = "serving", generation: int = 0) -> None:
        """Announce this socket to the driver as a routable worker."""
        from ..serving.distributed import _http_json
        _http_json(f"{driver_address.rstrip('/')}/register",
                   {"server_id": server_id, "host": self.host,
                    "port": self.port, "api_path": api_path,
                    "request_class": request_class, "role": role,
                    "generation": generation, "partition_ids": []})

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._thread = None
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"


class WorkerKiller:
    """Kill/restart chaos for distributed serving.

    ``kill`` stops the worker's HTTP socket WITHOUT deregistering — exactly
    what a crashed executor looks like to the driver: still in the routing
    table until the health prober evicts it.  ``restart`` brings the worker
    back on a fresh ``PipelineServer`` (same model/config, port 0) and
    re-registers it.
    """

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.killed: list = []

    def kill(self, worker) -> None:
        """worker: serving.distributed.WorkerServer"""
        worker.server.stop()
        self.killed.append(worker.server_id)

    def kill_one(self, workers) -> object:
        """Seeded pick — deterministic victim selection."""
        victim = workers[self.rng.randrange(len(workers))]
        self.kill(victim)
        return victim

    def restart(self, worker) -> None:
        from ..serving.server import PipelineServer
        old = worker.server
        worker.server = PipelineServer(
            old.model, input_col=old.input_col, reply_col=old.reply_col,
            host=old.host, port=0, api_path=old.api_path, mode=old.mode,
            max_batch=old.max_batch,
            micro_batch_interval_ms=old.interval_ms,
            input_parser=old.input_parser, reply_encoder=old.reply_encoder,
            request_timeout_s=old.request_timeout_s,
            max_queue_depth=old.max_queue_depth,
            max_queue_age_s=old.max_queue_age_s,
            shed_retry_after_s=old.shed_retry_after_s, clock=old.clock)
        worker.start()
