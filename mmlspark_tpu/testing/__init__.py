from .fuzzing import TestObject, ExperimentFuzzing, SerializationFuzzing, \
    assert_frames_equal
from .benchmarks import Benchmarks, Benchmark

__all__ = ["TestObject", "ExperimentFuzzing", "SerializationFuzzing",
           "assert_frames_equal", "Benchmarks", "Benchmark"]
