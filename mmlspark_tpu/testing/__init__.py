from .fuzzing import TestObject, ExperimentFuzzing, SerializationFuzzing, \
    assert_frames_equal
from .benchmarks import Benchmarks, Benchmark
from .chaos import (ChaosInjector, ConnectionErrorInjector, FakeClock,
                    LatencyInjector, StatusStormInjector, WorkerKiller)

__all__ = ["TestObject", "ExperimentFuzzing", "SerializationFuzzing",
           "assert_frames_equal", "Benchmarks", "Benchmark",
           "ChaosInjector", "LatencyInjector", "ConnectionErrorInjector",
           "StatusStormInjector", "WorkerKiller", "FakeClock"]
