"""Test modifiers — retry/flaky/time-limit decorators.

Reference: ``TestBase.scala`` modifiers ``tryWithRetries`` (:95 area),
``LinuxOnly`` (:60), ``Flaky`` (:65), ``TimeLimitedFlaky`` (:77) — the
reference's approximation of fault injection (SURVEY.md §5.3).
"""
from __future__ import annotations

import functools
import platform
import time
from typing import Callable, Tuple


def try_with_retries(times: Tuple[int, ...] = (0, 100, 500), exceptions=(AssertionError, Exception)):
    """Retry the wrapped callable with the given sleep schedule (ms)."""
    def deco(fn: Callable):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            last = None
            for i, delay_ms in enumerate(times):
                if delay_ms:
                    time.sleep(delay_ms / 1000.0)
                try:
                    return fn(*a, **k)
                except exceptions as e:  # noqa: BLE001
                    last = e
            raise last
        return wrapper
    return deco


def flaky(retries: int = 3):
    """pytest-friendly Flaky modifier: rerun up to `retries` times."""
    return try_with_retries(times=tuple([0] + [200] * (retries - 1)))


def time_limited_flaky(seconds: float = 60.0, retries: int = 3):
    """Retry; fail if any attempt exceeds the time limit (reference
    TimeLimitedFlaky)."""
    def deco(fn: Callable):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            last = None
            for _ in range(retries):
                t0 = time.perf_counter()
                try:
                    out = fn(*a, **k)
                    if time.perf_counter() - t0 > seconds:
                        raise TimeoutError(
                            f"{fn.__name__} took {time.perf_counter() - t0:.1f}s "
                            f"> {seconds}s")
                    return out
                except Exception as e:  # noqa: BLE001
                    last = e
            raise last
        return wrapper
    return deco


def linux_only(fn: Callable):
    """Skip outside Linux (reference LinuxOnly)."""
    import pytest
    return pytest.mark.skipif(platform.system() != "Linux",
                              reason="LinuxOnly")(fn)
