"""Expert parallelism — a mixture-of-experts layer sharded over ``expert``.

No counterpart exists in the reference; completes the framework's parallelism
surface (dp/tp/sp/pp/ep).  Token-choice top-1 routing with capacity-free
dense dispatch: the combine is an einsum whose expert axis is sharded over
the mesh's ``expert`` dimension, so GSPMD partitions expert FFNs across
devices and inserts the dispatch/combine collectives (the all-to-all
pattern) from the sharding annotations alone.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from .mesh import AXIS_EXPERT


class MoELayer(nn.Module):
    """Dense-dispatch top-1 MoE FFN: y = Σ_e gate_e(x) · FFN_e(x) with a
    one-hot gate (straight-through top-1)."""

    num_experts: int
    hidden: int
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # x: (tokens, d)
        T, d = x.shape
        E, H = self.num_experts, self.hidden
        gate_logits = nn.Dense(E, dtype=self.dtype, name="gate")(x)   # (T, E)
        probs = nn.softmax(gate_logits, axis=-1)
        top1 = jnp.argmax(probs, axis=-1)                             # (T,)
        dispatch = jax.nn.one_hot(top1, E, dtype=self.dtype)          # (T, E)
        gate_val = jnp.sum(probs * dispatch, axis=-1, keepdims=True)  # (T, 1)

        w_in = self.param("w_in", nn.initializers.lecun_normal(), (E, d, H))
        w_out = self.param("w_out", nn.initializers.lecun_normal(), (E, H, d))
        # expert-parallel einsums: the E axis shards over the `expert` mesh
        # dim (see shard_moe_params); GSPMD turns these into local expert
        # compute + cross-device combine
        h = jnp.einsum("te,td,edh->teh", dispatch, x.astype(self.dtype), w_in)
        h = nn.gelu(h)
        y = jnp.einsum("teh,ehd->td", h, w_out)
        y = y * gate_val

        # load-balancing aux loss (Switch-style): mean prob * mean dispatch
        me = probs.mean(axis=0)
        ce = dispatch.mean(axis=0)
        self.sow("losses", "moe_aux", self.aux_loss_weight * E *
                 jnp.sum(me * ce))
        return y.astype(x.dtype)


def shard_moe_params(params, mesh):
    """device_put expert-stacked leaves (leading dim == num_experts on the
    ``expert`` axis) and replicate the rest."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..observability.compute import device_put as _obs_device_put
    e_size = mesh.shape[AXIS_EXPERT]

    def place(leaf):
        if leaf.ndim >= 1 and leaf.shape[0] % e_size == 0 and leaf.ndim >= 3:
            return _obs_device_put(leaf, NamedSharding(mesh, P(AXIS_EXPERT)),
                                   site="parallel.moe")
        return _obs_device_put(leaf, NamedSharding(mesh, P()),
                               site="parallel.moe")

    return jax.tree.map(place, params)
