from .mesh import (AXIS_DATA, AXIS_MODEL, AXIS_SEQ, AXIS_PIPE, AXIS_EXPERT,
                   make_mesh, data_parallel_mesh, get_active_mesh,
                   set_active_mesh, active_mesh, initialize_distributed)
from .sharding import (named_sharding, replicated, batch_sharded, shard_batch,
                       replicate, pad_to_multiple)
from .collectives import (psum, pmean, pmax, all_gather, ppermute, ring_perm,
                          axis_index, shard_mapped)
from .partition import (match_partition_rules, replace_on_mesh,
                        tree_path_names)

__all__ = [
    "AXIS_DATA", "AXIS_MODEL", "AXIS_SEQ", "AXIS_PIPE", "AXIS_EXPERT",
    "make_mesh", "data_parallel_mesh", "get_active_mesh", "set_active_mesh",
    "active_mesh", "initialize_distributed", "named_sharding", "replicated",
    "batch_sharded", "shard_batch", "replicate", "pad_to_multiple", "psum",
    "pmean", "pmax", "all_gather", "ppermute", "ring_perm", "axis_index",
    "shard_mapped", "match_partition_rules", "replace_on_mesh",
    "tree_path_names",
]
