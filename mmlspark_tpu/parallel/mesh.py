"""Device-mesh bootstrap — the rendezvous replacement.

The reference bootstraps its distributed rings through three bespoke socket
channels (SURVEY.md §2.12): a driver TCP rendezvous collecting ``host:port``
from every task (``LightGBMBase.createDriverNodesThread:392-430``), LightGBM's
C++ socket allreduce ring, and VW's spanning-tree server.  TPU-native, all
three collapse into: form a ``jax.sharding.Mesh`` once (multi-host via
``jax.distributed.initialize`` with the driver as coordinator) and let XLA
collectives ride ICI/DCN.  This module owns mesh formation and the axis-name
conventions used across the framework:

- ``data``  — data parallelism (batch sharding; gradient/histogram psum)
- ``model`` — tensor parallelism (weight sharding)
- ``seq``   — sequence/context parallelism (ring attention)
- ``pipe``  — pipeline parallelism stages
- ``expert``— expert parallelism (MoE)
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"

_ACTIVE_MESH = None


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap: the Spark driver's only remaining distributed
    role (SURVEY.md §2.12) — distribute the coordinator address, then each
    executor (one per TPU host) calls this before any collective."""
    import jax
    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None):
    """Build a Mesh whose axis sizes multiply to the device count.

    ``axes`` maps axis name -> size; a single ``-1`` size is inferred.  With
    no axes, returns a 1-d data-parallel mesh over all devices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if not axes:
        axes = {AXIS_DATA: n}
    axes = dict(axes)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"cannot infer axis: {n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
        axes = dict(zip(axes.keys(), sizes))
    total = int(np.prod(list(axes.values())))
    if total != n:
        raise ValueError(f"mesh axes {axes} require {total} devices, have {n}")
    dev_array = np.asarray(devices).reshape(*axes.values())
    return Mesh(dev_array, tuple(axes.keys()))


def data_parallel_mesh(num_devices: Optional[int] = None):
    import jax
    devices = jax.devices()[: num_devices or None]
    return make_mesh({AXIS_DATA: len(devices)}, devices)


def get_active_mesh():
    """The framework-wide default mesh (set once at executor startup)."""
    global _ACTIVE_MESH
    if _ACTIVE_MESH is None:
        _ACTIVE_MESH = data_parallel_mesh()
    return _ACTIVE_MESH


def set_active_mesh(mesh) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


@contextmanager
def active_mesh(mesh):
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev


def host_device_count_flag(n: int) -> str:
    """XLA flag forcing n virtual CPU devices — the test-time 'cluster in a
    box' (SURVEY.md §4 implications)."""
    return f"--xla_force_host_platform_device_count={n}"
