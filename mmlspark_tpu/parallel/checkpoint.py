"""Training-state checkpoint/resume for the distributed trainer.

Reference checkpointing (SURVEY.md §5.4) covers stage persistence, native
warm starts, and streaming checkpoints; for DNN training the TPU framework
adds proper train-state checkpoints: params + optimizer state + step +
batch_stats.  Two backends:

- ``npz`` (default): NPZ arrays + pickled optimizer state — exact pytree
  fidelity with zero dependencies, fine for single-host states.
- ``orbax``: ``orbax.checkpoint.StandardCheckpointer`` — the TPU-ecosystem
  standard.  Restore takes a TEMPLATE TrainState (e.g. a freshly-built
  ``trainer.init_state``) whose array shardings drive a sharding-aware
  restore: each host reads only its shards, and tuples/namedtuples in the
  optimizer state keep their exact structure (a raw orbax restore without a
  target flattens them to lists, breaking the compiled step's structure
  match).

All save paths publish atomically through ``io.checkpoint.atomic_write``
(ISSUE 10; graft-lint RES003 enforces it): a crash mid-save can no longer
tear the only copy.  :class:`TrainLoopCheckpointer` adds step-numbered
periodic snapshots with keep-last-K retention and torn-newest fallback —
the loop-level layer ``Trainer.train_stream`` rides for auto-resume.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..io.checkpoint import CheckpointManager, atomic_write
from .trainer import TrainState


def _state_tree(state: TrainState):
    return {"params": state.params, "opt_state": state.opt_state,
            "step": state.step, "batch_stats": state.batch_stats or {}}


def save_train_state(state: TrainState, path: str,
                     backend: str = "npz") -> None:
    import jax
    if backend not in ("npz", "orbax"):
        raise ValueError(f"backend must be 'npz' or 'orbax', got {backend!r}")
    if backend == "orbax":
        import orbax.checkpoint as ocp
        target = os.path.join(os.path.abspath(path), "orbax")
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(target, _state_tree(state), force=True)
        _write_marker(path, "orbax")
        return
    # NPZ arrays + pickled optimizer state: exact pytree fidelity.  Every
    # file publishes via atomic_write (temp + os.replace): a crash
    # mid-save leaves the previous copy intact instead of a torn npz that
    # would strand the run it exists to protect.
    from flax import traverse_util
    os.makedirs(path, exist_ok=True)
    tree = jax.device_get({"params": state.params,
                           "batch_stats": state.batch_stats or {},
                           "step": np.asarray(state.step)})
    flat = traverse_util.flatten_dict({"t": tree}, sep="/")
    with atomic_write(os.path.join(path, "state.npz"), "wb") as f:
        np.savez(f, **{k: v for k, v in flat.items() if v is not None})
    from ..utils import pickling
    with atomic_write(os.path.join(path, "opt_state.pkl"), "wb") as f:
        pickling.dump(jax.device_get(state.opt_state), f)
    _write_marker(path, "npz")


def _write_marker(path: str, backend: str) -> None:
    """Record which backend wrote last: mtimes survive neither cp nor rsync
    reliably, so backend selection on load must not depend on them."""
    os.makedirs(path, exist_ok=True)
    with atomic_write(os.path.join(path, "LATEST_BACKEND"), "w") as f:
        f.write(backend)


def load_train_state(path: str, trainer=None,
                     template: Optional[TrainState] = None,
                     backend: Optional[str] = None) -> TrainState:
    """Load a checkpoint; with ``trainer`` given, re-shard onto its mesh.
    Orbax checkpoints additionally need ``template`` (structure + shardings
    to restore into).  ``backend`` forces a backend; otherwise the
    LATEST_BACKEND marker decides, with mtime comparison as a last resort
    for pre-marker checkpoints."""
    import jax
    orbax_dir = os.path.join(os.path.abspath(path), "orbax")
    npz_path = os.path.join(path, "state.npz")
    marker = os.path.join(path, "LATEST_BACKEND")
    if backend is not None:
        if backend not in ("npz", "orbax"):
            raise ValueError(f"backend must be 'npz' or 'orbax', got {backend!r}")
        use_orbax = backend == "orbax"
    elif os.path.exists(orbax_dir) and os.path.exists(npz_path):
        if os.path.exists(marker):
            with open(marker) as f:
                use_orbax = f.read().strip() == "orbax"
        else:
            # both backends wrote here pre-marker: take the newer artifact,
            # never silently shadow a fresher save with a stale one
            use_orbax = os.path.getmtime(orbax_dir) >= os.path.getmtime(npz_path)
    else:
        use_orbax = os.path.exists(orbax_dir)
    if use_orbax:
        if template is None:
            raise ValueError(
                "orbax restore needs template= (a TrainState with the target "
                "structure/shardings, e.g. trainer.init_state(...))")
        import orbax.checkpoint as ocp

        def abstract(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                sharding = getattr(x, "sharding", None)
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=sharding)
            return x

        tpl = jax.tree.map(abstract, _state_tree(template))
        with ocp.StandardCheckpointer() as ckptr:
            tree = ckptr.restore(orbax_dir, tpl)
        state = TrainState(params=tree["params"], opt_state=tree["opt_state"],
                           step=tree["step"],
                           batch_stats=tree.get("batch_stats") or None)
        if trainer is not None:
            state = trainer.shard_state(state)
        return state
    if os.path.exists(npz_path):
        from flax import traverse_util
        with np.load(os.path.join(path, "state.npz"), allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files}
        tree = traverse_util.unflatten_dict(flat, sep="/")["t"]
        from ..utils import pickling
        with open(os.path.join(path, "opt_state.pkl"), "rb") as f:
            opt_state = pickling.load(f)
        state = TrainState(params=tree["params"], opt_state=opt_state,
                           step=tree["step"],
                           batch_stats=tree.get("batch_stats") or None)
    else:
        raise FileNotFoundError(f"no checkpoint at {path}")
    if trainer is not None:
        state = trainer.shard_state(state)
    return state


# ---------------------------------------------------------------------------
# loop-level periodic checkpointing (ISSUE 10) — step-numbered snapshots
# ---------------------------------------------------------------------------

class TrainLoopCheckpointer:
    """Periodic TrainState snapshots for long-running training loops.

    Rides :class:`~mmlspark_tpu.io.checkpoint.CheckpointManager`: each
    snapshot is ONE atomically-published ``state_<step>.npz`` (flattened
    params/batch_stats arrays, the step scalar, and the optimizer pytree
    pickled into a uint8 payload lane), with keep-last-K retention, async
    background writes, shared ``mmlspark_checkpoint_*`` telemetry, and
    torn-newest fallback on load.

    The ONE synchronous cost on the training thread is ``jax.device_get``
    of the state inside :meth:`save` — unavoidable, because the trainer
    donates the state buffers into the next ``train_step`` and a deferred
    fetch would read freed memory.  Serialization and disk I/O then happen
    on the writer thread.
    """

    _OPT_KEY = "__opt_state__"
    _STEP_KEY = "__step__"

    def __init__(self, directory: str, *, keep_last: int = 3,
                 site: str = "parallel.trainer", registry=None,
                 topology: Optional[dict] = None):
        self._mgr = CheckpointManager(directory, site=site,
                                      keep_last=keep_last, prefix="state",
                                      registry=registry)
        self.site = site
        self._registry = registry
        #: the topology stanza recorded into every snapshot's meta
        #: (elastic resume, ISSUE 14): device count / mesh shape — allowed
        #: to differ on restore, surfaced as ``last_topology_delta``
        self.topology = dict(topology) if topology else None
        self.last_topology_delta: Optional[dict] = None

    @property
    def manager(self) -> CheckpointManager:
        return self._mgr

    def save(self, state: TrainState, step: int, *,
             meta: Optional[dict] = None, block: bool = False) -> None:
        import jax
        from flax import traverse_util
        from ..utils import pickling
        host = jax.device_get({"params": state.params,
                               "batch_stats": state.batch_stats or {},
                               "step": np.asarray(state.step)})
        flat = traverse_util.flatten_dict(
            {"t": {"params": host["params"],
                   "batch_stats": host["batch_stats"]}}, sep="/")
        # device_get on the CPU backend returns ZERO-COPY views of the
        # device buffers (ndarray.base is the jax capsule) — and the
        # training loop donates this state into the very next train_step
        # while the background writer is still serializing.  The sync
        # fetch on the training thread must therefore be a sync COPY, or
        # the writer reads freed/overwritten memory (segfault, or worse:
        # a silently torn snapshot that resumes to wrong losses).
        arrays = {k: np.array(v) for k, v in flat.items() if v is not None}
        arrays[self._STEP_KEY] = np.array(host["step"])
        arrays[self._OPT_KEY] = np.frombuffer(
            pickling.dumps(jax.device_get(state.opt_state)), dtype=np.uint8)
        meta = dict(meta or {}, kind="train_state")
        if self.topology is not None:
            meta["topology"] = self.topology
        self._mgr.save(step, arrays, meta, block=block)

    def wait(self) -> None:
        self._mgr.wait()

    def close(self) -> None:
        self._mgr.close()

    def load_latest(self, trainer=None) -> Optional[TrainState]:
        """Newest valid snapshot as a TrainState (re-sharded onto
        ``trainer``'s mesh when given), or None.  A torn newest snapshot
        falls back to the previous one (CheckpointManager contract).

        Elastic resume (ISSUE 14): when this checkpointer carries a
        topology stanza, the snapshot's recorded stanza is diffed against
        it — a change is booked (``mmlspark_reshard_total{driver=
        "parallel.trainer"}`` + ring event) and surfaced as
        ``self.last_topology_delta``; the state then re-places onto the
        trainer's CURRENT mesh through its partition rules, which is what
        makes restoring onto a grown/shrunk fleet a plain restore."""
        got = self._mgr.load_latest(current_topology=self.topology)
        self.last_topology_delta = None
        if got is None:
            return None
        _, arrays, _meta = got
        delta = _meta.get("topology_delta")
        if delta is not None:
            self.last_topology_delta = delta
            if delta["changed"]:
                from ..io.checkpoint import book_reshard
                book_reshard("parallel.trainer", delta,
                             registry=self._registry)
        from flax import traverse_util
        from ..utils import pickling
        flat = {k: v for k, v in arrays.items()
                if k not in (self._OPT_KEY, self._STEP_KEY)}
        tree = traverse_util.unflatten_dict(flat, sep="/").get("t", {})
        opt_state = pickling.loads(arrays[self._OPT_KEY].tobytes())
        state = TrainState(params=tree.get("params", {}),
                           opt_state=opt_state,
                           step=arrays[self._STEP_KEY],
                           batch_stats=tree.get("batch_stats") or None)
        if trainer is not None:
            state = trainer.shard_state(state)
        return state
