"""Training-state checkpoint/resume for the distributed trainer.

Reference checkpointing (SURVEY.md §5.4) covers stage persistence, native
warm starts, and streaming checkpoints; for DNN training the TPU framework
adds proper train-state checkpoints: params + optimizer state + step +
batch_stats, saved via orbax when available (sharding-aware) with an NPZ
fallback.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .trainer import TrainState


def save_train_state(state: TrainState, path: str) -> None:
    # NPZ arrays + pickled optimizer state: exact pytree fidelity (orbax's
    # StandardCheckpointer restores tuples as lists without a target tree,
    # which breaks the compiled step's structure match)
    import jax
    from flax import traverse_util
    os.makedirs(path, exist_ok=True)
    tree = jax.device_get({"params": state.params,
                           "batch_stats": state.batch_stats or {},
                           "step": np.asarray(state.step)})
    flat = traverse_util.flatten_dict({"t": tree}, sep="/")
    np.savez(os.path.join(path, "state.npz"),
             **{k: v for k, v in flat.items() if v is not None})
    from ..utils import pickling
    with open(os.path.join(path, "opt_state.pkl"), "wb") as f:
        pickling.dump(jax.device_get(state.opt_state), f)


def load_train_state(path: str, trainer=None) -> TrainState:
    """Load a checkpoint; with `trainer` given, re-shard onto its mesh."""
    import jax
    state = None
    if os.path.exists(os.path.join(path, "state.npz")):
        from flax import traverse_util
        with np.load(os.path.join(path, "state.npz"), allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files}
        tree = traverse_util.unflatten_dict(flat, sep="/")["t"]
        from ..utils import pickling
        with open(os.path.join(path, "opt_state.pkl"), "rb") as f:
            opt_state = pickling.load(f)
        state = TrainState(params=tree["params"], opt_state=opt_state,
                           step=tree["step"],
                           batch_stats=tree.get("batch_stats") or None)
    else:
        raise FileNotFoundError(f"no checkpoint at {path}")
    if trainer is not None:
        state = trainer.shard_state(state)
    return state
