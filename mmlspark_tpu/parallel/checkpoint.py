"""Training-state checkpoint/resume for the distributed trainer.

Reference checkpointing (SURVEY.md §5.4) covers stage persistence, native
warm starts, and streaming checkpoints; for DNN training the TPU framework
adds proper train-state checkpoints: params + optimizer state + step +
batch_stats.  Two backends:

- ``npz`` (default): NPZ arrays + pickled optimizer state — exact pytree
  fidelity with zero dependencies, fine for single-host states.
- ``orbax``: ``orbax.checkpoint.StandardCheckpointer`` — the TPU-ecosystem
  standard.  Restore takes a TEMPLATE TrainState (e.g. a freshly-built
  ``trainer.init_state``) whose array shardings drive a sharding-aware
  restore: each host reads only its shards, and tuples/namedtuples in the
  optimizer state keep their exact structure (a raw orbax restore without a
  target flattens them to lists, breaking the compiled step's structure
  match).
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .trainer import TrainState


def _state_tree(state: TrainState):
    return {"params": state.params, "opt_state": state.opt_state,
            "step": state.step, "batch_stats": state.batch_stats or {}}


def save_train_state(state: TrainState, path: str,
                     backend: str = "npz") -> None:
    import jax
    if backend not in ("npz", "orbax"):
        raise ValueError(f"backend must be 'npz' or 'orbax', got {backend!r}")
    if backend == "orbax":
        import orbax.checkpoint as ocp
        target = os.path.join(os.path.abspath(path), "orbax")
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(target, _state_tree(state), force=True)
        _write_marker(path, "orbax")
        return
    # NPZ arrays + pickled optimizer state: exact pytree fidelity
    from flax import traverse_util
    os.makedirs(path, exist_ok=True)
    tree = jax.device_get({"params": state.params,
                           "batch_stats": state.batch_stats or {},
                           "step": np.asarray(state.step)})
    flat = traverse_util.flatten_dict({"t": tree}, sep="/")
    np.savez(os.path.join(path, "state.npz"),
             **{k: v for k, v in flat.items() if v is not None})
    from ..utils import pickling
    with open(os.path.join(path, "opt_state.pkl"), "wb") as f:
        pickling.dump(jax.device_get(state.opt_state), f)
    _write_marker(path, "npz")


def _write_marker(path: str, backend: str) -> None:
    """Record which backend wrote last: mtimes survive neither cp nor rsync
    reliably, so backend selection on load must not depend on them."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "LATEST_BACKEND"), "w") as f:
        f.write(backend)


def load_train_state(path: str, trainer=None,
                     template: Optional[TrainState] = None,
                     backend: Optional[str] = None) -> TrainState:
    """Load a checkpoint; with ``trainer`` given, re-shard onto its mesh.
    Orbax checkpoints additionally need ``template`` (structure + shardings
    to restore into).  ``backend`` forces a backend; otherwise the
    LATEST_BACKEND marker decides, with mtime comparison as a last resort
    for pre-marker checkpoints."""
    import jax
    orbax_dir = os.path.join(os.path.abspath(path), "orbax")
    npz_path = os.path.join(path, "state.npz")
    marker = os.path.join(path, "LATEST_BACKEND")
    if backend is not None:
        if backend not in ("npz", "orbax"):
            raise ValueError(f"backend must be 'npz' or 'orbax', got {backend!r}")
        use_orbax = backend == "orbax"
    elif os.path.exists(orbax_dir) and os.path.exists(npz_path):
        if os.path.exists(marker):
            with open(marker) as f:
                use_orbax = f.read().strip() == "orbax"
        else:
            # both backends wrote here pre-marker: take the newer artifact,
            # never silently shadow a fresher save with a stale one
            use_orbax = os.path.getmtime(orbax_dir) >= os.path.getmtime(npz_path)
    else:
        use_orbax = os.path.exists(orbax_dir)
    if use_orbax:
        if template is None:
            raise ValueError(
                "orbax restore needs template= (a TrainState with the target "
                "structure/shardings, e.g. trainer.init_state(...))")
        import orbax.checkpoint as ocp

        def abstract(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                sharding = getattr(x, "sharding", None)
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=sharding)
            return x

        tpl = jax.tree.map(abstract, _state_tree(template))
        with ocp.StandardCheckpointer() as ckptr:
            tree = ckptr.restore(orbax_dir, tpl)
        state = TrainState(params=tree["params"], opt_state=tree["opt_state"],
                           step=tree["step"],
                           batch_stats=tree.get("batch_stats") or None)
        if trainer is not None:
            state = trainer.shard_state(state)
        return state
    if os.path.exists(npz_path):
        from flax import traverse_util
        with np.load(os.path.join(path, "state.npz"), allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files}
        tree = traverse_util.unflatten_dict(flat, sep="/")["t"]
        from ..utils import pickling
        with open(os.path.join(path, "opt_state.pkl"), "rb") as f:
            opt_state = pickling.load(f)
        state = TrainState(params=tree["params"], opt_state=opt_state,
                           step=tree["step"],
                           batch_stats=tree.get("batch_stats") or None)
    else:
        raise FileNotFoundError(f"no checkpoint at {path}")
    if trainer is not None:
        state = trainer.shard_state(state)
    return state
