"""Sharding helpers — put arrays where the mesh wants them.

Thin layer over ``jax.sharding.NamedSharding`` / ``PartitionSpec`` so stages
can say "shard this batch over the data axis" or "replicate these weights"
without repeating boilerplate.  Follows the scaling-book recipe: pick a mesh,
annotate shardings, let XLA insert the collectives.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from .mesh import AXIS_DATA, get_active_mesh


def named_sharding(mesh=None, *spec):
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = mesh or get_active_mesh()
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh=None):
    return named_sharding(mesh)


def batch_sharded(mesh=None, axis: str = AXIS_DATA):
    """Leading dim sharded over the data axis; rest replicated."""
    return named_sharding(mesh, axis)


def shard_batch(x, mesh=None, axis: str = AXIS_DATA):
    """Device_put a host array with its leading dim split over `axis`.
    Pads the batch up to a multiple of the axis size (padding rows are
    repeated last rows; callers mask via the returned valid-count)."""
    from ..observability.compute import device_put
    mesh = mesh or get_active_mesh()
    n_shards = mesh.shape[axis]
    x = np.asarray(x)
    n = x.shape[0]
    rem = (-n) % n_shards
    if rem:
        pad = np.repeat(x[-1:], rem, axis=0)
        x = np.concatenate([x, pad], axis=0)
    return device_put(x, batch_sharded(mesh, axis),
                      site="parallel.shard_batch"), n


def replicate(x, mesh=None):
    from ..observability.compute import device_put
    return device_put(x, replicated(mesh or get_active_mesh()),
                      site="parallel.replicate")


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0,
                    fill: Optional[float] = None):
    """Pad along `axis` to a multiple; returns (padded, original_length)."""
    n = x.shape[axis]
    rem = (-n) % multiple
    if not rem:
        return x, n
    pad_shape = list(x.shape)
    pad_shape[axis] = rem
    if fill is None:
        idx = [slice(None)] * x.ndim
        idx[axis] = slice(n - 1, n)
        pad = np.repeat(x[tuple(idx)], rem, axis=axis)
    else:
        pad = np.full(pad_shape, fill, dtype=x.dtype)
    return np.concatenate([x, pad], axis=axis), n
