"""Sequence-parallel training step — dp x sp over (data, seq) mesh axes.

Long-context training where each device holds a slice of every sequence:
tokens shard over both batch (``data``) and sequence (``seq``); attention is
ring attention over ``seq``; the loss pmean and the gradient psum are the
only other collectives.  This is the capability the reference lacks entirely
(SURVEY.md §5.7) and the task brief makes first-class.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import numpy as np

from .mesh import AXIS_DATA, AXIS_SEQ, get_active_mesh


def make_seq_parallel_train_step(module, learning_rate: float = 1e-3,
                                 mesh=None):
    """SGD train step for a per-token classifier (pool='none',
    attention_mode='ring') with tokens (B, L) sharded (data, seq).

    Returns (init_fn, step_fn):
      init_fn(rng, tokens, positions) -> replicated params
      step_fn(params, tokens, positions, labels) -> (params, loss)
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh or get_active_mesh()
    tok_spec = P(AXIS_DATA, AXIS_SEQ)
    rep = P()

    def local_step(params, tokens, positions, labels):
        def loss_fn(p):
            logits = module.apply({"params": p}, tokens, positions=positions)
            ll = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            return jax.lax.pmean(ll.mean(), (AXIS_DATA, AXIS_SEQ))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # each shard holds its partial gradient of the pmean'd loss
        grads = jax.tree.map(lambda g: jax.lax.psum(g, (AXIS_DATA, AXIS_SEQ)),
                             grads)
        params = jax.tree.map(lambda w, g: w - learning_rate * g, params, grads)
        return params, loss

    from ..observability.compute import device_put as _obs_device_put
    from ..observability.compute import instrumented_jit
    step_fn = instrumented_jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(rep, tok_spec, tok_spec, tok_spec),
        out_specs=(rep, rep), check_vma=False),
        name="parallel.seq_step")

    def init_fn(rng, tokens, positions):
        variables = module.init(rng, tokens[:1, : tokens.shape[1] // mesh.shape[AXIS_SEQ]],
                                positions=positions[:1, : tokens.shape[1] // mesh.shape[AXIS_SEQ]])
        params = variables["params"]
        return _obs_device_put(params, NamedSharding(mesh, rep),
                               site="parallel.seq_init")

    return init_fn, step_fn


def global_positions(batch: int, seq_len: int) -> np.ndarray:
    """(B, L) global position ids to shard alongside tokens."""
    return np.broadcast_to(np.arange(seq_len, dtype=np.int32)[None, :],
                           (batch, seq_len)).copy()
