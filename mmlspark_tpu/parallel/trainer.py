"""Distributed training step builder — dp×tp sharded train steps via jit.

The reference's training parallelism is data-parallel tasks + native allreduce
(SURVEY.md §2.11).  TPU-native we go further: a 2-d ``data × model`` mesh
where the batch is sharded over ``data`` and large Dense/Conv kernels are
sharded over ``model`` (tensor parallelism).  XLA inserts the gradient psums
and weight all-gathers from the sharding annotations alone (scaling-book
recipe) — there is no hand-written allreduce anywhere.

``shard_params_by_rule`` implements the annotation policy; ``Trainer`` builds
a jitted ``train_step`` with donated state so optimizer updates happen
in-place in HBM.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .mesh import AXIS_DATA, AXIS_MODEL, get_active_mesh


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any
    batch_stats: Any = None


def _register_trainstate():
    import jax
    jax.tree_util.register_dataclass(
        TrainState, data_fields=["params", "opt_state", "step", "batch_stats"],
        meta_fields=[])


_register_trainstate()


def param_spec(leaf, model_axis_size: int, min_size: int = 2 ** 16):
    """Sharding rule: shard the last axis of big >=2-d kernels over `model`;
    replicate everything else.  Keeps small params replicated (cheap) and the
    MXU-heavy matmuls tensor-parallel."""
    from jax.sharding import PartitionSpec as P
    shape = getattr(leaf, "shape", ())
    if len(shape) >= 2 and np.prod(shape) >= min_size and shape[-1] % model_axis_size == 0 \
            and model_axis_size > 1:
        return P(*([None] * (len(shape) - 1) + [AXIS_MODEL]))
    return P()


def shard_params_by_rule(params, mesh, min_size: int = 2 ** 16):
    import jax
    from jax.sharding import NamedSharding
    model_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(AXIS_MODEL, 1)
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, param_spec(leaf, model_size, min_size)), params)


class Trainer:
    """Builds sharded, jitted train/eval steps for a flax module.

    loss_fn(logits, batch) -> scalar; the module is applied to
    ``batch["x"]``.  BatchNorm modules (mutable batch_stats) are supported.
    """

    #: default on-device time sampling period: the out-of-core streaming
    #: loop tunes tile sizes against transfer/compute overlap numbers, so
    #: the device series must exist by default — one forced sync per 32
    #: steps costs ~3% of the pipeline overlap, and 0 stays available to
    #: switch it off entirely
    DEVICE_TIME_EVERY_DEFAULT = 32

    def __init__(self, module, optimizer, loss_fn: Callable,
                 mesh=None, has_batch_stats: bool = False,
                 apply_kwargs: Optional[Dict[str, Any]] = None,
                 min_shard_size: int = 2 ** 16,
                 device_time_every: Optional[int] = None):
        self.module = module
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_active_mesh()
        self.has_batch_stats = has_batch_stats
        self.apply_kwargs = dict(apply_kwargs or {})
        self.min_shard_size = min_shard_size
        # every Nth step additionally measures on-device time by a
        # block_until_ready after dispatch (0 = off: a forced sync breaks
        # the async pipeline).  None resolves to the sampled default — the
        # PR 6 follow-up: overlap tuning needs the device series without
        # every caller remembering to opt in.
        if device_time_every is None:
            device_time_every = self.DEVICE_TIME_EVERY_DEFAULT
        self.device_time_every = max(0, int(device_time_every))
        self._step_count = 0
        self._train_step = None
        self._state_shardings = None
        from ..observability import get_registry
        from ..observability.tracing import current_trace_id
        reg = get_registry()
        self._m_step = reg.histogram(
            "mmlspark_parallel_train_step_seconds",
            "train_step dispatch+wait time on the host (async under jit: "
            "the device may still be running when the call returns)")
        # compute-plane breakdown (labels: trace = first-call lower+compile;
        # dispatch = host time to enqueue the program; device = extra
        # block_until_ready wait on sampled steps)
        self._m_phase = reg.histogram(
            "mmlspark_parallel_train_step_phase_seconds",
            "train_step breakdown: trace (compile), dispatch (host enqueue), "
            "device (sampled block_until_ready wait)", labels=("phase",))
        # bound once: train_step runs per batch, no per-call import lookup
        self._current_trace_id = current_trace_id

    # ------------------------------------------------------------------ init
    def init_state(self, rng, example_batch) -> TrainState:
        import jax
        import jax.numpy as jnp
        variables = self.module.init(rng, example_batch["x"], **self.apply_kwargs)
        params = variables["params"]
        batch_stats = variables.get("batch_stats") if self.has_batch_stats else None
        opt_state = self.optimizer.init(params)
        state = TrainState(params=params, opt_state=opt_state,
                           step=jnp.zeros((), jnp.int32), batch_stats=batch_stats)
        return self.shard_state(state)

    def partition_rules(self):
        """Ordered ``(regex, PartitionSpec-or-callable)`` placement table
        for a TrainState tree (``parallel.partition`` semantics): params
        ride the size-aware kernel rule (big >=2-d kernels shard their
        last axis over ``model``), everything else replicates.  One table
        instead of per-field ``tree.map`` glue — the same rules place a
        fresh ``init_state`` and a checkpoint restored onto a DIFFERENT
        mesh (elastic resume, ISSUE 14)."""
        from jax.sharding import PartitionSpec as P
        model_size = dict(zip(self.mesh.axis_names,
                              self.mesh.devices.shape)).get(AXIS_MODEL, 1)
        min_size = self.min_shard_size

        def kernel_rule(name, leaf):
            return param_spec(leaf, model_size, min_size)

        return ((r"^params(/|$)", kernel_rule),
                (r"^(opt_state|step|batch_stats)(/|$)", P()))

    def shard_state(self, state: TrainState) -> TrainState:
        import jax
        from jax.sharding import NamedSharding
        from .partition import match_partition_rules, replace_on_mesh
        mesh = self.mesh
        tree = {"params": state.params, "opt_state": state.opt_state,
                "step": state.step, "batch_stats": state.batch_stats or {}}
        rules = self.partition_rules()
        specs = match_partition_rules(rules, tree)
        sh = jax.tree.map(lambda spec: NamedSharding(mesh, spec), specs)
        self._state_shardings = TrainState(
            params=sh["params"], opt_state=sh["opt_state"], step=sh["step"],
            batch_stats=None if state.batch_stats is None
            else sh["batch_stats"])
        # instrumented placement: mmlspark_device_transfer_bytes_total books
        # the host->device feed per site (the out-of-core work needs this
        # visible before it lands).  replace_on_mesh also re-places device
        # arrays sharded over a PREVIOUS mesh, so a restored checkpoint
        # and a fresh init take the same path; the matched specs are
        # passed through so the tree is walked once.
        placed = replace_on_mesh(tree, rules, mesh,
                                 site="parallel.trainer.shard_state",
                                 specs=specs)
        return TrainState(
            params=placed["params"], opt_state=placed["opt_state"],
            step=placed["step"],
            batch_stats=None if state.batch_stats is None
            else placed["batch_stats"])

    # ------------------------------------------------------------------ steps
    def _build_train_step(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self.mesh
        batch_sh = NamedSharding(mesh, P(AXIS_DATA))
        rep = NamedSharding(mesh, P())
        module, loss_fn, opt = self.module, self.loss_fn, self.optimizer
        has_bs, kw = self.has_batch_stats, self.apply_kwargs

        def step_fn(state: TrainState, batch):
            def loss(params):
                variables = {"params": params}
                if has_bs:
                    variables["batch_stats"] = state.batch_stats
                    out, updates = module.apply(variables, batch["x"], train=True,
                                                mutable=["batch_stats"], **kw)
                    return loss_fn(out, batch), updates["batch_stats"]
                out = module.apply(variables, batch["x"], train=True, **kw) \
                    if _accepts_train(module) else module.apply(variables, batch["x"], **kw)
                return loss_fn(out, batch), None

            (l, new_bs), grads = jax.value_and_grad(loss, has_aux=True)(state.params)
            updates, new_opt = opt.update(grads, state.opt_state, state.params)
            import optax
            new_params = optax.apply_updates(state.params, updates)
            return TrainState(params=new_params, opt_state=new_opt,
                              step=state.step + 1,
                              batch_stats=new_bs if has_bs else None), l

        sh = self._state_shardings
        state_in = TrainState(params=sh.params, opt_state=sh.opt_state,
                              step=sh.step, batch_stats=sh.batch_stats)
        from ..observability.compute import instrumented_jit
        return instrumented_jit(
            step_fn,
            in_shardings=(state_in, {"x": batch_sh, "y": batch_sh}),
            out_shardings=(state_in, rep),
            donate_argnums=(0,), name="parallel.train_step")

    def train_step(self, state: TrainState, batch) -> Tuple[TrainState, Any]:
        if self._train_step is None:
            if self._state_shardings is None:
                raise RuntimeError("call init_state/shard_state before train_step")
            self._train_step = self._build_train_step()
        fn = self._train_step
        trace_id = self._current_trace_id()
        compiles_before = fn.compiles
        t0 = time.perf_counter()
        out = fn(state, batch)
        dispatch_s = time.perf_counter() - t0
        # exemplar when a span is active (e.g. a traced fit loop): a slow
        # step's histogram bucket keeps the trace id of the run that hit it
        self._m_step.observe(dispatch_s, trace_id)
        # compute-plane breakdown: a first-signature call spent most of its
        # host time in lower+compile — book it as the trace phase and keep
        # dispatch comparable across steps
        if fn.compiles != compiles_before:
            self._m_phase.observe(fn.last_compile_s, trace_id, phase="trace")
            dispatch_s = max(0.0, dispatch_s - fn.last_compile_s)
        self._m_phase.observe(dispatch_s, trace_id, phase="dispatch")
        self._step_count += 1
        if self.device_time_every and \
                self._step_count % self.device_time_every == 0:
            # sampled only: the forced sync ends async pipelining for this
            # step, so the device-time series costs 1/N of the overlap
            t1 = time.perf_counter()
            import jax
            jax.block_until_ready(out)
            device_s = time.perf_counter() - t1
            self._m_phase.observe(device_s, trace_id, phase="device")
            from ..observability.tracing import Span, export_span
            span = Span("compute.train_step", trace_id=trace_id,
                        start_s=t0,
                        attributes={"dispatch_s": round(dispatch_s, 6),
                                    "device_s": round(device_s, 6),
                                    "step": self._step_count})
            span.finish(time.perf_counter())
            export_span(span)
        return out

    def train_stream(self, state: TrainState, batches,
                     site: str = "parallel.trainer.stream",
                     checkpoint_dir: Optional[str] = None,
                     checkpoint_every: int = 0,
                     checkpoint_keep_last: int = 3,
                     resume: str = "auto",
                     callbacks: Optional[list] = None,
                     total_steps: Optional[int] = None,
                     monitor_port: Optional[int] = None,
                     monitor_stall_timeout_s: Optional[float] = None):
        """Out-of-core training loop: iterate host batches through a
        double-buffered prefetcher — batch ``k+1`` is ``device_put`` (row
        sharded over the mesh's data axis, through the instrumented
        transfer counter) on a background thread while ``train_step`` runs
        on batch ``k``.  ``batches`` is any iterable of host pytrees (e.g.
        ``{"x": ..., "y": ...}``); the stream's overlap efficiency books
        into ``mmlspark_prefetch_wait_seconds`` /
        ``mmlspark_tile_compute_seconds`` under ``site``.

        Fault tolerance (ISSUE 10): with ``checkpoint_dir`` set, the state
        snapshots atomically every ``checkpoint_every`` steps (plus once at
        the end) through :class:`parallel.checkpoint.TrainLoopCheckpointer`,
        and ``resume="auto"`` restores the newest valid snapshot and
        fast-forwards ``batches`` past the steps it already holds — so the
        SAME batch iterable must be passed again on resume (``resume=
        "never"`` disables restoring; ``resume="must"`` additionally
        RAISES when no usable snapshot exists, the restart-script
        contract).  Elastic resume (ISSUE 14): the snapshot records a
        topology stanza, and restoring onto a trainer with a DIFFERENT
        device count/mesh re-places the state through the partition
        rules (replicated params/opt_state re-placed, batch re-sharded
        over the new ``data`` axis) — the change is booked
        (``mmlspark_reshard_total``) and surfaced as
        ``stats["resharded"]``.  SIGTERM/SIGINT during the loop
        requests one final checkpoint at the next step boundary and
        returns cleanly with ``stats["preempted"]`` set — a preempted
        worker resumes instead of restarting.

        Live monitoring (ISSUE 19): ``callbacks`` are invoked after every
        step as ``cb(step_index, None)`` — the evals slot is always
        ``None`` here because fetching a per-step loss would force the
        float() sync this loop exists to avoid.  ``monitor_port`` (0 =
        ephemeral) starts a :class:`~mmlspark_tpu.observability.trainwatch.
        MonitorServer` named after ``site`` serving ``/progress`` +
        ``/metrics``; the stall watchdog heartbeats per step, with rows
        inferred from the batch's leading leaf.  ``total_steps`` (the
        batch count, when the caller knows it) enables the progress ratio
        and ETA; ``monitor_stall_timeout_s`` pins the stall timeout
        instead of the EWMA-scaled default.

        Returns ``(state, losses, stats)`` — ``stats`` is the prefetcher's
        overlap summary plus ``steps`` / ``resumed_from_step`` /
        ``preempted`` / ``checkpoint_saves``.
        """
        import contextlib
        import itertools
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..io.chunked import TilePrefetcher
        from ..observability.compute import device_put as _obs_device_put
        from ..utils.resilience import PreemptionToken, preemption_scope
        batch_sh = NamedSharding(self.mesh, P(AXIS_DATA))

        from ..io.checkpoint import (check_resume_arg,
                                     resume_required_error, topology_stanza)
        check_resume_arg(resume, checkpoint_dir=checkpoint_dir)
        ckpt = None
        skip = 0
        step0 = None
        resharded = False
        if checkpoint_dir:
            from .checkpoint import TrainLoopCheckpointer
            mesh_axes = dict(zip(self.mesh.axis_names,
                                 self.mesh.devices.shape))
            ckpt = TrainLoopCheckpointer(
                checkpoint_dir, keep_last=checkpoint_keep_last, site=site,
                topology=topology_stanza(
                    mesh=self.mesh,
                    shard_count=int(mesh_axes.get(AXIS_DATA, 1))))
            step0 = int(jax.device_get(state.step))
            if resume in ("auto", "must"):
                # load_latest(trainer=self) re-places the restored state
                # onto THIS trainer's mesh through the partition rules —
                # the device count/mesh the snapshot was taken on may
                # differ (elastic resume); the topology delta is booked
                # by the checkpointer and surfaced in stats
                restored = ckpt.load_latest(trainer=self)
                if restored is None and resume == "must":
                    raise resume_required_error(checkpoint_dir)
                if restored is not None:
                    skip = max(0, int(jax.device_get(restored.step)) - step0)
                    state = restored
                    delta = ckpt.last_topology_delta
                    resharded = bool(delta and delta["changed"])

        def _load(batch):
            return jax.tree.map(
                lambda leaf: _obs_device_put(leaf, batch_sh, site=site),
                batch)

        items = itertools.islice(iter(batches), skip, None) if skip \
            else batches
        prefetcher = TilePrefetcher(items, _load, site=site)
        losses = []
        steps_done = skip
        preempted = False
        # live monitor (ISSUE 19): heartbeat per train step — a wedged
        # device program or a hung batch source stops the ticks and trips
        # the stall watchdog into a train_stall flight dump
        from ..observability.tracing import ambient_phase
        watch = wsrv = None
        if monitor_port is not None or monitor_stall_timeout_s is not None:
            from ..observability.trainwatch import start_training_monitor
            watch, wsrv = start_training_monitor(
                site, total_steps=total_steps, monitor_port=monitor_port,
                stall_timeout_s=monitor_stall_timeout_s,
                driver="parallel.trainer")
            watch.set_phase("parallel.train_step")
            watch.set_prefetch_fn(prefetcher.snapshot)
        scope = preemption_scope() if ckpt is not None \
            else contextlib.nullcontext(PreemptionToken())
        with contextlib.ExitStack() as stack:
            if wsrv is not None:
                stack.callback(wsrv.stop)
            if watch is not None:
                stack.callback(watch.close)
            token = stack.enter_context(scope)
            if watch is not None:
                watch.set_preemption_token(token)
            for batch in prefetcher:
                with ambient_phase("parallel.train_step"):
                    state, loss = self.train_step(state, batch)
                losses.append(loss)
                steps_done += 1
                if callbacks:
                    for cb in callbacks:
                        cb(steps_done - 1, None)
                if watch is not None:
                    try:
                        rows = int(jax.tree.leaves(batch)[0].shape[0])
                    except Exception:  # noqa: BLE001 — shapeless pytree
                        rows = 0
                    watch.tick(step=steps_done, rows=rows)
                if ckpt is not None and token.requested:
                    # preemption: final snapshot at this step boundary,
                    # then a clean return the caller can resume from
                    ckpt.save(state, step0 + steps_done, block=True)
                    preempted = True
                    break
                if ckpt is not None and checkpoint_every > 0 \
                        and steps_done % checkpoint_every == 0:
                    ckpt.save(state, step0 + steps_done)
        # losses fetched AFTER the loop: per-step float() syncs would
        # serialize the very pipeline the prefetcher exists to overlap
        losses = [float(l) for l in losses]
        stats = prefetcher.overlap_stats()
        stats.update(steps=float(steps_done), resumed_from_step=float(skip),
                     preempted=float(preempted), resharded=float(resharded))
        if ckpt is not None:
            if not preempted and (steps_done > skip or skip == 0):
                # terminal snapshot: resume of a finished stream restores
                # the final state instead of re-training the tail (a
                # restore that ran zero steps skips the redundant re-save)
                ckpt.save(state, step0 + steps_done, block=True)
            stats["checkpoint_saves"] = float(ckpt.manager.saves_ok)
            ckpt.close()
        return state, losses, stats


def _accepts_train(module) -> bool:
    import inspect
    try:
        return "train" in inspect.signature(module.__call__).parameters
    except (TypeError, ValueError):
        return False


def softmax_cross_entropy(logits, batch):
    import jax.numpy as jnp
    import optax
    labels = batch["y"]
    if labels.ndim == logits.ndim:  # one-hot
        return optax.softmax_cross_entropy(logits, labels).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
