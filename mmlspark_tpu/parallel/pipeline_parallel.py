"""Pipeline parallelism — GPipe-style microbatch pipelining over the ``pipe`` axis.

No counterpart exists in the reference (SURVEY.md §2.11: model parallelism is
absent); this completes the framework's parallelism surface alongside dp/tp
(`trainer.py`) and sp (`seq_parallel.py`).

Design: stage s of a depth-S sequential model lives on pipe-rank s (its
params are the s-th slice of a leading-axis-stacked pytree sharded over
``pipe``).  A `lax.scan` over M + S - 1 ticks rotates activations rightward
with ``ppermute`` each tick while stage 0 injects microbatches — the classic
GPipe schedule including its bubble.  The whole schedule is differentiable
(scan + ppermute transpose), so one `value_and_grad` yields per-stage
gradients that stay local to each device.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import numpy as np

from .mesh import AXIS_PIPE, get_active_mesh


def make_pipeline_train_step(stage_apply: Callable, num_stages: int,
                             loss_fn: Callable, learning_rate: float = 1e-2,
                             mesh=None):
    """Build (init_fn, step_fn, forward_fn) for a pipelined sequential model.

    stage_apply(stage_params, x) -> x' : one stage's computation; every stage
    must preserve the activation shape (uniform-width pipeline).
    loss_fn(outputs (M, mb, d), y (M, mb, ...)) -> scalar, evaluated on the
    final stage's collected outputs.
    Params are a pytree whose leaves have leading dim ``num_stages``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh or get_active_mesh()
    S = num_stages
    if mesh.shape[AXIS_PIPE] != S:
        raise ValueError(f"mesh pipe axis {mesh.shape[AXIS_PIPE]} != stages {S}")

    def local_forward(params_stage, x_mb):
        """Runs inside shard_map; params_stage leaves have leading dim 1."""
        params_local = jax.tree.map(lambda a: a[0], params_stage)
        idx = jax.lax.axis_index(AXIS_PIPE)
        M = x_mb.shape[0]
        T = M + S - 1
        perm = [(i, i + 1) for i in range(S - 1)]       # rightward shift
        act0 = jnp.zeros_like(x_mb[0])

        def tick(act, t):
            act_in = jax.lax.ppermute(act, AXIS_PIPE, perm)
            mb = x_mb[jnp.clip(t, 0, M - 1)]
            act_in = jnp.where(idx == 0, mb, act_in)
            act_out = stage_apply(params_local, act_in)
            return act_out, act_out

        _, outs = jax.lax.scan(tick, act0, jnp.arange(T))
        # on the last stage, outs[m + S - 1] is microbatch m's result
        return outs[S - 1:]                              # (M, mb, d)

    def local_collect(params_stage, x_mb):
        """Replicated final outputs (mask + psum selects the last stage)."""
        outs = local_forward(params_stage, x_mb)
        idx = jax.lax.axis_index(AXIS_PIPE)
        return jax.lax.psum(jnp.where(idx == S - 1, outs, 0.0), AXIS_PIPE)

    def local_loss(params_stage, x_mb, y_mb):
        outs = local_forward(params_stage, x_mb)
        idx = jax.lax.axis_index(AXIS_PIPE)
        l_local = loss_fn(outs, y_mb)
        # only the last stage's outputs are meaningful
        return jax.lax.psum(jnp.where(idx == S - 1, l_local, 0.0), AXIS_PIPE)

    def local_step(params_stage, x_mb, y_mb):
        loss, grads = jax.value_and_grad(local_loss)(params_stage, x_mb, y_mb)
        new_params = jax.tree.map(lambda w, g: w - learning_rate * g,
                                  params_stage, grads)
        return new_params, loss

    p_spec = P(AXIS_PIPE)
    rep = P()
    from ..observability.compute import device_put as _obs_device_put
    from ..observability.compute import instrumented_jit
    step_fn = instrumented_jit(jax.shard_map(
        local_step, mesh=mesh, in_specs=(p_spec, rep, rep),
        out_specs=(p_spec, rep), check_vma=False),
        name="parallel.pipeline_step")
    forward_fn = instrumented_jit(jax.shard_map(
        local_collect, mesh=mesh, in_specs=(p_spec, rep),
        out_specs=rep, check_vma=False),
        name="parallel.pipeline_forward")

    def init_fn(params_stacked):
        sh = NamedSharding(mesh, p_spec)
        return jax.tree.map(
            lambda a: _obs_device_put(np.asarray(a), sh,
                                      site="parallel.pipeline_init"),
            params_stacked)

    return init_fn, step_fn, forward_fn


def microbatch(x: np.ndarray, num_microbatches: int) -> np.ndarray:
    """(batch, ...) -> (M, batch/M, ...)."""
    n = x.shape[0]
    if n % num_microbatches:
        raise ValueError(f"batch {n} not divisible by {num_microbatches} microbatches")
    return x.reshape(num_microbatches, n // num_microbatches, *x.shape[1:])
