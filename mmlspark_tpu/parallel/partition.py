"""Declarative partition rules — regex name-pattern -> PartitionSpec.

The minimal slice of ROADMAP's partition-rule engine (SNIPPETS [2]
pattern, fmengine/EasyLM lineage): instead of every module hand-writing
``jax.tree.map`` sharding glue, a model family declares ONE ordered rule
table — ``(regex, PartitionSpec-or-callable)`` pairs matched against each
leaf's ``/``-joined tree path — and placement becomes data.  Introduced
for elastic resume (ISSUE 14): a checkpoint restored onto a *different*
mesh re-places every leaf through :func:`replace_on_mesh`, so growing or
shrinking the fleet is a rule lookup, not bespoke re-sharding code.  The
other ``parallel/`` modules adopt the same table shape as they migrate.

Rule semantics (first match wins, SNIPPETS [2]):

- scalars (0-d or single-element leaves) are never partitioned: ``P()``
  before any rule is consulted;
- a rule value may be a ``PartitionSpec`` (declarative) or a callable
  ``(name, leaf) -> PartitionSpec`` for shape-dependent policies (the
  trainer's "shard big kernels over ``model``" rule);
- no match raises: silent replication of a tensor the table meant to
  shard is exactly the placement bug declarative rules exist to prevent.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Sequence, Tuple, Union

import numpy as np

__all__ = ["tree_path_names", "match_partition_rules", "replace_on_mesh"]

RuleValue = Union[Any, Callable[[str, Any], Any]]
Rules = Sequence[Tuple[str, RuleValue]]


def _path_name(path) -> str:
    """``/``-joined human name of one tree path: dict keys, sequence
    indices, and dataclass/namedtuple field names all render as path
    segments (``params/Dense_0/kernel``, ``opt_state/0/mu/...``)."""
    parts = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


def tree_path_names(tree) -> Any:
    """Same-structure pytree of each leaf's ``/``-joined path name."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [_path_name(path) for path, _ in flat])


def match_partition_rules(rules: Rules, tree) -> Any:
    """Pytree of ``PartitionSpec`` for ``tree`` under ordered ``rules``.

    Each leaf's ``/``-joined path name is ``re.search``-ed against the
    rule patterns in order; the first hit's spec applies (callable specs
    are invoked with ``(name, leaf)``).  Scalar leaves short-circuit to
    ``P()``; an unmatched non-scalar leaf raises ``ValueError`` naming
    the leaf, so a grown model surface cannot silently fall through the
    table."""
    import jax
    from jax.sharding import PartitionSpec as P

    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path, leaf):
        name = _path_name(path)
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec(name, leaf) if callable(spec) else spec
        raise ValueError(
            f"no partition rule matched leaf {name!r} "
            f"(shape {tuple(shape)}) — add a pattern (a final ('.*', P()) "
            "catch-all makes replication explicit)")

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(path, leaf) for path, leaf in flat])


def replace_on_mesh(tree, rules: Rules, mesh, *,
                    site: str = "parallel.replace_on_mesh",
                    specs: Any = None) -> Any:
    """Re-place every leaf of ``tree`` onto ``mesh`` under ``rules``.

    The elastic-resume primitive: leaves may be host arrays (a restored
    checkpoint) or device arrays sharded over a PREVIOUS mesh — each is
    ``device_put`` with ``NamedSharding(mesh, spec)`` through the
    instrumented transfer counter (``site``), so state restored from a
    snapshot lands on the new topology exactly where the rule table says,
    and the re-placement traffic is visible per site.  A caller that
    already matched the rules (to build jit in_shardings, say) passes
    ``specs`` so the tree is walked once."""
    import jax
    from jax.sharding import NamedSharding

    from ..observability.compute import device_put as _obs_device_put
    if specs is None:
        specs = match_partition_rules(rules, tree)
    return jax.tree.map(
        lambda leaf, spec: _obs_device_put(
            leaf, NamedSharding(mesh, spec), site=site),
        tree, specs)
