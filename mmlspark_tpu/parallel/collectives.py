"""XLA collectives — the allreduce backend.

Replaces the reference's three native comm channels (SURVEY.md §2.12):
LightGBM's TCP-ring ``LGBM_NetworkInit`` allreduce, VW's spanning-tree
allreduce, and the driver rendezvous.  Inside ``shard_map`` these lower to
ICI/DCN collectives; helpers below also provide host-level one-shot reductions
for driver-side aggregation.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

from .mesh import AXIS_DATA, get_active_mesh


def psum(x, axis: str = AXIS_DATA):
    import jax
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: str = AXIS_DATA):
    import jax
    return jax.lax.pmean(x, axis_name=axis)


def pmax(x, axis: str = AXIS_DATA):
    import jax
    return jax.lax.pmax(x, axis_name=axis)


def all_gather(x, axis: str = AXIS_DATA, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def ppermute(x, perm, axis: str = AXIS_DATA):
    import jax
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def ring_perm(n: int, shift: int = 1):
    """Neighbour permutation for ring pipelines (ring attention etc.)."""
    return [(i, (i + shift) % n) for i in range(n)]


def axis_index(axis: str = AXIS_DATA):
    import jax
    return jax.lax.axis_index(axis)


def shard_mapped(fn: Callable, mesh=None, in_specs=None, out_specs=None,
                 check_vma: bool = False):
    """Wrap fn with shard_map on the active mesh (SPMD entry point)."""
    import jax
    from jax.sharding import PartitionSpec as P
    mesh = mesh or get_active_mesh()
    in_specs = in_specs if in_specs is not None else P(AXIS_DATA)
    out_specs = out_specs if out_specs is not None else P()
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=check_vma)
