"""XLA collectives — the allreduce backend.

Replaces the reference's three native comm channels (SURVEY.md §2.12):
LightGBM's TCP-ring ``LGBM_NetworkInit`` allreduce, VW's spanning-tree
allreduce, and the driver rendezvous.  Inside ``shard_map`` these lower to
ICI/DCN collectives; helpers below also provide host-level one-shot reductions
for driver-side aggregation.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

from .mesh import AXIS_DATA, get_active_mesh


def psum(x, axis: str = AXIS_DATA):
    import jax
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: str = AXIS_DATA):
    import jax
    return jax.lax.pmean(x, axis_name=axis)


def pmax(x, axis: str = AXIS_DATA):
    import jax
    return jax.lax.pmax(x, axis_name=axis)


def histogram_psum(hist_i32, axis: str = AXIS_DATA, row_bound: int = None,
                   quant_bins: int = None, num_tiles: int = 1):
    """Allreduce for quantized GBDT histograms — ``(..., 3)`` int32
    ``[sum_qg, sum_qh, count]`` tensors (``ops.histogram`` quantized
    builders).

    When the STATIC global row bound keeps both integer lanes under 14 bits
    (``row_bound * num_tiles * max(quant level) < 2**14`` — signed 16/16
    lanes with carry margin), the grad and hess sums pack into ONE int32
    channel for the transfer: the allreduce moves 2 channels instead of 3
    f32/int32 ones — a third off the per-level ICI volume on top of the
    exactness the integer psum already buys (f32 psums of large histograms
    are reduction-order dependent; int32 sums are not).  Above the bound
    the tensor psums as-is, still exact.

    ``row_bound`` is a trace-time contract like ``max_rows`` in
    ``ops.histogram``: callers pass the TOTAL row count across shards (the
    padded global n), never a guess.  ``num_tiles`` extends the contract to
    the out-of-core pipeline: a shard that ACCUMULATES per-tile int32
    partials before (or after) the allreduce holds cells bounded by
    ``row_bound * num_tiles`` — the global row bound is the sum over
    shards AND tiles, and both statics are baked into the caller's jit
    cache key exactly like ``row_bound`` alone was.
    """
    import jax
    import jax.numpy as jnp
    if (hist_i32.dtype != jnp.int32 or row_bound is None
            or quant_bins is None):
        return jax.lax.psum(hist_i32, axis_name=axis)
    qcap = max(1, quant_bins - 1)              # worst lane magnitude
    if int(row_bound) * max(1, int(num_tiles)) * qcap >= (1 << 14):
        return jax.lax.psum(hist_i32, axis_name=axis)
    packed = hist_i32[..., 0] * 65536 + hist_i32[..., 1]
    two = jax.lax.psum(
        jnp.stack([packed, hist_i32[..., 2]], axis=-1), axis_name=axis)
    qh = two[..., 0] % 65536                   # hess lane is non-negative,
    qg = (two[..., 0] - qh) // 65536           # so floor mod/div decode
    return jnp.stack([qg, qh, two[..., 1]], axis=-1)


def all_gather(x, axis: str = AXIS_DATA, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def ppermute(x, perm, axis: str = AXIS_DATA):
    import jax
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def ring_perm(n: int, shift: int = 1):
    """Neighbour permutation for ring pipelines (ring attention etc.)."""
    return [(i, (i + shift) % n) for i in range(n)]


def axis_index(axis: str = AXIS_DATA):
    import jax
    return jax.lax.axis_index(axis)


def shard_mapped(fn: Callable, mesh=None, in_specs=None, out_specs=None,
                 check_vma: bool = False):
    """Wrap fn with shard_map on the active mesh (SPMD entry point)."""
    import jax
    from jax.sharding import PartitionSpec as P
    mesh = mesh or get_active_mesh()
    in_specs = in_specs if in_specs is not None else P(AXIS_DATA)
    out_specs = out_specs if out_specs is not None else P()
    # raw-jit: bare SPMD building block — callers jit (and instrument) the
    # wrapped result; wrapping here would double-jit every composition
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=check_vma)
